"""Capacity overflow study: measure the minimal non-overflowing
``cap_factor`` per (algorithm, p, n, distribution).

The reference over-allocates every rank's working buffer to the full
``n`` (``Parallel-Sorting/src/psort.cc:385``) — overflow is impossible
and so is the question. icikit's capacity-padded exchanges make the
trade explicit: a factor too small triggers a retry-recompile, a factor
too large wastes HBM. The shipped defaults (sample 4.0, quicksort 2.0)
must therefore be *measured* over the envelope the sorts actually run
at — this module produces that record (``capacity_study.json``).

For each configuration the study builds the real per-shard program at
``cap = factor · n_loc / p`` (sample family) or ``factor · n_loc``
(quicksort) and reads the program's own overflow flag — the exact
signal the retry path keys on, not a reimplementation of the
bucketing.

CLI (simulated mesh; capacities are count properties, not timings)::

    python -m icikit.bench.capacity --ns 20,22,24 --ps 4,8 \
        --out capacity_study.json
"""

from __future__ import annotations

import argparse
import json
import sys


def _keys(n: int, dist: str):
    import jax
    import jax.numpy as jnp

    from icikit.utils.prandom import uniform_global
    u = uniform_global(jax.random.key(7), n, odd_dist=(dist == "odd"))
    return (u * 2e9 - 1e9).astype(jnp.int32)


def _overflowed(alg: str, mesh, x2d, n_loc: int, p: int,
                factor: float) -> bool:
    import jax

    from icikit.models.sort import quicksort as Q
    from icikit.models.sort import sample as S
    if alg == "quicksort":
        cap = max(1, int(factor * n_loc))
        out = Q._build(mesh, "p", cap)(x2d)
        return int(jax.device_get(out[-1].sum())) > 0
    splitter = "bitonic" if alg == "sample[bitonic]" else "allgather"
    cap = max(1, min(n_loc, int(factor * n_loc / p)))
    out = S._build(mesh, "p", cap, splitter)(x2d)
    return int(jax.device_get(out[-1].sum())) > 0


FACTORS = (1.25, 1.5, 2.0, 3.0, 4.0, 6.0)
ALGS = ("sample[allgather]", "sample[bitonic]", "quicksort")


def run_study(ns, ps, dists=("uniform", "odd"), factors=FACTORS,
              algs=ALGS, log=print):
    from icikit.utils.mesh import make_mesh, shard_along
    records = []
    for p in ps:
        mesh = make_mesh(p)
        for n_log in ns:
            n = 1 << n_log
            n_loc = n // p
            for dist in dists:
                keys = _keys(n, dist)
                x2d = shard_along(keys.reshape(p, n_loc), mesh)
                for alg in algs:
                    found = None
                    for f in factors:
                        if not _overflowed(alg, mesh, x2d, n_loc, p, f):
                            found = f
                            break
                    records.append({"alg": alg, "p": p, "n": n_log,
                                    "dist": dist, "min_factor": found})
                    log(f"p={p} n=2^{n_log} {dist:8s} {alg:18s} "
                        f"min_factor={found}")
    return records


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ns", default="20,22,24",
                    help="log2 global sizes, comma-separated")
    ap.add_argument("--ps", default="4,8")
    ap.add_argument("--out", default="capacity_study.json")
    ap.add_argument("--simulate", action="store_true",
                    help="force a simulated CPU mesh (set before jax "
                         "initializes)")
    args, _ = ap.parse_known_args(argv)
    if args.simulate:
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    ns = [int(x) for x in args.ns.split(",")]
    ps = [int(x) for x in args.ps.split(",")]
    records = run_study(ns, ps)
    with open(args.out, "w") as f:
        json.dump(records, f)
    print(f"wrote {len(records)} records to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
