"""Fleet coordinator: one queue, N engines, defect-aware leasing.

The production shape ROADMAP item 1 named: N ``serve.Engine``
processes behind ONE coordinator-owned :class:`RequestQueue`. Every
hard property was already built single-process and composes across
the wire because none of it ever depended on being in one process:

- **leases + claim generations** — an engine that stops renewing
  (death, stall, partition) loses its lease; ``reap_expired`` requeues
  the work and the claim-seq fence turns the stale engine's late RPCs
  into counted no-ops. Commits are idempotent. Sampled outputs are
  schedule-invariant (counter keys carry no engine state), so a
  reissue replays **bitwise on any engine** — the p−1-survive soak's
  exit bar.
- **prefill/decode disaggregation** (DistServe/Mooncake-style) —
  engines register a role; when the fleet holds dedicated prefill AND
  decode engines, a fresh request enters *prefill phase*: a
  prefill-capable engine claims it with ``n_new`` clamped to 1
  (prefill + first token = the TTFT-owning phase), streams its
  finalized sealed blocks to the block bridge, and the coordinator
  turns that completion into a :meth:`RequestQueue.handoff` — the
  request requeues for decode-capable engines with the committed
  token folded into the prompt. Absolute-position counter keys make
  the spliced stream bitwise the unsplit one.
- **defect-aware scheduling** — the r13 distinction ("host died" vs
  "host computes garbage") drives two different reactions: a dead
  engine is reaped by lease expiry / heartbeat timeout and its work
  reissued; an engine whose *completions fail KV integrity verify*
  (an ``IntegrityError`` fail RPC — the sealed-page checksums are the
  detector) is **quarantined**: no further claims, its in-flight
  leases force-expired and reissued to survivors. Content quarantine
  (a corrupt bridged block) is NOT an engine defect — the block is
  purged bridge-wide and recomputed, exactly the r16 swap-in rule.
- **SLO aggregation** — engines report heartbeat snapshots;
  per-request SLO marks (admit / first-token / worst-gap, monotonic —
  one host, one clock domain) ride the complete RPC onto the
  authoritative Request, and fleet-level gauges/counters
  (``fleet.engines.alive``, ``fleet.kv.migrations``, ...) land on
  the coordinator's obs bus.

- **HA (r18)** — with an :mod:`icikit.fleet.ha` context attached, the
  queue journals every verb append-before-ack
  (:mod:`icikit.fleet.journal`), the reap loop renews the leader
  lease and snapshots periodically, and a renewal failure **deposes**
  this coordinator: every mutating op raises
  :class:`DeposedError` from then on, bounding the stale-write
  window to one renewal interval — and even inside that window,
  stale appends land in this epoch's own journal segments, which the
  successor's takeover snapshot supersedes. Engine joins are
  authenticated by a shared ``join_token``; a fresh leader's replayed
  queue denies claims from engines it has never seen, and the engine
  re-registers (``fleet.roster.joins``) — the elastic-roster path.

Control plane rule (``fleet-control-plane`` analysis rule): this
module performs no jax device dispatch and allocates no jnp arrays —
claims, leases and KV bytes move over host sockets only.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from icikit import obs
from icikit.fleet.kvbridge import DEFAULT_RAM_BLOCKS, BlockBridge
from icikit.fleet.telemetry import bloom_prefix_hits
from icikit.fleet.transport import RpcServer
# kvpool's hashing helpers are numpy+hashlib only (no jax at module
# scope) — the coordinator may compute chain hashes without breaking
# the control-plane rule
from icikit.serve.kvpool import block_hashes
from icikit.serve.scheduler import RequestQueue
from icikit.serve.store import PrefixStore

ROLES = ("prefill", "decode", "both")

DEFAULT_HEARTBEAT_TIMEOUT_S = 60.0


class DeposedError(RuntimeError):
    """This coordinator lost its leader lease: it must not mutate the
    queue again (its journal epoch is dead). Surfaces to RPC clients
    as ``RpcError(etype="DeposedError")`` — the resolving client's
    cue to re-read the lease file and retarget the successor."""


class Coordinator:
    """Owns the queue, the engine registry, the block bridge, and the
    RPC surface the engine workers speak.

    ``store_dir`` backs the bridge with a real on-disk
    :class:`PrefixStore` — which is what makes the bridge a
    *persistent* fleet tier: a restarted coordinator re-serves every
    block the previous life persisted (the restart-rewarm drill in
    ``tests/test_fleet.py``).
    """

    def __init__(self, store_dir, lease_s: float = 5.0,
                 heartbeat_timeout_s: float =
                 DEFAULT_HEARTBEAT_TIMEOUT_S,
                 reap_interval_s: float = 0.25,
                 defect_threshold: int = 1,
                 host: str = "127.0.0.1", port: int = 0,
                 ha=None, join_token: str | None = None,
                 snapshot_every: int = 512, watch=None,
                 collector=None,
                 bridge_ram_blocks: int = DEFAULT_RAM_BLOCKS,
                 route_block_size: int | None = None,
                 route_staleness_s: float = 5.0,
                 route_escape_rounds: int = 32,
                 route_escape_s: float = 2.0):
        if ha is not None and ha.queue is not None:
            # a replayed queue (takeover or restart): already holds
            # every in-flight request the previous leader journaled
            self.queue = ha.queue
        else:
            self.queue = RequestQueue(lease_s=lease_s)
        self.bridge = BlockBridge(PrefixStore(store_dir),
                                  ram_blocks=bridge_ram_blocks)
        # -- cache-aware routing (r20) --------------------------------
        # route_block_size=None keeps dispatch cache-BLIND (the r19
        # behavior, and the bench's control arm). With a block size,
        # submit() hashes each prompt's block-aligned chain; claims
        # are steered to the engine whose heartbeat bloom advertises
        # the deepest resident prefix. ALL of this state is a
        # preference, never correctness: it is deliberately
        # unjournaled (a failed-over coordinator starts cache-blind
        # and re-learns from the next heartbeats), and every deny has
        # the starvation escape below it.
        self.route_block_size = route_block_size
        self.route_staleness_s = float(route_staleness_s)
        self.route_escape_rounds = int(route_escape_rounds)
        self.route_escape_s = float(route_escape_s)
        self._chains: dict = {}         # rid -> [chain hash hex, ...]
        self._resident: dict = {}       # eid -> (bloom summary, t)
        self._resident_ver = 0
        self._route_cache: dict = {}    # rid -> (ver, {eid: score})
        self._route_skips: dict = {}    # rid -> claim rounds passed over
        self._route_escaped: set = set()
        # mirrors of the fleet.route.* counters (mutated only inside
        # the claim predicate, i.e. serialized under the queue lock)
        self.n_route_hits = 0
        self.n_route_misses = 0
        self.n_route_steered = 0
        self.n_route_escaped = 0
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.defect_threshold = defect_threshold
        self._lock = threading.Lock()
        self._engines: dict = {}    # id -> {role,state,last_seen,...}
        self._owner: dict = {}      # rid -> engine id of live claim
        self._phase: dict = {}      # rid -> "prefill"|"decode"|"any"
        self.n_handoffs = 0
        self._hold = False
        self._stop = threading.Event()
        self._ha = ha
        self.join_token = join_token
        self.snapshot_every = int(snapshot_every)
        self.epoch = ha.epoch if ha is not None else 0
        self._deposed = False
        self._watch = watch
        # fleet obs plane (r19): an obs.aggregate.FleetCollector —
        # telemetry.* RPCs route into it, heartbeats feed it, and the
        # reap loop drives its aggregated-stream watch poll
        self.collector = collector
        self.shutdown_requested = threading.Event()
        if ha is not None:
            meta = ha.meta.to_dict() if ha.meta is not None else {}
            self._phase = dict(meta.get("phases") or {})
            # replayed owners: engines of the PREVIOUS life — kept so
            # a heartbeat-timeout sweep can expire their rids; the
            # engines themselves must re-hello before claiming again
            self._owner = dict(meta.get("owners") or {})
            self.n_handoffs = int(meta.get("n_handoffs") or 0)
            self.queue.journal = ha.journal.append
        self.server = RpcServer(self._handle, host=host, port=port)
        self.addr = self.server.addr
        if ha is not None:
            # publish the bound address on the lease, then pin a
            # takeover snapshot: replay for the NEXT life starts at
            # this epoch's first segment, superseding every record a
            # deposed predecessor might still append
            ha.publish(self.addr)
            self._checkpoint()
        self._reaper = threading.Thread(
            target=self._reap_loop, args=(reap_interval_s,),
            daemon=True, name="fleet-reaper")
        self._reaper.start()

    # -- client side (the bench / the driving process) ---------------

    def _check_leader(self) -> None:
        if self._deposed:
            raise DeposedError(
                f"coordinator epoch {self.epoch} lost its lease")

    def _journal_meta(self, verb: str, rec: dict) -> None:
        """Append one coordinator-side record (``cphase``/``cowner``)
        — called under ``self._lock`` so meta records serialize with
        the snapshot the same way queue verbs do under theirs."""
        if self._ha is not None:
            self._ha.journal.append(verb, rec)

    def submit(self, prompt, n_new: int, **kw) -> str:
        """Queue one request. With disaggregation active (the registry
        holds a dedicated prefill engine AND a decode-capable one),
        the request enters prefill phase; otherwise any-role."""
        self._check_leader()
        rid = self.queue.submit(prompt, n_new, **kw)
        chains = None
        if self.route_block_size:
            # the routing key: the prompt's block-aligned chain-hash
            # lineage, same hash space the engines' heartbeat blooms
            # summarize (kvpool.block_hashes, fp/q8 arena side)
            chains = block_hashes(
                prompt, self.route_block_size,
                side="q8" if kw.get("quant") else "fp")
        with self._lock:
            roles = {e["role"] for e in self._engines.values()
                     if e["state"] == "live"}
            disagg = "prefill" in roles and (
                "decode" in roles or "both" in roles)
            self._phase[rid] = "prefill" if disagg else "any"
            if chains:
                self._chains[rid] = chains
            self._journal_meta("cphase", {"rid": rid,
                                          "phase": self._phase[rid]})
        return rid

    def drained(self) -> bool:
        return self.queue.drained()

    def hold(self, flag: bool) -> None:
        """While held, engines are told the queue is NOT drained even
        when it momentarily is — the bench's warm-up barrier: workers
        must idle between the warm batch completing and the timed
        trace's first arrival instead of exiting their run loop."""
        self._hold = bool(flag)

    def engines(self) -> dict:
        """Registry snapshot (states/roles/defects) for benches."""
        with self._lock:
            return {eid: dict(role=e["role"], state=e["state"],
                              defects=e["defects"],
                              stats=dict(e["stats"]))
                    for eid, e in self._engines.items()}

    def shutdown(self) -> None:
        self._stop.set()
        self.server.close()

    # -- eligibility / phases ----------------------------------------

    def _eligible(self, rid: str, role: str, has_prefill: bool,
                  has_decode: bool) -> bool:
        """Role-eligibility for one queued request. Runs under the
        QUEUE lock (the claim predicate), so the registry facts it
        needs (``has_prefill``/``has_decode`` = does any live engine
        of that capability remain) are snapshotted by the caller
        under the coordinator lock BEFORE the claim — never read
        here (the locks must not nest queue→coordinator; _untrack
        nests the other way). The degraded modes keep the fleet
        LIVE: when the last prefill-capable engine dies, decode
        engines may serve prefill-phase requests to completion (a
        full-token handoff finishes in one hop), and symmetrically —
        a stranded phase must never hang the queue."""
        phase = self._phase.get(rid, "any")
        if phase == "prefill":
            return role in ("prefill", "both") or not has_prefill
        # decode phase and undisaggregated requests both want an
        # engine that can run the request to completion
        return role in ("decode", "both") or not has_decode

    # -- cache-aware routing (r20) -----------------------------------

    def _route_scores(self, rid: str, chains, peers, ver: int) -> dict:
        """Per-engine longest-resident-prefix scores for one request,
        cached per residency-roster version (heartbeats bump the
        version ~2/s per engine; between bumps the same queued request
        is re-scored for free across claim polls). Runs under the
        QUEUE lock — everything it reads was snapshotted by
        ``_op_claim`` under the coordinator lock (``peers``) or is a
        coordinator-private dict only ever mutated under the queue
        lock (the cache itself)."""
        ent = self._route_cache.get(rid)
        if ent is not None and ent[0] == ver:
            return ent[1]
        scores = {eid: bloom_prefix_hits(summary, chains)
                  for eid, (_, summary) in peers.items()
                  if summary is not None}
        self._route_cache[rid] = (ver, scores)
        return scores

    def _route_accept(self, r, engine_id: str, role: str,
                      has_prefill: bool, has_decode: bool,
                      peers: dict, ver: int, now: float) -> bool:
        """The steered claim predicate (queue lock): eligibility is
        still the hard gate; on top of it, a request whose chain
        prefix scores strictly higher on some OTHER live, fresh,
        eligible engine is passed over — it keeps its heap position
        and the better engine's next poll wins it. Ties (including
        all-zero: nobody resident) go to whoever asked first, so a
        cold fleet is exactly blind dispatch. The escape hatch makes
        starvation impossible: after ``route_escape_rounds``
        pass-overs or ``route_escape_s`` of visibility the request is
        claimable by anyone, permanently — routing is a preference,
        never a correctness constraint."""
        if not self._eligible(r.rid, role, has_prefill, has_decode):
            return False
        chains = self._chains.get(r.rid)
        if not chains or r.rid in self._route_escaped:
            return True
        visible = max(r.arrival_t, r.visible_after)
        if (now - visible >= self.route_escape_s
                or self._route_skips.get(r.rid, 0)
                >= self.route_escape_rounds):
            self._route_escaped.add(r.rid)
            self.n_route_escaped += 1
            obs.count("fleet.route.escaped")
            return True
        scores = self._route_scores(r.rid, chains, peers, ver)
        mine = scores.get(engine_id, 0)
        best = mine
        for eid, (peer_role, summary) in peers.items():
            if eid == engine_id or summary is None:
                continue
            if not self._eligible(r.rid, peer_role, has_prefill,
                                  has_decode):
                continue
            best = max(best, scores.get(eid, 0))
        if mine >= best:
            self._route_skips.pop(r.rid, None)
            if mine > 0:
                self.n_route_hits += 1
                obs.count("fleet.route.hits")
            else:
                self.n_route_misses += 1
                obs.count("fleet.route.misses")
            return True
        self._route_skips[r.rid] = \
            self._route_skips.get(r.rid, 0) + 1
        self.n_route_steered += 1
        obs.count("fleet.route.steered")
        return False

    def _serialize_claim(self, req, role: str) -> dict:
        remaining = req.n_new - len(req.tokens)
        phase = self._phase.get(req.rid, "any")
        if phase == "prefill" and role == "prefill":
            # the DistServe split: prefill + first token, then handoff
            remaining = 1
        return {"rid": req.rid,
                "prompt": np.asarray(req.prompt).tolist(),
                "n_new": int(remaining),
                "eos_id": req.eos_id,
                "checksum": req.checksum,
                "quant": bool(req.quant),
                "seed": int(req.seed),
                "temperature": float(req.temperature),
                "top_k": int(req.top_k),
                "top_p": float(req.top_p),
                "max_retries": int(req.max_retries),
                "claim_seq": int(req.claim_seq),
                "attempts": int(req.attempts),
                "arrival_t": float(req.arrival_t),
                "admit_t": req.admit_t,
                "prefix_hit_tokens": 0,
                "phase": phase,
                "trace_id": req.trace.trace_id}

    # -- RPC handler -------------------------------------------------

    def _handle(self, op: str, msg: dict, blobs):
        if op is None:
            raise ValueError("message without an op")
        if op.startswith("store."):
            self._touch(msg.get("engine"))
            return self.bridge.handle(op, msg, blobs)
        if op.startswith("telemetry."):
            # telemetry is deliberately NOT journaled: it mutates no
            # queue state, and a deposed coordinator may keep
            # collecting while the successor takes over
            if self.collector is None:
                raise ValueError("fleet telemetry plane is not armed")
            return self.collector.handle(op, msg, blobs)
        fn = getattr(self, "_op_" + op, None)
        if fn is None:
            raise ValueError(f"unknown fleet op {op!r}")
        if self.collector is not None and op in ("claim", "renew"):
            # control-plane op latency into the fleet registry —
            # lease-path stalls are a coordinator health signal
            t0 = time.monotonic()
            out = fn(msg, blobs)
            self.collector.observe_latency(
                f"fleet.{op}_ms", (time.monotonic() - t0) * 1000.0)
            return out
        return fn(msg, blobs)

    def _touch(self, engine_id) -> None:
        if engine_id is None:
            return
        with self._lock:
            e = self._engines.get(engine_id)
            if e is not None:
                e["last_seen"] = time.monotonic()

    def _op_hello(self, msg, blobs):
        self._check_leader()
        engine_id, role = msg["engine"], msg["role"]
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r} (known: {ROLES})")
        if self.join_token is not None \
                and msg.get("token") != self.join_token:
            obs.count("fleet.roster.join_denied")
            raise PermissionError(
                f"engine {engine_id!r}: join token mismatch")
        with self._lock:
            rejoin = engine_id in self._engines
            self._engines[engine_id] = {
                "role": role, "state": "live",
                "last_seen": time.monotonic(), "defects": 0,
                "first_commit_t": None, "stats": {}}
        obs.count("fleet.engine.registered")
        obs.emit("fleet.engine.registered", engine=engine_id,
                 role=role)
        obs.count("fleet.roster.joins")
        obs.emit("fleet.roster.joined", engine=engine_id, role=role,
                 rejoin=rejoin, epoch=self.epoch)
        self._gauges()
        return {"lease_s": self.queue.lease_s,
                "epoch": self.epoch}, ()

    def _op_claim(self, msg, blobs):
        self._check_leader()
        engine_id = msg["engine"]
        self._touch(engine_id)
        now = time.monotonic()
        with self._lock:
            e = self._engines.get(engine_id)
            if e is None or e["state"] != "live":
                return {"req": None,
                        "denied": e["state"] if e else "unknown"}, ()
            role = e["role"]
            live = [x["role"] for x in self._engines.values()
                    if x["state"] == "live"]
            peers = None
            if self.route_block_size and len(live) > 1:
                # routing snapshot (coordinator lock, BEFORE the
                # claim — the _eligible lock discipline): live roles
                # plus each engine's residency summary, already
                # demoted to None past the staleness window so a
                # silent engine just looks cold
                peers = {}
                for eid, x in self._engines.items():
                    if x["state"] != "live":
                        continue
                    ent = self._resident.get(eid)
                    fresh = (ent is not None and
                             now - ent[1] <= self.route_staleness_s)
                    peers[eid] = (x["role"],
                                  ent[0] if fresh else None)
            ver = self._resident_ver
        has_prefill = any(r in ("prefill", "both") for r in live)
        has_decode = any(r in ("decode", "both") for r in live)
        if peers is None:
            accept = lambda r: self._eligible(  # noqa: E731
                r.rid, role, has_prefill, has_decode)
        else:
            accept = lambda r: self._route_accept(  # noqa: E731
                r, engine_id, role, has_prefill, has_decode,
                peers, ver, now)
        req = self.queue.claim(accept=accept)
        if req is None:
            return {"req": None}, ()
        # serialize BEFORE any possible expire below: the wire claim
        # must carry THIS claim's generation — an expire-then-reissue
        # bumps claim_seq, and serializing after it could hand the
        # stale engine the live generation
        wire = self._serialize_claim(req, role)
        with self._lock:
            self._owner[req.rid] = engine_id
            self._journal_meta("cowner", {"rid": req.rid,
                                          "engine": engine_id})
            still_live = self._engines[engine_id]["state"] == "live"
        if not still_live:
            # a quarantine/death raced the claim between the state
            # check and the owner registration: its rid escaped the
            # force-expire sweep, so expire it NOW — the engine still
            # receives the claim, but its generation is already
            # invalid and every mutation it sends fences out
            self.queue.expire([req.rid])
        obs.count("fleet.claims")
        return {"req": wire}, ()

    def _op_renew(self, msg, blobs):
        self._touch(msg["engine"])
        self.queue.renew(msg["rid"], seq=msg.get("seq"))
        return {}, ()

    def _first_commit(self, engine_id: str) -> None:
        """Stamp the engine's first successful commit instant — the
        elastic-roster scale-up metric (join decision -> first token
        served; monotonic is cross-process comparable on one host)."""
        now = time.monotonic()
        with self._lock:
            e = self._engines.get(engine_id)
            if e is not None and e.get("first_commit_t") is None:
                e["first_commit_t"] = now

    def _observe_slo(self, rid: str,
                     engine_id: str | None = None) -> None:
        """Feed the request's terminal TTFT into this process's
        histogram registry — what the fleet_watch SLO-burn detector
        windows over for the scale-up signal — and, with the obs
        plane armed, the full SLO record into the collector's
        PER-ENGINE watch windows (straggler/outlier detection needs
        to know which engine served it)."""
        slo = self.queue.request(rid).slo()
        if slo.get("ttft_ms") is not None:
            obs.observe("serve.ttft_ms", float(slo["ttft_ms"]))
        if self.collector is not None:
            self.collector.observe_slo(engine_id or "unknown", slo)

    def _op_complete(self, msg, blobs):
        self._check_leader()
        engine_id, rid = msg["engine"], msg["rid"]
        seq = msg.get("seq")
        tokens = [int(t) for t in msg["tokens"]]
        self._touch(engine_id)
        req = self.queue.request(rid)
        # the commit decision is TOKEN ARITHMETIC, never the phase
        # map: the authoritative stream is the handoff-committed
        # prefix (req.tokens — empty before any handoff; only our
        # live lease can be mutating it, stale callers fence out
        # below) plus this engine's continuation. A partial stream
        # hands off; a complete one terminates. The phase map only
        # drives claim ELIGIBILITY and the prefill n_new clamp, where
        # a racy read costs at most one extra handoff hop — it can
        # never truncate a committed result.
        full = list(req.tokens) + tokens
        finished = (len(full) >= req.n_new
                    or (req.eos_id is not None and tokens
                        and tokens[-1] == req.eos_id))
        if not finished:
            state = self.queue.handoff(rid, tokens, seq=seq)
            if state == "stale":
                return {"state": "stale", "committed": False}, ()
            self.queue.stamp_marks(rid, msg.get("marks"))
            self._first_commit(engine_id)
            if state == "queued":
                with self._lock:
                    self._phase[rid] = "decode"
                    self.n_handoffs += 1
                    self._owner.pop(rid, None)
                    self._journal_meta("cphase", {"rid": rid,
                                                  "phase": "decode"})
                obs.count("fleet.handoffs")
            else:
                self._untrack(rid)
                self._observe_slo(rid, engine_id)
            return {"state": state, "committed": True}, ()
        committed = self.queue.complete(rid, full, seq=seq)
        if committed:
            self.queue.stamp_marks(rid, msg.get("marks"))
            self._first_commit(engine_id)
            self._untrack(rid)
            self._observe_slo(rid, engine_id)
        return {"state": req.state, "committed": committed}, ()

    def _op_fail(self, msg, blobs):
        self._check_leader()
        engine_id, rid = msg["engine"], msg["rid"]
        self._touch(engine_id)
        exc = RuntimeError(msg.get("error", "engine failure"))
        state = self.queue.fail(rid, exc,
                                retry=bool(msg.get("retry", True)),
                                seq=msg.get("seq"))
        if state != "stale":
            self._untrack(rid, requeued=state == "queued")
        if msg.get("etype") == "IntegrityError":
            # "host computes garbage": the sealed-page checksums on
            # THIS engine's completions failed — that is the defect
            # signal, distinct from death (lease expiry) and from
            # content rot on the bridge (purged + recomputed, no
            # engine blamed)
            self._defect(engine_id, msg.get("error", ""))
        return {"state": state}, ()

    def _op_release(self, msg, blobs):
        self._check_leader()
        self._touch(msg["engine"])
        self.queue.release(msg["rid"],
                           delay=float(msg.get("delay", 0.0)),
                           seq=msg.get("seq"))
        self._untrack(msg["rid"], requeued=True)
        return {}, ()

    # -- driver-side RPC surface (the HA bench/soak process) ---------

    def _op_submit(self, msg, blobs):
        """Remote submit — the HA driver runs out-of-process (it must
        survive this coordinator's death), so admission is an RPC."""
        rid = self.submit(
            np.asarray(msg["prompt"], np.int32),
            int(msg["n_new"]),
            eos_id=msg.get("eos_id"),
            not_before=msg.get("not_before"),
            max_retries=int(msg.get("max_retries", 2)),
            quant=bool(msg.get("quant", False)),
            seed=int(msg.get("seed", 0)),
            temperature=float(msg.get("temperature", 0.0)),
            top_k=int(msg.get("top_k", 0)),
            top_p=float(msg.get("top_p", 1.0)))
        return {"rid": rid}, ()

    def _op_request(self, msg, blobs):
        """Serialized view of one request — the driver's post-drain
        audit read (tokens compared bitwise against single-request
        decode)."""
        try:
            req = self.queue.request(msg["rid"])
        except KeyError:
            return {"known": False}, ()
        return {"known": True, "state": req.state,
                "tokens": [int(t) for t in req.tokens],
                "error": req.error, "slo": req.slo()}, ()

    def _op_hold(self, msg, blobs):
        self.hold(bool(msg["flag"]))
        return {}, ()

    def _op_fleet_stats(self, msg, blobs):
        with self._lock:
            engines = {eid: {"role": e["role"], "state": e["state"],
                             "defects": e["defects"],
                             "first_commit_t": e.get("first_commit_t"),
                             "stats": dict(e["stats"])}
                       for eid, e in self._engines.items()}
            n_handoffs = self.n_handoffs
        out = {"epoch": self.epoch,
               "deposed": self._deposed,
               "pending": self.queue.pending(),
               "completed": len(self.queue.done),
               "failed": len(self.queue.failed),
               "reissues": self.queue.n_reissues,
               "duplicate_commits": self.queue.n_duplicate_commits,
               "handoffs": n_handoffs,
               "hold": self._hold,
               "engines": engines,
               "bridge": self.bridge.stats(),
               "route": {"enabled": bool(self.route_block_size),
                         "hits": self.n_route_hits,
                         "misses": self.n_route_misses,
                         "steered": self.n_route_steered,
                         "escaped": self.n_route_escaped}}
        if self._ha is not None:
            out["journal"] = self._ha.journal.stats()
        if self._watch is not None:
            out["watch"] = self._watch.verdict()
        if self.collector is not None:
            out["telemetry"] = self.collector.stats()
        return out, ()

    def _op_resident_chains(self, msg, blobs):
        """Roster residency query: per-engine resident-chain bloom
        summaries from the heartbeats — the substrate cache-aware
        ``claim(accept=)`` routing will consume (ROADMAP 1a)."""
        if self.collector is None:
            return {"resident": {}}, ()
        return {"resident": self.collector.resident_summaries()}, ()

    def _op_retire(self, msg, blobs):
        """Graceful scale-down: no further claims for this engine; it
        drains its in-flight work, then ``drained`` answers True for
        it and the worker exits through its normal path."""
        self._check_leader()
        engine_id = msg["engine"]
        with self._lock:
            e = self._engines.get(engine_id)
            known = e is not None and e["state"] == "live"
            if known:
                e["state"] = "retired"
        if known:
            obs.count("fleet.roster.retired")
            obs.emit("fleet.roster.retired", engine=engine_id)
            self._gauges()
        return {"retired": known}, ()

    def _op_shutdown(self, msg, blobs):
        """Driver-initiated clean exit (the CLI main loop watches the
        event) — replies with final stats first. The event is set on
        a short timer, not inline: the serve loop tears the RPC
        server down as soon as it fires, and an inline set races the
        handler thread's reply write against the socket close."""
        out, _ = self._op_fleet_stats(msg, blobs)
        threading.Timer(0.25, self.shutdown_requested.set).start()
        return out, ()

    def _op_report(self, msg, blobs):
        """Heartbeat + per-engine snapshot: keeps ``last_seen`` fresh
        independent of the engine loop (XLA compiles stall renewals,
        not the report thread) and aggregates fleet SLO gauges."""
        engine_id = msg["engine"]
        stats = {k: msg.get(k) for k in
                 ("tokens", "steps", "occupancy",
                  "integrity_failures")
                 if msg.get(k) is not None}
        with self._lock:
            e = self._engines.get(engine_id)
            if e is None:
                return {"state": "unknown"}, ()
            e["last_seen"] = time.monotonic()
            e["stats"] = stats
            state = e["state"]
            if msg.get("resident") is not None:
                # the routing roster: latest bloom summary plus its
                # arrival instant (the staleness clock). Version bump
                # invalidates the per-request score cache.
                self._resident[engine_id] = (msg["resident"],
                                             time.monotonic())
                self._resident_ver += 1
        if self.collector is not None:
            # roster state into the obs plane (outside our lock —
            # the collector takes its own)
            self.collector.update_report(engine_id, stats)
            if msg.get("resident") is not None:
                self.collector.update_resident(engine_id,
                                               msg["resident"])
        return {"state": state}, ()

    def _op_drained(self, msg, blobs):
        engine_id = msg.get("engine")
        if engine_id is not None:
            with self._lock:
                e = self._engines.get(engine_id)
                retired = e is not None and e["state"] == "retired"
            if retired and not self._rids_of(engine_id):
                # a retired engine leaves as soon as ITS plate is
                # clean — the rest of the fleet keeps serving
                return {"drained": True}, ()
        return {"drained": self.queue.drained()
                and not self._hold}, ()

    def _op_next_visible(self, msg, blobs):
        return {"wait": self.queue.next_visible_in()}, ()

    def _op_pending_prompts(self, msg, blobs):
        return {"prompts": [np.asarray(p).tolist()
                            for p in self.queue.pending_prompts()]}, ()

    def _op_bye(self, msg, blobs):
        with self._lock:
            e = self._engines.get(msg["engine"])
            if e is not None and e["state"] == "live":
                e["state"] = "gone"
        self._gauges()
        return {}, ()

    # -- defect / death handling -------------------------------------

    def _untrack(self, rid: str, requeued: bool = False) -> None:
        with self._lock:
            self._owner.pop(rid, None)
            if not requeued and self.queue.request(rid).state in (
                    "done", "failed"):
                self._phase.pop(rid, None)
                self._chains.pop(rid, None)
                self._route_cache.pop(rid, None)
                self._route_skips.pop(rid, None)
                self._route_escaped.discard(rid)

    def _rids_of(self, engine_id: str) -> list:
        with self._lock:
            return [rid for rid, eid in self._owner.items()
                    if eid == engine_id]

    def _defect(self, engine_id: str, reason: str) -> None:
        with self._lock:
            e = self._engines.get(engine_id)
            if e is None:
                return
            e["defects"] += 1
            quarantine = (e["defects"] >= self.defect_threshold
                          and e["state"] == "live")
            if quarantine:
                e["state"] = "quarantined"
        if not quarantine:
            return
        # drain -> quarantine -> reissue: no new leases for this
        # engine (claims denied), and its in-flight work force-expires
        # to survivors NOW — its late commits are already fenced by
        # claim seq, so the reissue replays bitwise elsewhere
        reaped = self.queue.expire(self._rids_of(engine_id))
        obs.count("fleet.engine.quarantined")
        obs.emit("fleet.engine.quarantined", engine=engine_id,
                 reason=reason, reissued=reaped)
        self._gauges()

    def _checkpoint(self) -> None:
        """Snapshot queue + coordinator meta as ONE compaction point.
        Holds the coordinator lock across the queue snapshot so no
        ``cphase``/``cowner`` record lands between the meta capture
        and the ``snap`` append (replay would supersede it with the
        stale copy). May no-op (queue mid-requeue) — retried next
        reap tick."""
        if self._ha is None:
            return
        with self._lock:
            meta = {"phases": dict(self._phase),
                    "owners": dict(self._owner),
                    "n_handoffs": self.n_handoffs}
            self.queue.checkpoint(meta=meta)

    def _ha_tick(self) -> None:
        """Renew the leader lease (a failed renewal deposes us — from
        then on every mutating op raises DeposedError) and keep
        replay bounded with a periodic snapshot."""
        if self._ha is None or self._deposed:
            return
        if not self._ha.renew():
            self._deposed = True
            obs.count("fleet.leader.losses")
            obs.emit("fleet.leader.lost", epoch=self.epoch)
            return
        if (self._ha.journal.records_since_snap
                >= self.snapshot_every):
            self._checkpoint()

    def _reap_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self._ha_tick()
            self.queue.reap_expired()
            now = time.monotonic()
            dead = []
            with self._lock:
                for eid, e in self._engines.items():
                    if (e["state"] == "live" and
                            now - e["last_seen"]
                            > self.heartbeat_timeout_s):
                        e["state"] = "dead"
                        dead.append(eid)
            for eid in dead:
                obs.count("fleet.engine.dead")
                obs.emit("fleet.engine.dead", engine=eid)
                self.queue.expire(self._rids_of(eid))
            self._gauges()
            if self._watch is not None:
                self._watch.maybe_poll()
            if self.collector is not None:
                self.collector.maybe_poll()

    def _gauges(self) -> None:
        with self._lock:
            alive = sum(e["state"] == "live"
                        for e in self._engines.values())
            quarantined = sum(e["state"] == "quarantined"
                              for e in self._engines.values())
        obs.gauge("fleet.engines.alive", float(alive))
        obs.gauge("fleet.engines.quarantined", float(quarantined))
        obs.gauge("fleet.pending", float(self.queue.pending()))
