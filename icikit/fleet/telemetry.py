"""Fleet telemetry forwarding: obs events over the coordinator RPC.

Since r17 the serving system is N processes, but the r15 obs stack is
process-local: each engine's bus events, metrics and trace live in a
buffer only that process can read. This module is the engine/standby
half of the fleet observability plane — the coordinator half is
:mod:`icikit.obs.aggregate`:

- a :class:`TelemetryForwarder` owns a **bounded** local queue and a
  daemon flusher thread. The bus sink (:class:`TelemetrySink`) and the
  trace delta capture are non-blocking appends; the flusher ships
  batches over the ordinary checksummed fleet RPC
  (``telemetry.batch``) on its OWN client connection. A slow or dead
  collector can therefore NEVER stall or perturb token generation:
  overflow and failed sends *drop and count* — the
  ``fleet.telemetry.dropped`` counter is the honest record, surfaced
  in the collector's health verdict, never silently absorbed.
- **clock alignment** — each process's trace timestamps come from its
  own ``perf_counter`` monotonic domain. The ``telemetry.hello``
  handshake echoes the collector's clock (NTP-style: client marks t0,
  collector replies with its clock t_s, client marks t1; offset =
  t_s − (t0+t1)/2), and every batch carries the offset so the
  collector can shift a source's events into its own domain. A
  constant per-process shift preserves per-(pid, tid) monotonicity,
  which is what keeps the merged trace checker-valid.
- **content integrity** — the batch payload carries its own
  blake2b-128 digest *inside* the RPC (the transport's frame checksum
  is computed after the ``fleet.telemetry.send`` corruption probe, so
  a flipped telemetry frame passes the wire and is caught by this
  layer's re-verify at the collector: content rot detected
  mechanically, batch dropped and counted, tokens untouched).
- chaos sites ``fleet.telemetry.send`` / ``fleet.telemetry.recv``
  drill the channel: delay (slow collector), die (dead channel — the
  flusher thread exits, the queue fills, drops count), corrupt
  (frame rot). All three must leave committed tokens bitwise
  identical to a disarmed run.

Also here (host-only, hashlib-based — the heartbeat payload must obey
the control-plane rule): :func:`chain_bloom` compresses an engine's
resident KV chain hashes into a compact bloom summary that rides the
heartbeat ``report`` RPC, giving the coordinator the per-engine
residency picture ROADMAP 1a's cache-aware routing will consume.

Control-plane rule (enforced by the ``fleet-control-plane`` analysis
rule): no jax import, no device dispatch — telemetry must keep
flowing while an engine's device schedules are exactly what is under
suspicion.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
import time

from icikit import chaos, obs
from icikit.fleet.transport import RpcClient, _maybe_corrupt_bytes
from icikit.obs import bus as _bus
from icikit.obs import tracer as _tracer

chaos.register_site("fleet.telemetry.send", "fleet.telemetry.recv")

DIGEST_BYTES = 16


def _now_us() -> int:
    # the tracer's clock (perf_counter microseconds) — handshake and
    # trace events must live in the SAME per-process monotonic domain
    # or the computed offset would not align the trace
    return time.perf_counter_ns() // 1000


def payload_digest(payload: bytes) -> str:
    """Content digest of one batch payload (hex blake2b-128). Computed
    sender-side before the send-corruption probe, re-verified
    collector-side after the recv probe — the telemetry layer's own
    rot detector, independent of the transport frame checksum."""
    return hashlib.blake2b(payload,
                           digest_size=DIGEST_BYTES).hexdigest()


# -- resident-chain summaries (heartbeat payload) --------------------

def chain_bloom(hashes, bits: int = 1024, k: int = 4) -> dict:
    """Compress chain hashes into a bloom summary dict
    (``{"bloom": hex, "bits", "k", "n"}``) compact enough to ride
    every heartbeat. False positives only (a set bit collision says
    "maybe resident"), never false negatives — the right polarity for
    cache-aware routing, where a miss costs one migration, not
    correctness."""
    if k > DIGEST_BYTES // 4:
        raise ValueError(f"k={k} needs more than {DIGEST_BYTES} "
                         "digest bytes")
    nbytes = max(1, bits // 8)
    buf = bytearray(nbytes)
    n = 0
    for h in hashes:
        n += 1
        for pos in _bloom_positions(h, nbytes * 8, k):
            buf[pos >> 3] |= 1 << (pos & 7)
    return {"bloom": bytes(buf).hex(), "bits": nbytes * 8, "k": k,
            "n": n}


def _bloom_positions(h, bits: int, k: int):
    d = hashlib.blake2b(str(h).encode(), digest_size=4 * k).digest()
    return [int.from_bytes(d[4 * i:4 * i + 4], "little") % bits
            for i in range(k)]


def bloom_contains(summary: dict, h) -> bool:
    """Is ``h`` (possibly) in the summarized set?"""
    buf = bytes.fromhex(summary["bloom"])
    return all(buf[p >> 3] & (1 << (p & 7))
               for p in _bloom_positions(h, int(summary["bits"]),
                                         int(summary["k"])))


def bloom_hits(summary: dict, hashes) -> int:
    """Longest consecutive *prefix* of ``hashes`` present in the
    summary — chain hashes are prefix-lineage keys, so only an
    unbroken resident prefix is reusable KV."""
    n = 0
    for h in hashes:
        if not bloom_contains(summary, h):
            break
        n += 1
    return n


def bloom_prefix_hits(summary, hashes) -> int:
    """Routing score: length of the longest *block-aligned prefix* of
    ``hashes`` (a request's chain-hash lineage, oldest block first)
    that the residency summary claims resident. This is the quantity
    cache-aware dispatch ranks engines by — a deep unbroken prefix is
    reusable KV; scattered mid-chain membership is worth nothing,
    because chain hash ``h_j`` only pays off if ``h_0..h_{j-1}`` are
    resident too.

    Hardened for the claim path: a missing, empty or malformed
    summary (an engine that never heartbeated, or a corrupt frame the
    digest check dropped) scores 0 — the engine just looks cold, which
    degrades routing to today's blind dispatch, never to an error.
    Bloom polarity guarantees no false negatives (a truly resident
    prefix always scores at least its length ... against the summary
    that advertised it); false positives can only INFLATE a score, and
    an inflated score mis-routes to a migration — the path every
    request could already take."""
    if not summary or not hashes:
        return 0
    try:
        buf = bytes.fromhex(summary["bloom"])
        bits = int(summary["bits"])
        k = int(summary["k"])
        if bits <= 0 or k <= 0 or len(buf) * 8 < bits:
            return 0
    except (KeyError, TypeError, ValueError):
        return 0
    n = 0
    for h in hashes:
        if not all(buf[p >> 3] & (1 << (p & 7))
                   for p in _bloom_positions(h, bits, k)):
            break
        n += 1
    return n


# -- forwarding ------------------------------------------------------

class TelemetrySink(_bus.Sink):
    """Bus sink that hands every event to a forwarder's bounded queue
    (non-blocking: overflow drops and counts, the producer never
    waits)."""

    def __init__(self, forwarder: "TelemetryForwarder"):
        self._fwd = forwarder

    def write(self, ev: dict) -> None:
        self._fwd.enqueue(ev)


class TelemetryForwarder:
    """Ships this process's obs stream to the fleet collector.

    ``start()`` performs the clock handshake, installs the bus sink,
    captures the armed trace buffer, and starts the daemon flusher;
    ``stop()`` drains one final batch and closes the client. Every
    loss mode — queue overflow, serialization failure, transport
    failure, injected death — increments ``dropped`` (mirrored into
    the local metrics registry and stamped on every batch header, so
    the collector's verdict sees it even when the metrics snapshot
    itself was the casualty).
    """

    def __init__(self, addr=None, source: str = "engine",
                 role: str = "engine", client=None,
                 queue_cap: int = 4096, flush_s: float = 0.25):
        if client is None:
            # ONE bounded retry: the flusher is the only caller, and a
            # dead collector must cost a drop, not minutes of backoff
            client = RpcClient(addr, retries=1, connect_timeout=2.0)
        self._client = client
        self.source = source
        self.role = role
        self.flush_s = flush_s
        self._cap = queue_cap
        self._events: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self.dropped = 0
        self._dropped_counted = 0
        self.sent_batches = 0
        self.offset_us: int | None = None
        self._seq = 0
        self._trace = None
        self._trace_idx = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.sink = TelemetrySink(self)
        self._sink_installed = False

    # -- producer side (engine threads) ------------------------------

    def enqueue(self, ev: dict) -> None:
        with self._lock:
            if len(self._events) >= self._cap:
                self.dropped += 1
                return
            self._events.append(ev)

    # -- lifecycle ----------------------------------------------------

    def start(self, install_sink: bool = True) -> "TelemetryForwarder":
        self._trace = _tracer.tracing()
        try:
            self._hello()
        except Exception:  # noqa: BLE001 - collector may come up later
            pass           # offset stays None; re-handshake per flush
        if install_sink:
            _bus.add_sink(self.sink)
            self._sink_installed = True
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"fleet-telemetry-{self.source}")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._sink_installed:
            _bus.remove_sink(self.sink)
            self._sink_installed = False
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    def alive(self) -> bool:
        """Is the channel still flushing? False after the die drill
        killed the flusher (the engine keeps generating; drops count)."""
        t = self._thread
        return t is not None and t.is_alive()

    def stats(self) -> dict:
        return {"source": self.source, "sent_batches": self.sent_batches,
                "dropped": self.dropped, "offset_us": self.offset_us,
                "alive": self.alive()}

    # -- flusher thread ----------------------------------------------

    def _hello(self) -> None:
        t0 = _now_us()
        reply, _ = self._client.call("telemetry.hello", {
            "source": self.source, "role": self.role,
            "pid": os.getpid()})
        t1 = _now_us()
        # NTP handshake-echo: the collector's clock read sits between
        # our two marks; half the round trip is the best offset bound
        self.offset_us = int(reply["clock_us"]) - (t0 + t1) // 2

    def _run(self) -> None:
        try:
            while not self._stop.wait(self.flush_s):
                self._flush_once()
            self._flush_once()      # final drain on clean stop
        except chaos.InjectedDeath:
            # the dead-channel drill: the CHANNEL dies, the engine
            # does not — the queue fills and drops count from here on
            pass
        finally:
            try:
                self._client.close()
            except Exception:  # noqa: BLE001 - teardown
                pass

    def _collect(self) -> tuple:
        with self._lock:
            events = list(self._events)
            self._events.clear()
        trace_delta: list = []
        tb = self._trace
        if tb is not None:
            evs = tb.events          # append-only; len() then slice is
            n = len(evs)             # safe against concurrent appends
            if n > self._trace_idx:
                trace_delta = evs[self._trace_idx:n]
                self._trace_idx = n
        snap = obs.metrics_snapshot()
        return events, trace_delta, snap

    def _flush_once(self) -> None:
        # surface queue-overflow drops into the local registry first,
        # so even a never-sending channel leaves an honest counter in
        # this process's own metrics snapshot
        with self._lock:
            new_drops = self.dropped - self._dropped_counted
            self._dropped_counted = self.dropped
        obs.count("fleet.telemetry.dropped", new_drops)
        events, trace_delta, snap = self._collect()
        if not events and not trace_delta and snap is None:
            return
        if self.offset_us is None:
            try:
                self._hello()
            except Exception:  # noqa: BLE001 - keep shipping unaligned
                pass
        n = len(events) + len(trace_delta)
        try:
            payload = _bus.dumps_strict(
                {"events": events, "trace": trace_delta,
                 "metrics": snap}).encode()
        except Exception:  # noqa: BLE001 - a hostile event payload
            self._count_drop(max(1, n))
            return
        digest = payload_digest(payload)
        self._seq += 1
        try:
            chaos.maybe_delay("fleet.telemetry.send")
            chaos.maybe_die("fleet.telemetry.send")
            # corruption AFTER the content digest: wire/content rot the
            # collector's re-verify must catch (the transport's frame
            # checksum is computed later, over the already-rotten
            # bytes, so it passes — by design)
            payload = _maybe_corrupt_bytes("fleet.telemetry.send",
                                           payload)
            self._client.call("telemetry.batch", {
                "source": self.source, "seq": self._seq,
                "offset_us": self.offset_us, "digest": digest,
                "dropped": self.dropped}, blobs=(payload,))
            self.sent_batches += 1
        except chaos.InjectedDeath:
            self._count_drop(max(1, n))
            raise
        except Exception:  # noqa: BLE001 - dead/slow collector: drop,
            self._count_drop(max(1, n))    # count, never stall
            # a failed send may mean a failed-over collector with a
            # fresh clock domain: force a re-handshake before the next
            # batch ships an offset into the wrong domain
            self.offset_us = None

    def _count_drop(self, n: int) -> None:
        with self._lock:
            self.dropped += n
            self._dropped_counted = self.dropped
        obs.count("fleet.telemetry.dropped", n)
