"""Engine-side fleet glue: the queue-shaped RPC proxy + role workers.

The design move that keeps this PR small relative to what it does:
``serve.Engine`` never learns it is in a fleet. It is constructed with
a **queue-shaped** object (:class:`RemoteQueue` — every verb the
engine calls forwards over the transport to the coordinator's real
:class:`RequestQueue`, fenced by the same claim generations) and a
**store-shaped** object (``kvbridge.BridgeStore``), and every
single-process behavior — admission, chunked prefill, tier restore
with digest verify, speculation, integrity verify at completion —
composes across the process boundary unchanged.

Roles:

- ``"both"`` — a full engine: claims any-phase and decode-phase work.
- ``"prefill"`` — claims prefill-phase work only; the coordinator
  clamps its claims to ``n_new=1`` (prefill + first token), and this
  worker pushes the request's finalized sealed blocks to the block
  bridge BEFORE sending ``complete`` — so by the time the coordinator
  hands the request off, a decode engine's admission already finds
  the chain on the bridge and *migrates* it instead of recomputing.
- ``"decode"`` — claims decode-phase (and undisaggregated) work; its
  pool's ``tier_plan`` consults the bridge, pulls blocks over the
  transport, re-verifies each content-keyed seal at swap-in
  (mismatch: quarantine bridge-wide, recompute fresh, no retry
  burned), and adopts them through the ordinary restore path.

``fleet.engine.die`` is the cross-process chaos boundary: it fires
inside the per-step lease renewal, i.e. mid-decode — the p−1-survive
soak kills workers there and the reissued work must replay bitwise on
survivors.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from icikit import chaos, obs
from icikit.fleet.kvbridge import BridgeStore
from icikit.fleet.telemetry import chain_bloom
from icikit.fleet.transport import (RpcClient, RpcError,
                                    _maybe_corrupt_bytes)
from icikit.obs import trace_ctx
from icikit.serve.scheduler import Request

chaos.register_site("fleet.engine.die")


class RemoteQueue:
    """Queue-shaped proxy over the coordinator RPC surface.

    Local bookkeeping mirrors just enough for the engine's host loop:
    claimed requests live in ``_local`` until a terminal RPC settles
    them, ``done``/``failed`` hold THIS engine's commits (``run()``
    returns their delta — per-engine completion counts), and SLO marks
    stamped by the engine on its local copy ride the complete RPC to
    the coordinator's authoritative Request. ``reap_expired`` is a
    no-op: lease reaping is the coordinator's job, engines only renew.
    """

    def __init__(self, client, engine_id: str, hello=None):
        self._client = client
        self.engine_id = engine_id
        # re-registration hook (HA, r18): a failed-over coordinator
        # replays the QUEUE but not the roster — engines are expected
        # to re-hello; claim answers ``denied: "unknown"`` until then
        self._hello = hello
        self._local: dict = {}
        self.done: dict = {}
        self.failed: dict = {}
        self.n_integrity_fails = 0
        # the engine completes/fails through this hook BEFORE the RPC
        # lands: the prefill worker pushes its sealed chain here so
        # the bridge holds the blocks before the handoff requeues the
        # request
        self.on_complete = None

    def _call(self, op: str, extra: dict | None = None):
        msg = {"engine": self.engine_id}
        if extra:
            msg.update(extra)
        reply, _ = self._client.call(op, msg)
        return reply

    # -- engine verbs -------------------------------------------------

    def claim(self) -> Request | None:
        reply = self._call("claim")
        w = reply.get("req")
        if w is None:
            if reply.get("denied") == "unknown" \
                    and self._hello is not None:
                # the coordinator that answered has never met us — a
                # failover successor. Re-register and retry on the
                # next loop pass; in-flight leases survived the
                # replay, only the roster entry is fresh.
                self._hello()
            return None
        req = Request(
            rid=w["rid"],
            prompt=np.asarray(w["prompt"], np.int32),
            n_new=int(w["n_new"]),
            checksum=w["checksum"], eos_id=w["eos_id"],
            quant=bool(w["quant"]), seed=int(w["seed"]),
            temperature=float(w["temperature"]),
            top_k=int(w["top_k"]), top_p=float(w["top_p"]),
            max_retries=int(w["max_retries"]),
            state="running", attempts=int(w["attempts"]),
            claim_seq=int(w["claim_seq"]),
            visible_after=float(w["arrival_t"]),
            arrival_t=float(w["arrival_t"]))
        if w.get("admit_t") is not None:
            # a decode-phase claim keeps the prefill phase's admission
            # mark: the SLO record is per-request, not per-attempt
            req.admit_t = float(w["admit_t"])
        # the trace id rode the RPC: engine-side spans/instants land
        # under the SAME async track as the coordinator's root/attempt
        # spans — one request, one tree, across processes
        req.trace = trace_ctx.adopt(w["rid"], w["trace_id"],
                                    int(w["claim_seq"]))
        # stamp THIS process into the tree immediately: even an
        # attempt that dies before any other instant leaves the
        # claiming engine's pid in the merged cross-process tree
        req.trace.instant("serve.req.claimed",
                          seq=int(w["claim_seq"]),
                          engine=self.engine_id)
        self._local[req.rid] = req
        return req

    def renew(self, rid: str, seq: int | None = None) -> None:
        # the kill-drill boundary: fires mid-decode, between steps —
        # the process dies holding live leases, which is exactly the
        # abandonment the coordinator's reaper must heal
        chaos.maybe_die("fleet.engine.die")
        self._call("renew", {"rid": rid, "seq": seq})

    def _marks(self, req: Request) -> dict:
        return {"admit_t": req.admit_t,
                "first_token_t": req.first_token_t,
                "max_gap_ms": req.max_gap_ms,
                "prefix_hit_tokens": req.prefix_hit_tokens}

    def complete(self, rid: str, tokens,
                 seq: int | None = None) -> bool:
        req = self._local.get(rid)
        tokens = [int(t) for t in tokens]
        if req is not None:
            req.tokens = tokens
            req.done_t = time.monotonic()
            if self.on_complete is not None:
                self.on_complete(req, tokens)
        reply = self._call("complete", {
            "rid": rid, "seq": seq, "tokens": tokens,
            "marks": self._marks(req) if req is not None else {}})
        committed = bool(reply.get("committed"))
        if committed and req is not None:
            req.state = "done"
            self.done[rid] = self._local.pop(rid)
        return committed

    def fail(self, rid: str, exc: BaseException, retry: bool = True,
             seq: int | None = None) -> str:
        etype = type(exc).__name__
        if etype == "IntegrityError":
            self.n_integrity_fails += 1
        reply = self._call("fail", {
            "rid": rid, "seq": seq, "error": repr(exc),
            "etype": etype, "retry": bool(retry)})
        state = reply.get("state", "stale")
        req = self._local.pop(rid, None)
        if state == "failed" and req is not None:
            req.state = "failed"
            req.error = repr(exc)
            self.failed[rid] = req
        return state

    def release(self, rid: str, delay: float = 0.0,
                seq: int | None = None) -> None:
        self._call("release", {"rid": rid, "seq": seq,
                               "delay": float(delay)})
        self._local.pop(rid, None)

    # -- loop support -------------------------------------------------

    def reap_expired(self) -> list:
        return []       # the coordinator's reaper owns lease expiry

    def drained(self) -> bool:
        return bool(self._call("drained")["drained"])

    def next_visible_in(self):
        return self._call("next_visible")["wait"]

    def pending_prompts(self) -> list:
        return [np.asarray(p, np.int32)
                for p in self._call("pending_prompts")["prompts"]]

    def request(self, rid: str) -> Request:
        for table in (self._local, self.done, self.failed):
            if rid in table:
                return table[rid]
        raise KeyError(f"{rid} is not resident on engine "
                       f"{self.engine_id}")


class EngineWorker:
    """One fleet engine: a ``serve.Engine`` wired to the coordinator.

    Heartbeats run on their OWN thread and connection
    (``report_interval_s``): an XLA compile stalls the engine loop's
    renewals for seconds, and declaring a merely-slow engine dead
    would churn reissues — the report thread keeps ``last_seen``
    honest about process liveness specifically.
    """

    def __init__(self, addr, engine_id: str, role: str,
                 params, mesh, cfg, serve_cfg,
                 report_interval_s: float = 0.5,
                 rewarm: bool = False,
                 ha_dir: str | None = None,
                 token: str | None = None):
        from icikit.serve.engine import Engine
        self.engine_id = engine_id
        self.role = role
        self.addr = tuple(addr) if addr is not None else None
        self.ha_dir = ha_dir
        self.token = token
        self.client = self._make_client()
        self._say_hello()
        self.queue = RemoteQueue(self.client, engine_id,
                                 hello=self._say_hello)
        self.bridge = BridgeStore(self.client, engine_id)
        if not serve_cfg.prefix_cache:
            raise ValueError(
                "fleet engines require prefix_cache=True: the KV "
                "bridge is consumed through the content-addressed "
                "index (tier_plan/restore), which does not exist "
                "with the cache off")
        self.engine = Engine(params, mesh, cfg, serve_cfg,
                             queue=self.queue, store=self.bridge)
        if role == "prefill":
            # stream finalized sealed blocks to the bridge BEFORE the
            # complete RPC triggers the handoff: the decode engine's
            # admission must find the chain already bridged
            self.queue.on_complete = self._push_chain
        self.report_interval_s = report_interval_s
        self._stop = threading.Event()
        self._report_thread: threading.Thread | None = None
        # restart-rewarm hook: pull the pending prompts' chains from
        # the bridge into the CACHED state before the first claim
        self.rewarm_blocks = (
            self.engine.rewarm(self.queue.pending_prompts())
            if rewarm else 0)

    def _make_client(self):
        """A lease-resolving :class:`~icikit.fleet.ha.LeaderClient`
        when the fleet runs HA (``ha_dir`` set) — it retargets across
        failovers — else a plain bounded-backoff RpcClient."""
        if self.ha_dir is not None:
            from icikit.fleet.ha import LeaderClient
            return LeaderClient(self.ha_dir, fallback_addr=self.addr)
        return RpcClient(self.addr)

    def _say_hello(self) -> None:
        msg = {"engine": self.engine_id, "role": self.role}
        if self.token is not None:
            msg["token"] = self.token
        self.client.call("hello", msg)

    def _push_chain(self, req: Request, tokens) -> None:
        n = self.engine.export_chain(
            np.concatenate([req.prompt,
                            np.asarray(tokens, np.int32)]))
        if n:
            req.trace.instant("serve.req.bridged", seq=req.claim_seq,
                              blocks=n)

    def _report_loop(self) -> None:
        client = self._make_client()
        try:
            while not self._stop.wait(self.report_interval_s):
                try:
                    # list() snapshots the dict in one GIL-atomic C
                    # call: the engine thread inserts into done
                    # concurrently, and a generator iterating it
                    # would raise mid-report — killing the heartbeat
                    # thread and getting a HEALTHY engine declared
                    # dead at the timeout
                    done = list(self.queue.done.values())
                    # residency summary for the coordinator's routing
                    # roster + the collector. The corrupt probe flips
                    # summary bits past every checksum — the stale/
                    # corrupt-bloom drill: routing built on a rotten
                    # summary may MIS-ROUTE (a claim lands on a cold
                    # engine, costing one migration), but can never
                    # mis-compute — the claim path replays bitwise on
                    # any engine
                    resident = chain_bloom(
                        self.engine.resident_chains())
                    raw = bytes.fromhex(resident["bloom"])
                    rot = _maybe_corrupt_bytes(
                        "fleet.telemetry.send", raw)
                    if rot is not raw:
                        resident["bloom"] = rot.hex()
                    client.call("report", {
                        "engine": self.engine_id,
                        "tokens": sum(len(r.tokens) for r in done),
                        "steps": self.engine.n_steps,
                        "occupancy": self.engine.occupancy_mean(),
                        "integrity_failures":
                            self.queue.n_integrity_fails,
                        "resident": resident})
                except (ConnectionError, OSError, RpcError):
                    return      # coordinator gone: the loop will see
                except Exception:   # noqa: BLE001 - heartbeat must
                    continue        # outlive any stats hiccup
        finally:
            client.close()

    def run(self, drain: bool = True, max_steps: int | None = None):
        """Serve until the coordinator's queue drains. Returns this
        engine's completed-request count. An ``InjectedDeath`` from
        ``fleet.engine.die`` propagates — the worker process exits
        holding its leases, which is the drill."""
        self._report_thread = threading.Thread(
            target=self._report_loop, daemon=True,
            name=f"fleet-report-{self.engine_id}")
        self._report_thread.start()
        clean = False
        try:
            out = self.engine.run(drain=drain, max_steps=max_steps)
            clean = True
            return out
        finally:
            self._stop.set()
            if clean:
                # a DYING worker must not say goodbye: death is
                # detected by heartbeat/lease expiry, that is the drill
                try:
                    self.queue._call("bye")
                except (ConnectionError, OSError, RpcError):
                    pass

    def close(self) -> None:
        self._stop.set()
        self.client.close()


def engine_stats(worker: EngineWorker) -> dict:
    """Per-engine bench snapshot (records carry one per worker)."""
    return {"engine": worker.engine_id, "role": worker.role,
            "completed": len(worker.queue.done),
            "steps": worker.engine.n_steps,
            "occupancy_mean": worker.engine.occupancy_mean(),
            "prefix": worker.engine.prefix_stats()}
