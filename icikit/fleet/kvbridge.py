"""Cross-engine KV block bridge: the fleet-shared bottom tier.

The r16 tiered KV cache ends at a per-process ``PrefixStore``; this
module lifts that tier over the transport so N engines share ONE
content-addressed block population (ROADMAP item 2b, Mooncake-style):

- the **server half** (:class:`BlockBridge`) lives in the coordinator
  process and wraps a real :class:`icikit.serve.store.PrefixStore` —
  blocks on the bridge are chain-hash-named ``.npz`` files with the
  exact ``serve/store.py`` layout, so a coordinator restart re-serves
  them (the restart-rewarm drill) and every torn-file/quarantine
  behavior is inherited, not reimplemented;
- the **client half** (:class:`BridgeStore`) is *store-shaped*: it
  duck-types ``PrefixStore`` (``has/get/put/quarantine`` plus the
  stats surface), so an engine constructed with ``store=BridgeStore``
  gets demand paging, ``tier_plan``, digest-verified restore,
  quarantine-and-recompute, drain-time persistence, and
  ``Engine.rewarm`` against the bridge with ZERO engine changes —
  the r13/r16 integrity story composes across the process boundary
  because the content digest rides next to the bytes.

Migration accounting: the bridge remembers which engine pushed each
hash; a pull by a *different* engine is a cross-engine KV migration
(``fleet.kv.migrations``) — the quantity the disaggregation bench and
the fleet smoke assert on.

Verification layering (deliberate, drilled): transport checksums catch
wire rot frame-by-frame; the ``fleet.kv.pull`` probe below corrupts
*after* those checksums pass, so the only detector left is the block's
content-keyed digest at ``KVPool`` swap-in — a mismatch quarantines
the content from every tier (a bridge-wide ``quarantine`` RPC removes
the file so no OTHER engine re-pulls the bad bytes), the row
recomputes fresh, and no retry is burned: the r16 swap-in semantics,
verbatim, across processes.

Control plane rule: no jax imports here (``fleet-control-plane``).
"""

from __future__ import annotations

import collections
import threading
import time

import numpy as np

from icikit import chaos, obs

# the migrate-SDC drill site: rot between the coordinator's disk and
# the pulling engine's arena that the wire checksums cannot see
chaos.register_site("fleet.kv.pull")

# default host-RAM tier capacity, in blocks: sized so the whole toy
# working set fits (the bench's Zipf shared prefixes are dozens of
# blocks); a real deployment sizes this in bytes against host RAM
DEFAULT_RAM_BLOCKS = 256


def encode_arrays(arrays):
    """``(meta_list, blobs)`` for a block payload: dtype/shape in the
    control frame, raw bytes as blob frames."""
    meta, blobs = [], []
    for a in arrays:
        a = np.ascontiguousarray(a)
        meta.append({"dtype": a.dtype.str, "shape": list(a.shape)})
        blobs.append(a.tobytes())
    return meta, blobs


def decode_arrays(meta, blobs):
    out = []
    for m, b in zip(meta, blobs):
        out.append(np.frombuffer(b, np.dtype(m["dtype"]))
                   .reshape(m["shape"]).copy())
    return out


class BlockBridge:
    """Coordinator-side bridge: a :class:`PrefixStore` plus per-hash
    writer provenance and an LRU **host-RAM tier** in front of the
    ``.npz`` disk tier (the r17 re-scope: a hot cross-engine migration
    should cost a memcpy + RPC, not a disk round trip). ``handle`` is
    the RPC dispatch surface the coordinator delegates ``store.*``
    ops to.

    RAM-tier contract:

    - **write-through** — a prefill push lands in RAM *and* on disk in
      the same ``store.put``, so coordinator restart/rewarm semantics
      are exactly the disk tier's (the RAM tier is a cache, never the
      system of record);
    - **promote-on-pull** — a disk hit is promoted into RAM so the
      second puller of a hot chain skips the disk;
    - **digest rides both tiers** — the content digest is stored next
      to the cached arrays and returned unchanged, so ``KVPool``
      swap-in verification is identical whichever tier served the
      bytes: a flipped cached byte fails the same digest check and
      the resulting ``store.quarantine`` purges BOTH tiers
      (bridge-wide, same as disk);
    - ``ram_blocks=0`` disables the tier (the bench's blind arm).

    The ``die:fleet.kv.pull`` drill fires on the RAM *hit* path: a
    host-tier fault (poisoned cache page, allocator failure) evicts
    the entry and falls back to the disk tier — and if disk can't
    serve either, the engine recomputes, so the tier degrades in the
    same recompute-beats-misread order as every other cache here."""

    def __init__(self, store, ram_blocks: int = DEFAULT_RAM_BLOCKS):
        self.store = store
        self._lock = threading.Lock()
        self._writer: dict = {}      # hash -> engine_id that pushed it
        self.ram_blocks = int(ram_blocks)
        # hash -> (side, digest, arrays); OrderedDict as LRU
        self._ram: collections.OrderedDict = collections.OrderedDict()
        self.n_migrations = 0
        self.migration_bytes = 0
        self.n_pushed = 0
        self.n_pulled = 0
        self.n_ram_hits = 0
        self.n_disk_hits = 0
        self.n_ram_faults = 0
        self._ram_hit_s = 0.0       # summed tier-fetch wall time
        self._disk_hit_s = 0.0

    # -- dispatch ----------------------------------------------------

    def handle(self, op: str, msg: dict, blobs):
        if op == "store.has":
            return {"found": self.store.has(msg["h"])}, ()
        if op == "store.get":
            return self._get(msg.get("engine", ""), msg["h"])
        if op == "store.put":
            return self._put(msg.get("engine", ""), msg["h"],
                             msg["side"], msg["digest"],
                             msg["meta"], blobs)
        if op == "store.quarantine":
            self.store.quarantine(msg["h"])
            with self._lock:
                self._writer.pop(msg["h"], None)
                # bridge-wide means EVERY tier: a digest failure at
                # any engine's swap-in purges the RAM copy too, so no
                # other engine can be served the suspect content from
                # the fast path the disk purge didn't cover
                self._ram.pop(msg["h"], None)
            obs.count("fleet.kv.quarantined")
            return {}, ()
        if op == "store.stats":
            return self.stats(), ()
        raise ValueError(f"unknown bridge op {op!r}")

    # -- ops ---------------------------------------------------------

    def _ram_insert(self, h: str, side: str, digest: str,
                    arrays) -> None:
        """LRU insert (lock held by caller NOT required — takes it):
        newest at the tail, evict from the head past capacity."""
        if self.ram_blocks <= 0:
            return
        with self._lock:
            self._ram[h] = (side, digest, arrays)
            self._ram.move_to_end(h)
            while len(self._ram) > self.ram_blocks:
                self._ram.popitem(last=False)

    def _put(self, engine: str, h: str, side: str, digest: str,
             meta, blobs):
        arrays = decode_arrays(meta, blobs)
        wrote = self.store.put(h, side, digest, arrays)
        if wrote:
            # write-through: disk is the system of record (restart
            # rewarm unchanged), RAM makes the NEXT puller fast
            self._ram_insert(h, side, digest, arrays)
            with self._lock:
                self._writer[h] = engine
                self.n_pushed += 1
            obs.count("fleet.kv.pushed")
            obs.gauge("fleet.kv.bridge_blocks",
                      float(self.store.n_blocks()))
        return {"wrote": wrote}, ()

    def _fetch(self, h: str):
        """Tiered block fetch: RAM, then disk (promoting the hit).
        Returns ``(side, digest, arrays)`` or None. Per-tier hit
        counters and wall time accumulate here — the quantities the
        r20 study prices the tier by."""
        t0 = time.perf_counter()
        hit = None
        with self._lock:
            if h in self._ram:
                hit = self._ram[h]
                self._ram.move_to_end(h)
        if hit is not None:
            try:
                # the host-tier fault drill: a die here means the RAM
                # copy can't be served — evict it and fall back to
                # disk (and, past disk, to recompute at the engine)
                chaos.maybe_die("fleet.kv.pull")
            except chaos.InjectedDeath:
                with self._lock:
                    self._ram.pop(h, None)
                    self.n_ram_faults += 1
                hit = None
            if hit is not None:
                with self._lock:
                    self.n_ram_hits += 1
                    self._ram_hit_s += time.perf_counter() - t0
                obs.count("fleet.bridge.ram_hits")
                return hit
        rec = self.store.get(h)
        if rec is None:
            return None
        side, digest, arrays = rec
        self._ram_insert(h, side, digest, arrays)   # promote-on-pull
        with self._lock:
            self.n_disk_hits += 1
            self._disk_hit_s += time.perf_counter() - t0
        obs.count("fleet.bridge.disk_hits")
        return side, digest, arrays

    def _get(self, engine: str, h: str):
        rec = self._fetch(h)
        if rec is None:
            return {"found": False}, ()
        side, digest, arrays = rec
        meta, blobs = encode_arrays(arrays)
        migrated = False
        with self._lock:
            self.n_pulled += 1
            writer = self._writer.get(h)
            if writer is not None and writer != engine:
                self.n_migrations += 1
                # the pricing quantity routed dispatch exists to
                # shrink: bytes moved because the claim landed on an
                # engine that did not write this block
                self.migration_bytes += sum(len(b) for b in blobs)
                migrated = True
        obs.count("fleet.kv.pulled")
        if migrated:
            obs.count("fleet.kv.migrations")
        return {"found": True, "side": side, "digest": digest,
                "meta": meta, "migrated": migrated}, blobs

    def stats(self) -> dict:
        with self._lock:
            n_ram = self.n_ram_hits
            n_disk = self.n_disk_hits
            return {"blocks": self.store.n_blocks(),
                    "pushed": self.n_pushed,
                    "pulled": self.n_pulled,
                    "migrations": self.n_migrations,
                    "migration_bytes": self.migration_bytes,
                    "quarantined": self.store.n_quarantined,
                    "ram_blocks": len(self._ram),
                    "ram_capacity": self.ram_blocks,
                    "ram_hits": n_ram,
                    "disk_hits": n_disk,
                    "ram_faults": self.n_ram_faults,
                    "ram_hit_us_mean":
                        round(self._ram_hit_s / n_ram * 1e6, 2)
                        if n_ram else None,
                    "disk_hit_us_mean":
                        round(self._disk_hit_s / n_disk * 1e6, 2)
                        if n_disk else None}


class BridgeStore:
    """Engine-side, store-shaped client for the coordinator's bridge.

    Duck-types :class:`icikit.serve.store.PrefixStore` exactly as the
    :class:`KVPool` consumes it — ``has``/``get``/``put``/
    ``quarantine`` plus the ``n_blocks()/n_writes/n_reads/
    n_quarantined`` stats surface — so it plugs into
    ``Engine(store=...)`` unchanged. All payload verification stays in
    the pool (digest at swap-in): this client only moves bytes and
    applies the ``fleet.kv.pull`` SDC probe after the transport has
    vouched for the wire."""

    def __init__(self, client, engine_id: str):
        self._client = client
        self.engine_id = engine_id
        self.n_writes = 0
        self.n_reads = 0
        self.n_quarantined = 0

    def has(self, h: str) -> bool:
        reply, _ = self._client.call("store.has", {"h": h})
        return bool(reply["found"])

    def n_blocks(self) -> int:
        reply, _ = self._client.call("store.stats")
        return int(reply["blocks"])

    def put(self, h: str, side: str, digest: str, arrays) -> bool:
        meta, blobs = encode_arrays(arrays)
        reply, _ = self._client.call(
            "store.put", {"engine": self.engine_id, "h": h,
                          "side": side, "digest": digest,
                          "meta": meta}, blobs)
        if reply["wrote"]:
            self.n_writes += 1
        return bool(reply["wrote"])

    def get(self, h: str):
        reply, blobs = self._client.call(
            "store.get", {"engine": self.engine_id, "h": h})
        if not reply["found"]:
            return None
        arrays = decode_arrays(reply["meta"], blobs)
        # the migrate-SDC drill boundary: past the wire checksums,
        # before the pool's swap-in digest verify — the only detector
        # for a flip HERE is the content digest, which is the point
        arrays[0] = chaos.maybe_corrupt("fleet.kv.pull", arrays[0])
        self.n_reads += 1
        return reply["side"], reply["digest"], arrays

    def quarantine(self, h: str) -> None:
        """Bridge-wide: the file leaves the coordinator's store so no
        OTHER engine can re-pull the corrupt content either."""
        try:
            self._client.call("store.quarantine", {"h": h})
        except (ConnectionError, OSError):
            pass     # quarantine is advisory cleanup; recompute wins
        self.n_quarantined += 1
        obs.count("serve.store.quarantined")
