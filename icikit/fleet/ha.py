"""Leader election + warm standby for the fleet coordinator (r18).

The control plane's HA story has three legs, all host-side (no jax —
the ``fleet-control-plane`` analysis rule enforces it):

- **Leader lease** — ``<ha_dir>/leader.json`` is a checksummed,
  atomically-replaced claim ``{epoch, owner, addr, deadline}``. The
  deadline is ``time.monotonic()``-based: CLOCK_MONOTONIC is shared by
  every process on the box (the single-host fleet's clock domain), so
  a standby can compare the leader's deadline against its own clock.
  A reader that fails the checksum treats the file as UNKNOWN, not
  expired: promotion on one corrupt read would make a half-written
  lease a double-leader factory. Two consecutive corrupt reads mean
  the file is rotten at rest — then the journal's own epoch floor
  (:func:`icikit.fleet.journal.epoch_floor`) substitutes for the
  unreadable epoch and the standby promotes over it.
- **Epoch fencing** — every acquisition mints ``max(seen, floor)+1``.
  If two candidates still mint the same epoch (the lease file lied),
  the journal's ``O_EXCL`` segment creation is the backstop: the loser
  gets :class:`~icikit.fleet.journal.EpochCollision`, bumps its floor
  past the collision, and re-elects. A deposed leader keeps its OLD
  epoch; its stale appends land in old-epoch segments that the
  successor's takeover snapshot supersedes (see journal docstring).
- **Warm standby** — :class:`Standby` tails the journal into a live
  :class:`~icikit.serve.scheduler.RequestQueue` replica while
  watching the lease. On expiry it acquires, drains the tail, and
  hands the coordinator a ready :class:`HaContext` — takeover cost is
  one final ``poll`` plus the snapshot, not a full replay.

Chaos sites: ``fleet.ha.lease`` (corrupt the lease bytes at read —
the corrupt-leader-file drill) and ``fleet.ha.epoch`` (io-fail at
epoch mint time, modeled as "the candidate read a stale epoch": it
re-mints an already-used epoch and must recover through the
``EpochCollision`` path — the double-leader drill).

``python -m icikit.fleet.ha cfg.json`` runs one coordinator process
(leader or standby role) for the HA soak and ``make fleet-ha-smoke``.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

import numpy as np

from icikit import chaos, obs
from icikit.fleet import journal as jlog
from icikit.serve.scheduler import DEFAULT_LEASE_S

chaos.register_site("fleet.ha.lease", "fleet.ha.epoch")

DIGEST_BYTES = 16
DEFAULT_LEASE_TIMEOUT_S = 2.0
DEFAULT_RENEW_S = 0.25


class LostElection(RuntimeError):
    """A candidate raced for the lease and lost to a live leader.
    Recoverable by design: a standby goes back to tailing, a cold
    starter retries within its ``wait_s`` budget."""


def _lease_path(ha_dir: str) -> str:
    return os.path.join(ha_dir, "leader.json")


class LeaderLease:
    """The checksummed leader claim file. All methods are single-shot
    and crash-safe: writes go through ``tmp + os.replace``, reads
    verify a trailing blake2b line before parsing."""

    def __init__(self, ha_dir: str,
                 timeout_s: float = DEFAULT_LEASE_TIMEOUT_S):
        self.ha_dir = ha_dir
        self.timeout_s = float(timeout_s)

    def read(self):
        """-> ``(claim_dict | None, status)`` with status ``"ok"``,
        ``"missing"`` or ``"corrupt"``. Corrupt is NOT expired — the
        caller owns the promote-or-wait policy."""
        try:
            with open(_lease_path(self.ha_dir), "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None, "missing"
        if chaos.active() is not None and raw:
            arr = np.frombuffer(raw, np.uint8).copy()
            out = chaos.maybe_corrupt("fleet.ha.lease", arr)
            raw = out.tobytes()
        payload, _, digest = raw.rpartition(b"\n")
        want = hashlib.blake2b(
            payload, digest_size=DIGEST_BYTES).hexdigest().encode()
        if digest.strip() != want:
            obs.count("fleet.leader.lease_corrupt")
            obs.emit("fleet.leader.lease_corrupt")
            return None, "corrupt"
        try:
            return json.loads(payload.decode()), "ok"
        except (UnicodeDecodeError, ValueError):
            obs.count("fleet.leader.lease_corrupt")
            obs.emit("fleet.leader.lease_corrupt")
            return None, "corrupt"

    def _write(self, claim: dict) -> None:
        payload = json.dumps(claim, allow_nan=False).encode()
        digest = hashlib.blake2b(
            payload, digest_size=DIGEST_BYTES).hexdigest().encode()
        os.makedirs(self.ha_dir, exist_ok=True)
        tmp = _lease_path(self.ha_dir) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload + b"\n" + digest)
        os.replace(tmp, _lease_path(self.ha_dir))

    def try_acquire(self, owner: str, addr=None,
                    floor: int = 0) -> int | None:
        """Claim leadership if the current lease is expired, missing,
        ours, or (caller's policy) rotten. Returns the minted epoch,
        or None while another live owner holds the lease."""
        now = time.monotonic()
        cur, status = self.read()
        if (status == "ok" and cur.get("owner") != owner
                and float(cur.get("deadline", 0)) > now):
            return None
        seen = int(cur.get("epoch", 0)) if cur else 0
        epoch = max(seen, floor) + 1
        try:
            chaos.maybe_io_fail("fleet.ha.epoch")
        except chaos.InjectedIOError:
            # drill: this candidate minted from a STALE epoch read —
            # collide with an epoch the journal already holds, so the
            # O_EXCL backstop has to catch it downstream
            stale = max(seen, floor)
            if stale >= 1:
                epoch = stale
        self._write({"epoch": epoch, "owner": owner,
                     "addr": list(addr) if addr else None,
                     "deadline": now + self.timeout_s})
        return epoch

    def renew(self, owner: str, epoch: int, addr=None) -> bool:
        """Push the deadline out; False means DEPOSED (a higher epoch
        or a different live owner took over) and the caller must stop
        acting as leader immediately."""
        now = time.monotonic()
        cur, status = self.read()
        if status == "ok":
            if int(cur.get("epoch", 0)) > int(epoch):
                return False
            if (cur.get("owner") != owner
                    and float(cur.get("deadline", 0)) > now):
                return False
        # missing/corrupt/ours: (re)assert — the leader repairs its
        # own rotten lease file rather than deposing itself
        self._write({"epoch": int(epoch), "owner": owner,
                     "addr": list(addr) if addr else None,
                     "deadline": now + self.timeout_s})
        return True


class HaContext:
    """What a coordinator needs to BE the leader: the minted epoch,
    the started journal, the replayed queue + meta (None/empty on a
    fresh cluster), and the lease to keep renewing."""

    def __init__(self, ha_dir: str, owner: str, lease: LeaderLease,
                 journal: jlog.Journal, epoch: int,
                 queue=None, meta=None):
        self.ha_dir = ha_dir
        self.owner = owner
        self.lease = lease
        self.journal = journal
        self.epoch = epoch
        self.queue = queue
        self.meta = meta
        self.addr = None

    def publish(self, addr) -> None:
        """Stamp the bound RPC address on the lease so resolvers
        (:class:`LeaderClient`) can find the new leader."""
        self.addr = tuple(addr)
        self.lease.renew(self.owner, self.epoch, addr=self.addr)

    def renew(self) -> bool:
        return self.lease.renew(self.owner, self.epoch,
                                addr=self.addr)

    def close(self) -> None:
        self.journal.close()


def _elect(ha_dir: str, owner: str, lease: LeaderLease,
           queue, meta, floor: int, t0: float,
           replayed: int, torn: int) -> HaContext:
    """Mint an epoch + start its journal, riding out epoch collisions
    by re-acquiring above the colliding epoch."""
    while True:
        epoch = lease.try_acquire(owner, floor=floor)
        if epoch is None:
            raise LostElection(
                f"{owner}: lease held by a live leader")
        journal = jlog.Journal(ha_dir)
        try:
            journal.start(epoch)
        except jlog.EpochCollision:
            obs.count("fleet.leader.epoch_collisions")
            obs.emit("fleet.leader.epoch_collision", owner=owner,
                     epoch=epoch)
            floor = max(floor, epoch, jlog.epoch_floor(ha_dir))
            continue
        obs.count("fleet.leader.elections")
        obs.gauge("fleet.leader.epoch", float(epoch))
        obs.emit("fleet.leader.elected", owner=owner, epoch=epoch,
                 takeover_ms=(time.monotonic() - t0) * 1e3,
                 replayed=replayed, torn=torn)
        return HaContext(ha_dir, owner, lease, journal, epoch,
                         queue=queue, meta=meta)


def become_leader(ha_dir: str, owner: str,
                  lease_timeout_s: float = DEFAULT_LEASE_TIMEOUT_S,
                  lease_s: float = DEFAULT_LEASE_S,
                  wait_s: float = 0.0) -> HaContext:
    """Cold-start election: replay whatever journal exists, then mint
    the next epoch. ``wait_s`` > 0 keeps retrying while another live
    leader holds the lease (the restart-into-running-cluster case)."""
    t0 = time.monotonic()
    lease = LeaderLease(ha_dir, timeout_s=lease_timeout_s)
    deadline = t0 + wait_s
    while True:
        cur, status = lease.read()
        live = (status == "ok" and cur.get("owner") != owner
                and float(cur.get("deadline", 0)) > time.monotonic())
        if live:
            if time.monotonic() >= deadline:
                raise TimeoutError(f"{owner}: lease held by "
                                   f"{cur.get('owner')} past wait_s")
            time.sleep(min(0.05, lease_timeout_s / 10))
            continue
        queue, meta, info = jlog.replay(ha_dir, lease_s=lease_s)
        try:
            return _elect(ha_dir, owner, lease, queue, meta,
                          jlog.epoch_floor(ha_dir), t0,
                          info["records"], info["torn"])
        except LostElection:
            # someone grabbed the lease between our read and acquire;
            # loop back into the wait (or raise once wait_s is spent)
            if time.monotonic() >= deadline:
                raise
            obs.count("fleet.leader.lost_elections")


class Standby:
    """Warm replica: tail the journal, watch the lease, promote on
    expiry. One instance per standby process."""

    def __init__(self, ha_dir: str, owner: str,
                 lease_timeout_s: float = DEFAULT_LEASE_TIMEOUT_S,
                 lease_s: float = DEFAULT_LEASE_S,
                 poll_s: float = 0.05):
        self.ha_dir = ha_dir
        self.owner = owner
        self.lease = LeaderLease(ha_dir, timeout_s=lease_timeout_s)
        self.lease_s = lease_s
        self.poll_s = poll_s
        self.tail = jlog.JournalTail(ha_dir, lease_s=lease_s)
        self._corrupt_streak = 0
        self._boot = time.monotonic()

    def _should_promote(self) -> bool:
        cur, status = self.read_lease()
        if status == "corrupt":
            # one rotten read could be a half-landed write; two in a
            # row is rot at rest — promote over it using the journal's
            # epoch floor (the lease's epoch is unreadable)
            self._corrupt_streak += 1
            return self._corrupt_streak >= 2
        self._corrupt_streak = 0
        if status == "missing":
            # cold-start grace: a standby launched alongside the seed
            # leader sees "missing" before the leader's first acquire
            # lands — promoting instantly would steal the cluster
            return (time.monotonic() - self._boot
                    >= self.lease.timeout_s)
        if cur.get("owner") == self.owner:
            return True
        return float(cur.get("deadline", 0)) <= time.monotonic()

    def read_lease(self):
        return self.lease.read()

    def run_until_leader(self, stop: threading.Event | None = None):
        """Block (tailing the journal) until the lease says the
        leader is gone, then promote. Returns the ready
        :class:`HaContext`, or None if ``stop`` was set first."""
        while stop is None or not stop.is_set():
            self.tail.poll()
            if self._should_promote():
                t0 = time.monotonic()
                queue, meta = self.tail.finish()
                try:
                    return _elect(self.ha_dir, self.owner,
                                  self.lease, queue, meta,
                                  jlog.epoch_floor(self.ha_dir), t0,
                                  self.tail.records, self.tail.torn)
                except LostElection:
                    # a sibling standby (or a restarting leader) won
                    # the race — go back to being a warm replica.
                    # finish() consumed the tail; rebuild it, which
                    # re-reads snapshot + tail from the journal.
                    obs.count("fleet.leader.lost_elections")
                    self.tail = jlog.JournalTail(
                        self.ha_dir, lease_s=self.lease_s)
                    self._corrupt_streak = 0
            time.sleep(self.poll_s)
        return None


class LeaderClient:
    """Failover-aware RPC client: resolves the current leader's
    address from the lease file, retargets on transport failure or a
    ``DeposedError`` reply, and keeps retrying within
    ``resolve_timeout_s`` — long enough to span one election."""

    def __init__(self, ha_dir: str, fallback_addr=None,
                 resolve_timeout_s: float = 20.0,
                 retry_s: float = 0.1):
        from icikit.fleet.transport import RpcClient
        self.ha_dir = ha_dir
        self.fallback_addr = (tuple(fallback_addr)
                              if fallback_addr else None)
        self.resolve_timeout_s = resolve_timeout_s
        self.retry_s = retry_s
        self._RpcClient = RpcClient
        self._lease = LeaderLease(ha_dir)
        self._client = None
        self._addr = None

    def _resolve(self):
        cur, status = self._lease.read()
        if status == "ok" and cur.get("addr"):
            return tuple(cur["addr"])
        return self.fallback_addr

    def _get_client(self):
        addr = self._resolve()
        if addr is None:
            return None
        if self._client is None or addr != self._addr:
            if self._client is not None:
                self._client.close()
            # few in-client retries; the failover loop out here owns
            # the long game (capped backoff keeps latency ~ lease)
            self._client = self._RpcClient(
                addr, retries=1, first_backoff=0.05, max_backoff=0.5)
            self._addr = addr
        return self._client

    def call(self, op: str, msg: dict | None = None, blobs=()):
        from icikit.fleet.transport import RpcError, TransportError
        deadline = time.monotonic() + self.resolve_timeout_s
        last = None
        while time.monotonic() < deadline:
            client = self._get_client()
            if client is None:
                time.sleep(self.retry_s)
                continue
            try:
                return client.call(op, msg, blobs)
            except RpcError as e:
                if e.etype != "DeposedError":
                    raise
                last = e            # stale leader: re-resolve
            except (TransportError, OSError) as e:
                last = e
            self._client.close()
            self._client = None
            obs.count("fleet.client.retargets")
            time.sleep(self.retry_s)
        raise TimeoutError(
            f"no leader reachable within {self.resolve_timeout_s}s "
            f"(last: {last!r})")

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


# -- coordinator process entry point (soak / smoke harness) ----------


def serve(cfg: dict) -> int:
    """Run one coordinator process until shutdown or deposal.
    Prints ``FLEET_HA_LEADER_OK {json}`` once leading (the harness
    barrier) and ``FLEET_HA_COORD_DONE {json}`` on clean exit."""
    from icikit.fleet.coordinator import Coordinator
    from icikit.obs.watch import fleet_watch

    ha_dir = cfg["ha_dir"]
    owner = cfg["owner"]
    role = cfg.get("role", "leader")
    lease_timeout_s = float(cfg.get("lease_timeout_s",
                                    DEFAULT_LEASE_TIMEOUT_S))
    lease_s = float(cfg.get("lease_s", 5.0))

    tele = None
    fleet_obs = bool(cfg.get("fleet_obs"))
    if fleet_obs:
        from icikit import obs as _obs
        _obs.enable_metrics()
        _obs.start_tracing()
        if role == "standby":
            # a WARM standby forwards its own obs stream to whoever
            # currently leads (lease-resolving client): its tail
            # progress and election telemetry land in the fleet
            # picture before it ever serves a claim
            from icikit.fleet.telemetry import TelemetryForwarder
            tele = TelemetryForwarder(
                client=LeaderClient(ha_dir), source=owner,
                role="standby").start()

    if role == "standby":
        standby = Standby(ha_dir, owner,
                          lease_timeout_s=lease_timeout_s,
                          lease_s=lease_s)
        ctx = standby.run_until_leader()
    else:
        ctx = become_leader(ha_dir, owner,
                            lease_timeout_s=lease_timeout_s,
                            lease_s=lease_s,
                            wait_s=float(cfg.get("wait_s", 0.0)))

    watch = None
    if cfg.get("watch") is not None:
        from icikit import obs as _obs
        _obs.enable_metrics()   # the watch windows THIS process's
        watch = fleet_watch(**cfg["watch"]).attach()
    collector = None
    if fleet_obs:
        # promoted (or seed leader): we ARE the collector now — stop
        # forwarding to ourselves and stand the aggregation plane up
        if tele is not None:
            tele.stop()
            tele = None
        from icikit.obs.aggregate import FleetCollector
        collector = FleetCollector()
    coord = Coordinator(
        cfg["store_dir"], lease_s=lease_s,
        heartbeat_timeout_s=float(cfg.get("heartbeat_timeout_s", 2.0)),
        reap_interval_s=float(cfg.get("reap_interval_s", 0.1)),
        defect_threshold=int(cfg.get("defect_threshold", 1)),
        host=cfg.get("host", "127.0.0.1"),
        port=int(cfg.get("port", 0)),
        ha=ctx, join_token=cfg.get("join_token"),
        snapshot_every=int(cfg.get("snapshot_every", 512)),
        watch=watch, collector=collector)
    print("FLEET_HA_LEADER_OK "
          + json.dumps({"owner": owner, "epoch": ctx.epoch,
                        "addr": list(coord.addr)}),
          flush=True)
    try:
        while not coord.shutdown_requested.wait(0.1):
            if coord._deposed:
                print("FLEET_HA_DEPOSED "
                      + json.dumps({"owner": owner,
                                    "epoch": ctx.epoch}), flush=True)
                return 3
        stats, _ = coord._op_fleet_stats({}, ())
        print("FLEET_HA_COORD_DONE " + json.dumps(stats), flush=True)
        return 0
    finally:
        coord.shutdown()
        ctx.close()


def main(argv=None) -> int:
    import sys
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m icikit.fleet.ha <cfg.json>",
              file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        cfg = json.load(f)
    return serve(cfg)


if __name__ == "__main__":
    raise SystemExit(main())
