"""Autoscale supervisor: watch-signal-driven spawn AND retire.

r18 proved the mechanisms one at a time, single-shot, inside the
bench driver: a ``fleet.pending`` watch alert spawned one
authenticated joiner (``run_fleet_ha --join``), and ``retire`` +
``drained`` let a worker leave gracefully. This module lifts that
into the policy loop a production fleet actually runs — the
coordinator-side half of "elasticity" (ROADMAP 1c):

- **scale up** when the watch verdict fires on queue depth
  (``fleet.pending`` watermark) or SLO burn (``serve.ttft_ms``
  burn-rate window) — the same :mod:`icikit.obs.watch` detectors that
  already gate the fleet's health verdict, so the supervisor invents
  no second monitoring path;
- **scale down** when the fleet has been *sustainedly* idle (queue
  depth at zero, no alert firing) — retire drains through the
  existing ``retire`` → ``drained`` RPC path, so an in-flight request
  on the victim finishes (or reissues via its lease) before the
  worker exits: scale-down can never lose work, for the same reason
  engine death can't;
- **cooldowns** on both directions bound the policy's thrash rate
  (an alert that keeps firing while a joiner is still compiling must
  not spawn a second joiner), and a roster **floor/ceiling** bounds
  its authority;
- only engines the supervisor itself spawned are retire candidates
  (LIFO) — the operator's base fleet is never scaled away.

The class is deliberately process-agnostic: it sees the fleet through
three callables (``stats_fn`` → the coordinator's ``fleet_stats``
dict, ``spawn_fn(engine_id)``, ``retire_fn(engine_id)``), so unit
tests drive the policy with fakes and a fake clock, and the bench
wires in real ``spawn_worker`` subprocesses + the ``retire`` RPC.
Every decision lands in ``events`` (monotonic-stamped) — the
scale-up/scale-down timeline the r20 study records.

Control plane rule (``fleet-control-plane``): no jax — the
supervisor must keep deciding while engines' devices are the thing
under load.
"""

from __future__ import annotations

import threading
import time

from icikit import obs

DEFAULT_ALERT_METRICS = ("fleet.pending", "serve.ttft_ms")


class Supervisor:
    """One fleet's scale policy. Call :meth:`tick` from your own loop
    (tests), or :meth:`start`/:meth:`stop` for the daemon-thread
    variant the bench uses."""

    def __init__(self, stats_fn, spawn_fn, retire_fn,
                 floor: int = 1, ceiling: int = 4,
                 spawn_cooldown_s: float = 3.0,
                 retire_cooldown_s: float = 3.0,
                 scale_down_idle_s: float = 1.5,
                 poll_s: float = 0.25,
                 alert_metrics=DEFAULT_ALERT_METRICS,
                 clock=time.monotonic):
        if floor < 0 or ceiling < max(1, floor):
            raise ValueError(
                f"need 0 <= floor <= ceiling (>=1), got "
                f"floor={floor} ceiling={ceiling}")
        self.stats_fn = stats_fn
        self.spawn_fn = spawn_fn
        self.retire_fn = retire_fn
        self.floor = int(floor)
        self.ceiling = int(ceiling)
        self.spawn_cooldown_s = float(spawn_cooldown_s)
        self.retire_cooldown_s = float(retire_cooldown_s)
        self.scale_down_idle_s = float(scale_down_idle_s)
        self.poll_s = float(poll_s)
        self.alert_metrics = tuple(alert_metrics)
        self._clock = clock
        self.events: list = []
        self.spawned: list = []     # our joiners, spawn order
        self.n_spawns = 0
        self.n_retires = 0
        self._last_spawn_t: float | None = None
        self._last_retire_t: float | None = None
        self._idle_since: float | None = None
        self._seen_alerts = 0
        self._seq = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- policy ------------------------------------------------------

    def _cooled(self, last_t, cooldown: float, now: float) -> bool:
        return last_t is None or now - last_t >= cooldown

    def tick(self, now: float | None = None) -> dict | None:
        """One policy decision against the current fleet stats.
        Returns the event dict when the tick scaled, else None."""
        now = self._clock() if now is None else now
        stats = self.stats_fn()
        alerts = (stats.get("watch") or {}).get("alerts", [])
        # the watch verdict is CUMULATIVE over the run; pressure is
        # alerts NEW since the last tick (sustained pressure keeps
        # producing them — one per polled window). A shrunken list
        # means the watch restarted (coordinator failover): rebase.
        if len(alerts) < self._seen_alerts:
            self._seen_alerts = 0
        fired = [a for a in alerts[self._seen_alerts:]
                 if a.get("metric") in self.alert_metrics]
        self._seen_alerts = len(alerts)
        engines = stats.get("engines") or {}
        live = sorted(eid for eid, e in engines.items()
                      if e.get("state") == "live")
        pending = int(stats.get("pending") or 0)
        if fired or pending > 0:
            self._idle_since = None
        if fired:
            if (len(live) < self.ceiling
                    and self._cooled(self._last_spawn_t,
                                     self.spawn_cooldown_s, now)):
                return self._spawn(now, fired[0])
            return None
        # no pressure signal: consider giving capacity back
        if pending == 0:
            if self._idle_since is None:
                self._idle_since = now
            if (now - self._idle_since >= self.scale_down_idle_s
                    and len(live) > self.floor
                    and self._cooled(self._last_retire_t,
                                     self.retire_cooldown_s, now)):
                # LIFO among OUR joiners still live: the base fleet
                # is not ours to shrink
                victim = next((eid for eid in reversed(self.spawned)
                               if eid in live), None)
                if victim is not None:
                    return self._retire(now, victim)
        return None

    def _spawn(self, now: float, alert: dict) -> dict:
        engine_id = f"auto{self._seq}"
        self._seq += 1
        self.spawn_fn(engine_id)
        self.spawned.append(engine_id)
        self._last_spawn_t = now
        self.n_spawns += 1
        ev = {"t": now, "action": "spawn", "engine": engine_id,
              "reason": alert.get("metric")}
        self.events.append(ev)
        obs.count("fleet.autoscale.spawns")
        obs.emit("fleet.autoscale.spawned", engine=engine_id,
                 reason=ev["reason"])
        return ev

    def _retire(self, now: float, engine_id: str) -> dict:
        self.retire_fn(engine_id)
        self._last_retire_t = now
        self._idle_since = None    # re-observe idleness from scratch
        self.n_retires += 1
        ev = {"t": now, "action": "retire", "engine": engine_id,
              "reason": "idle"}
        self.events.append(ev)
        obs.count("fleet.autoscale.retires")
        obs.emit("fleet.autoscale.retired", engine=engine_id)
        return ev

    # -- daemon-thread driver ----------------------------------------

    def start(self) -> "Supervisor":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleet-supervisor")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - a stats hiccup (e.g.
                continue       # coordinator mid-failover) must not
                               # kill the policy loop

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    def timeline(self) -> list:
        """Copy of the decision events (the study's record field)."""
        return [dict(ev) for ev in self.events]
