"""icikit.fleet — multi-engine serving coordinator.

ROADMAP item 1's production shape: N ``serve.Engine`` processes behind
one coordinator-owned ``RequestQueue``, prefill/decode roles split
DistServe-style, KV blocks migrating between engines through a
content-addressed block bridge (the r16 persistent tier, fleet-shared
over a checksummed host-socket transport), and defect-aware leasing
that distinguishes "host died" (lease expiry → reissue) from "host
computes garbage" (integrity-verify failures → quarantine the engine,
reissue its in-flight work). See docs/FLEET.md.

Layering: ``transport`` (frames/checksums/RPC, host-only) →
``kvbridge`` (store-shaped block migration) → ``journal``
(append-before-ack verb log + replay, r18) → ``ha`` (leader lease,
warm standby, failover-aware client) → ``coordinator`` (queue owner,
roles, defect ledger, fleet metrics) → ``roles`` (queue-shaped engine
proxy + workers) → ``worker`` (subprocess entry). The control plane
(transport/coordinator/kvbridge/journal/ha) never touches jax —
enforced by the ``fleet-control-plane`` analysis rule.
"""

from icikit.fleet.coordinator import Coordinator, DeposedError  # noqa: F401
from icikit.fleet.ha import (  # noqa: F401
    HaContext,
    LeaderClient,
    LeaderLease,
    LostElection,
    Standby,
    become_leader,
)
from icikit.fleet.journal import (  # noqa: F401
    EpochCollision,
    Journal,
    JournalTail,
    replay,
)
from icikit.fleet.kvbridge import BlockBridge, BridgeStore  # noqa: F401
from icikit.fleet.roles import (  # noqa: F401
    EngineWorker,
    RemoteQueue,
    engine_stats,
)
from icikit.fleet.transport import (  # noqa: F401
    ChecksumError,
    RpcClient,
    RpcError,
    RpcServer,
    TransportError,
)
