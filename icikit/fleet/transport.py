"""Host-side fleet transport: length-prefixed frames, checksums, RPC.

The fleet control plane cannot ride device collectives — this image's
jaxlib has no CPU multiprocess collectives ("Multiprocess computations
aren't implemented", the multihost bring-up skip), and a control plane
that *could* use them still must not: coordinator traffic (claims,
lease renewals, quarantine reports) has to keep flowing while a
defective engine's device schedules are exactly what is under
suspicion. So the seam is plain TCP on the host, with the repo's
integrity discipline applied to the wire:

- **framing** — every frame is ``MAGIC | u64 length | payload |
  blake2b-128(payload)``. The magic catches stream desync (a corrupted
  length prefix), the trailing digest catches payload rot in flight:
  a flipped wire byte is *detected mechanically* at receive
  (:class:`ChecksumError`), never parsed. One message = one strict-JSON
  control frame (``allow_nan=False`` — the bus's NaN rule, hardened to
  a parse error) followed by ``msg["blobs"]`` raw binary frames (KV
  block payloads ride here; base64-in-JSON would double the bytes).
- **bounded reconnect** — the client retries a failed call on a fresh
  connection with bounded exponential backoff (the ``chaos.io_retry``
  policy shape). Every fleet RPC is at-least-once safe by construction:
  queue mutations are idempotent/fenced (claim-seq), store puts are
  content-addressed, so a lost reply costs a retry, never corruption.
- **chaos sites** — ``fleet.rpc.send`` (delay / die / corrupt the
  outbound payload *after* its digest: wire rot, which the receiver's
  checksum must catch) and ``fleet.rpc.recv`` (corrupt the inbound
  payload *before* verification: same detection path from the other
  end). End-to-end content rot that never touches the wire is the KV
  bridge's ``fleet.kv.pull`` site — only the content digest catches
  that one, by design.

Control plane rule (enforced by the ``fleet-control-plane`` analysis
rule): this module never imports jax — no device dispatch, no jnp
allocation. numpy appears only to hand ``chaos.maybe_corrupt`` a byte
view.
"""

from __future__ import annotations

import hashlib
import json
import socket
import struct
import threading
import time

import numpy as np

from icikit import chaos, obs

chaos.register_site("fleet.rpc.send", "fleet.rpc.recv")

MAGIC = b"icfl"
_LEN = struct.Struct(">Q")
DIGEST_BYTES = 16
# a corrupted length prefix must fail loudly, not allocate garbage
MAX_FRAME = 1 << 31


class TransportError(ConnectionError):
    """Structural failure on the fleet wire (desync, short read)."""


class ChecksumError(TransportError):
    """A frame's payload failed its blake2b re-verify at receive —
    wire corruption, detected mechanically."""


class RpcError(RuntimeError):
    """The remote handler raised; ``etype`` carries the remote
    exception type name so callers can dispatch on it."""

    def __init__(self, msg: str, etype: str = "RuntimeError"):
        super().__init__(msg)
        self.etype = etype


def _digest(payload: bytes) -> bytes:
    return hashlib.blake2b(payload, digest_size=DIGEST_BYTES).digest()


def _maybe_corrupt_bytes(site: str, payload: bytes) -> bytes:
    """Route payload bytes through the SDC probe (zero-copy when the
    plan is cold — the common case is `is`-identity and no copy)."""
    if chaos.active() is None:
        return payload
    arr = np.frombuffer(payload, np.uint8)
    out = chaos.maybe_corrupt(site, arr)
    return payload if out is arr else out.tobytes()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise TransportError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf += chunk
    return bytes(buf)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    chaos.maybe_delay("fleet.rpc.send")
    chaos.maybe_die("fleet.rpc.send")
    digest = _digest(payload)
    # the corruption probe sits AFTER the digest: it models rot on the
    # wire, which the receiver's re-verify must detect — the drill in
    # tests/test_fleet_transport.py asserts exactly that
    payload = _maybe_corrupt_bytes("fleet.rpc.send", payload)
    sock.sendall(MAGIC + _LEN.pack(len(payload)) + payload + digest)


def recv_frame(sock: socket.socket) -> bytes:
    head = _recv_exact(sock, len(MAGIC) + _LEN.size)
    if head[:len(MAGIC)] != MAGIC:
        raise TransportError("frame desync: bad magic")
    (n,) = _LEN.unpack(head[len(MAGIC):])
    if n > MAX_FRAME:
        raise TransportError(f"frame length {n} exceeds cap")
    payload = _recv_exact(sock, n)
    digest = _recv_exact(sock, DIGEST_BYTES)
    chaos.maybe_delay("fleet.rpc.recv")
    payload = _maybe_corrupt_bytes("fleet.rpc.recv", payload)
    if _digest(payload) != digest:
        obs.count("fleet.rpc.checksum_failures")
        raise ChecksumError("frame payload failed checksum re-verify")
    return payload


def send_msg(sock: socket.socket, msg: dict, blobs=()) -> None:
    """One message: a strict-JSON control frame announcing
    ``blobs`` raw frames, then the frames themselves."""
    msg = dict(msg)
    msg["blobs"] = len(blobs)
    send_frame(sock, json.dumps(msg, allow_nan=False).encode())
    for b in blobs:
        send_frame(sock, bytes(b))


def recv_msg(sock: socket.socket):
    head = recv_frame(sock)
    try:
        msg = json.loads(head.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise TransportError(f"control frame is not strict JSON: {e}")
    if not isinstance(msg, dict):
        raise TransportError("control frame must be a JSON object")
    blobs = [recv_frame(sock) for _ in range(int(msg.pop("blobs", 0)))]
    return msg, blobs


class RpcServer:
    """Threaded request/reply server over the frame protocol.

    ``handler(op, msg, blobs) -> (reply_dict, reply_blobs)`` runs on a
    per-connection thread; an exception becomes an error reply
    (``ok: False``) raised client-side as :class:`RpcError`, and the
    connection survives. A frame-level failure (desync, checksum)
    drops the connection — the client reconnects; at-least-once RPC
    semantics are the contract (see module docstring)."""

    def __init__(self, handler, host: str = "127.0.0.1",
                 port: int = 0):
        from icikit.utils.net import server_socket
        self._handler = handler
        self._sock = server_socket(host, port)
        self.addr = self._sock.getsockname()
        self._stop = threading.Event()
        self._conns: list = []
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="fleet-rpc-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return          # socket closed: shutdown
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name="fleet-rpc-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg, blobs = recv_msg(conn)
                except (TransportError, OSError):
                    return      # drop the connection; client retries
                op = msg.pop("op", None)
                try:
                    reply, rblobs = self._handler(op, msg, blobs)
                    reply = {"ok": True, **(reply or {})}
                except Exception as e:  # noqa: BLE001 - wire boundary
                    obs.count("fleet.rpc.errors")
                    reply, rblobs = {"ok": False, "error": str(e),
                                     "etype": type(e).__name__}, ()
                try:
                    send_msg(conn, reply, rblobs)
                except (TransportError, OSError):
                    return
        finally:
            conn.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class RpcClient:
    """One connection to an :class:`RpcServer` with bounded
    reconnect-and-retry. ``call`` is serialized under a lock (one
    outstanding RPC per connection — the engine loop is single-
    threaded; the report thread opens its own client)."""

    def __init__(self, addr, retries: int = 3,
                 first_backoff: float = 0.05,
                 connect_timeout: float = 5.0,
                 max_backoff: float | None = None):
        self.addr = tuple(addr)
        self.retries = retries
        self.first_backoff = first_backoff
        self.connect_timeout = connect_timeout
        # failover clients ride many retries across a leader election:
        # capping the backoff keeps reconnect latency ~ lease timeout
        # instead of doubling past it
        self.max_backoff = max_backoff
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(
                self.addr, timeout=self.connect_timeout)
            s.settimeout(None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call(self, op: str, msg: dict | None = None, blobs=()):
        """One RPC round trip -> ``(reply_dict, reply_blobs)``.
        Transport failures (refused, reset, checksum) retry on a fresh
        connection with bounded exponential backoff; a remote handler
        error raises :class:`RpcError` immediately (retrying an
        application error is the caller's policy, not the wire's)."""
        payload = {"op": op, **(msg or {})}
        backoff = self.first_backoff
        with self._lock:
            for attempt in range(self.retries + 1):
                try:
                    sock = self._connect()
                    send_msg(sock, payload, blobs)
                    reply, rblobs = recv_msg(sock)
                    break
                except (TransportError, OSError):
                    self._drop()
                    if attempt == self.retries:
                        raise
                    time.sleep(backoff)
                    backoff *= 2
                    if self.max_backoff is not None:
                        backoff = min(backoff, self.max_backoff)
        if not reply.get("ok"):
            raise RpcError(reply.get("error", "remote error"),
                           reply.get("etype", "RuntimeError"))
        return reply, rblobs

    def close(self) -> None:
        with self._lock:
            self._drop()
