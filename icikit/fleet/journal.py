"""Durable coordinator journal: append-before-ack verb log + replay.

The HA half of the fleet control plane (r18). Every
:class:`~icikit.serve.scheduler.RequestQueue` mutation verb appends
one checksummed, length-prefixed record here — from inside the verb's
final lock section, i.e. strictly before the RPC ack leaves the
coordinator — so a leader that dies mid-decode leaves a log whose
replay reconstructs the queue **bitwise**
(``RequestQueue.state_digest`` equality is the tested bar).

Layout under ``<ha_dir>/journal/``::

    seg-<epoch:08d>-<k:08d>.log      one append-only segment per
                                     (leader epoch, rotation index)
    epoch-<epoch:08d>.lock           O_EXCL epoch-ownership marker
                                     (empty; survives compaction)
    ../cursor.json                   latest compaction point (best
                                     effort; corrupt/missing -> full
                                     scan from the oldest segment)

Record framing: ``b"icjl" | u32 len | strict-JSON {"v","rec"} |
blake2b-16(payload)`` — the same detect-mechanically contract as the
RPC frames in :mod:`icikit.fleet.transport`. A record that fails the
magic/length/checksum is **torn**: replay stops reading that segment
(a single sequential writer can only tear its tail — the mid-write
kill) and moves to the next one.

Snapshots are ordinary ``snap`` records (the queue serializes itself
under its own lock via ``RequestQueue.checkpoint``); the journal
reacts by rotating to a fresh segment whose FIRST record is the
snapshot, advancing the cursor, and deleting every earlier segment —
replay cost stays bounded by ``snapshot_every`` records regardless of
uptime.

Epoch fencing: ``start`` claims ``epoch-<epoch:08d>.lock`` with
``O_EXCL`` before opening the first segment, so two leaders that
somehow mint the same epoch collide on the marker file
(:class:`EpochCollision`) — the loser re-elects with a higher floor.
The marker (not the segment) is the ownership witness because
compaction deletes rotated-away segments: after the owner's first
snapshot rotation the epoch's ``k=0`` segment is gone, and without a
compaction-proof witness a second candidate could re-create it and
the epoch would have two writers. Markers are empty files, removed
only for epochs strictly below the current writer's. A deposed
leader's stale appends land in its OWN old-epoch segment; the
successor's takeover snapshot (first record of the new epoch's first
segment) supersedes everything that sorts before it, so stale writes
are structurally unable to reach replayed state.

Chaos sites: ``fleet.leader.die`` (process killed between records —
the kill-the-leader soak's mid-decode probe) and
``fleet.journal.write`` (process killed mid-record: half the frame
reaches the file, then ``os._exit`` — the torn-tail drill). Both
model ``kill -9``, so they exit the PROCESS rather than raise: a
torn record anywhere but a dead writer's tail would be a data-loss
bug, not a drill.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading

from icikit import chaos, obs
from icikit.serve.scheduler import DEFAULT_LEASE_S, RequestQueue

chaos.register_site("fleet.leader.die", "fleet.journal.write")

MAGIC = b"icjl"
_LEN = struct.Struct(">I")
DIGEST_BYTES = 16
MAX_RECORD = 1 << 28


class JournalError(RuntimeError):
    pass


class EpochCollision(JournalError):
    """Two leaders minted the same epoch: the segment file already
    exists. The caller must re-acquire the lease with a higher epoch
    floor — the double-leader defense of last resort."""


def _seg_name(epoch: int, k: int) -> str:
    return f"seg-{epoch:08d}-{k:08d}.log"


def _seg_epoch(name: str) -> int:
    return int(name[4:12])


def _marker_name(epoch: int) -> str:
    return f"epoch-{epoch:08d}.lock"


def _marker_epoch(name: str) -> int:
    return int(name[6:14])


def _markers(ha_dir: str) -> list:
    try:
        names = os.listdir(journal_dir(ha_dir))
    except FileNotFoundError:
        return []
    return sorted(n for n in names
                  if n.startswith("epoch-") and n.endswith(".lock"))


def journal_dir(ha_dir: str) -> str:
    return os.path.join(ha_dir, "journal")


def segments(ha_dir: str) -> list:
    """Segment file names in replay order (epoch, then rotation
    index — the zero-padded names sort exactly that way)."""
    try:
        names = os.listdir(journal_dir(ha_dir))
    except FileNotFoundError:
        return []
    return sorted(n for n in names
                  if n.startswith("seg-") and n.endswith(".log"))


def epoch_floor(ha_dir: str) -> int:
    """Highest epoch ever claimed on disk — the floor a candidate
    leader must acquire strictly above, even when the lease file
    itself is gone or corrupt. Markers count alongside segments: a
    claimed-but-not-yet-written epoch still fences."""
    seg_hi = max((_seg_epoch(n) for n in segments(ha_dir)), default=0)
    mark_hi = max((_marker_epoch(n) for n in _markers(ha_dir)),
                  default=0)
    return max(seg_hi, mark_hi)


def frame(verb: str, rec: dict) -> bytes:
    payload = json.dumps({"v": verb, "rec": rec},
                         allow_nan=False).encode()
    digest = hashlib.blake2b(payload,
                             digest_size=DIGEST_BYTES).digest()
    return MAGIC + _LEN.pack(len(payload)) + payload + digest


def read_records(path: str, offset: int = 0):
    """Decode records from ``offset``; returns ``(records,
    end_offset, status)`` with status ``"ok"`` (clean EOF),
    ``"partial"`` (trailing bytes too short for their claimed record —
    a write may still be in flight) or ``"torn"`` (bad magic/length/
    checksum — the writer died mid-record). ``end_offset`` always
    points at the first undecoded byte, so a tailing reader can
    resume there once more bytes land."""
    with open(path, "rb") as f:
        f.seek(offset)
        data = f.read()
    records = []
    pos, n = 0, len(data)
    status = "ok"
    while pos < n:
        if pos + len(MAGIC) + _LEN.size > n:
            status = "partial"
            break
        if data[pos:pos + len(MAGIC)] != MAGIC:
            status = "torn"
            break
        (length,) = _LEN.unpack(
            data[pos + len(MAGIC):pos + len(MAGIC) + _LEN.size])
        if length > MAX_RECORD:
            status = "torn"
            break
        body = pos + len(MAGIC) + _LEN.size
        end = body + length + DIGEST_BYTES
        if end > n:
            status = "partial"
            break
        payload = data[body:body + length]
        digest = data[body + length:end]
        if hashlib.blake2b(
                payload, digest_size=DIGEST_BYTES).digest() != digest:
            status = "torn"
            break
        obj = json.loads(payload.decode())
        records.append((obj["v"], obj["rec"]))
        pos = end
    return records, offset + pos, status


def _cursor_path(ha_dir: str) -> str:
    return os.path.join(ha_dir, "cursor.json")


def read_cursor(ha_dir: str) -> str | None:
    """Name of the segment replay may start from (it begins with a
    snap record). Best effort: anything wrong -> None -> full scan
    from the oldest surviving segment, which is always safe."""
    try:
        with open(_cursor_path(ha_dir)) as f:
            cur = json.load(f)
        return cur.get("seg")
    except (OSError, ValueError):
        return None


def _write_cursor(ha_dir: str, seg: str) -> None:
    tmp = _cursor_path(ha_dir) + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"seg": seg}, f)
    os.replace(tmp, _cursor_path(ha_dir))


class Journal:
    """Single-writer append log for one leader epoch.

    ``append`` is what ``RequestQueue.journal`` points at: it runs
    under the queue's (or the coordinator's, for ``cphase``/
    ``cowner`` meta records) lock, serialized further by its own lock
    since the two callers interleave. A ``snap`` verb triggers
    rotation + compaction inline — snapshots are rare by
    construction (``snapshot_every``), so the held-lock file work is
    a bounded, amortized cost the module docstring owns."""

    def __init__(self, ha_dir: str):
        self.ha_dir = ha_dir
        self._lock = threading.Lock()
        self._f = None
        self._epoch = None
        self._k = 0
        self._seg = None
        self._count_in_seg = 0
        self.records_since_snap = 0
        self.n_records = 0
        self.n_snapshots = 0

    def start(self, epoch: int) -> None:
        """Claim ``epoch-<epoch>.lock`` then open the epoch's first
        segment, both with ``O_EXCL`` — raises
        :class:`EpochCollision` if any leader (us in a previous life
        included) already owns the epoch. The marker is the witness
        that survives compaction: the ``k=0`` segment is deleted by
        the owner's own first snapshot rotation, so it alone cannot
        fence a late second candidate."""
        os.makedirs(journal_dir(self.ha_dir), exist_ok=True)
        marker = os.path.join(journal_dir(self.ha_dir),
                              _marker_name(epoch))
        try:
            os.close(os.open(marker, os.O_CREAT | os.O_EXCL))
        except FileExistsError:
            raise EpochCollision(
                f"epoch marker for epoch {epoch} already exists: "
                f"another leader claimed this epoch") from None
        name = _seg_name(epoch, 0)
        path = os.path.join(journal_dir(self.ha_dir), name)
        try:
            f = open(path, "xb")
        except FileExistsError:
            raise EpochCollision(
                f"journal segment for epoch {epoch} already exists "
                f"({name}): another leader holds this epoch") from None
        with self._lock:
            self._f = f
            self._epoch = int(epoch)
            self._k = 0
            self._seg = name
            self._count_in_seg = 0

    def append(self, verb: str, rec: dict) -> None:
        buf = frame(verb, rec)
        snapped = False
        with self._lock:
            if self._f is None:
                raise JournalError("journal not started")
            if verb == "snap" and self._count_in_seg:
                self._rotate_locked()
            if chaos.active() is not None:
                self._write_with_drills_locked(buf)
            else:
                self._f.write(buf)
                self._f.flush()
            self._count_in_seg += 1
            self.n_records += 1
            if verb == "snap":
                # this segment now STARTS with a full snapshot:
                # everything earlier is dead weight — advance the
                # cursor and compact
                self.records_since_snap = 0
                self.n_snapshots += 1
                _write_cursor(self.ha_dir, self._seg)
                self._compact_locked()
                snapped = True
            else:
                self.records_since_snap += 1
        obs.count("fleet.journal.records")
        if snapped:
            obs.count("fleet.journal.snapshots")

    def _write_with_drills_locked(self, buf: bytes) -> None:
        # both sites model kill -9: the process must die, not the
        # handler thread — an InjectedDeath swallowed by the RPC
        # server would leave a mid-file torn record, which replay
        # correctly treats as data loss
        try:
            chaos.maybe_die("fleet.leader.die")
        except chaos.InjectedDeath:
            os._exit(17)
        try:
            chaos.maybe_die("fleet.journal.write")
        except chaos.InjectedDeath:
            self._f.write(buf[:max(1, len(buf) // 2)])
            self._f.flush()
            os._exit(23)
        self._f.write(buf)
        self._f.flush()

    def _rotate_locked(self) -> None:
        self._f.close()
        self._k += 1
        name = _seg_name(self._epoch, self._k)
        path = os.path.join(journal_dir(self.ha_dir), name)
        try:
            self._f = open(path, "xb")
        except FileExistsError:
            raise JournalError(
                f"rotation target {name} exists: epoch "
                f"{self._epoch} has two writers") from None
        self._seg = name
        self._count_in_seg = 0

    def _compact_locked(self) -> None:
        jdir = journal_dir(self.ha_dir)
        for name in segments(self.ha_dir):
            if name < self._seg:
                try:
                    os.remove(os.path.join(jdir, name))
                except OSError:
                    pass
        # markers below the current epoch can never be re-minted
        # (epoch_floor includes OUR marker, so every future mint is
        # strictly above it) — safe to sweep; ours must stay
        for name in _markers(self.ha_dir):
            if _marker_epoch(name) < self._epoch:
                try:
                    os.remove(os.path.join(jdir, name))
                except OSError:
                    pass

    def stats(self) -> dict:
        with self._lock:
            return {"records": self.n_records,
                    "snapshots": self.n_snapshots,
                    "records_since_snap": self.records_since_snap,
                    "epoch": self._epoch, "segment": self._seg}

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class MetaTracker:
    """Coordinator-side state that rides the journal next to the
    queue: request phases (the prefill/decode disaggregation router),
    the rid->engine ownership map (what a dead engine's expiry
    sweeps), and the handoff counter. Derived from ``cphase``/
    ``cowner`` meta records plus the terminal queue verbs."""

    def __init__(self):
        self.phases: dict = {}
        self.owners: dict = {}
        self.n_handoffs = 0

    def to_dict(self) -> dict:
        return {"phases": dict(self.phases),
                "owners": dict(self.owners),
                "n_handoffs": self.n_handoffs}

    def apply(self, verb: str, rec: dict) -> None:
        if verb == "snap":
            m = rec.get("meta") or {}
            self.phases = dict(m.get("phases") or {})
            self.owners = dict(m.get("owners") or {})
            self.n_handoffs = int(m.get("n_handoffs") or 0)
        elif verb == "cphase":
            self.phases[rec["rid"]] = rec["phase"]
        elif verb == "cowner":
            if rec.get("engine") is None:
                self.owners.pop(rec["rid"], None)
            else:
                self.owners[rec["rid"]] = rec["engine"]
        elif verb == "complete" and not rec.get("dup"):
            self.owners.pop(rec["rid"], None)
            self.phases.pop(rec["rid"], None)
        elif verb == "handoff":
            outcome = rec.get("outcome")
            if outcome in ("done", "queued"):
                self.owners.pop(rec["rid"], None)
            if outcome == "done":
                self.phases.pop(rec["rid"], None)
            elif outcome == "queued":
                self.n_handoffs += 1
        elif verb == "fail":
            self.owners.pop(rec["rid"], None)
            if not rec.get("requeued"):
                self.phases.pop(rec["rid"], None)
        elif verb == "release":
            self.owners.pop(rec["rid"], None)
        elif verb == "reap":
            for rid, _seq in rec["reaped"]:
                self.owners.pop(rid, None)


def apply_one(queue: RequestQueue, meta: MetaTracker,
              verb: str, rec: dict) -> None:
    """Route one record: meta verbs to the tracker, queue verbs to
    both (the tracker derives terminal pops from them)."""
    meta.apply(verb, rec)
    if verb not in ("cphase", "cowner"):
        queue.apply_record(verb, rec)


def replay_records(records, lease_s: float = DEFAULT_LEASE_S):
    """Rebuild (queue, meta) from an in-memory record list — the
    property test's any-prefix-replays-bitwise entry point."""
    queue = RequestQueue(lease_s=lease_s)
    meta = MetaTracker()
    for verb, rec in records:
        apply_one(queue, meta, verb, rec)
    queue.finalize_replay()
    return queue, meta


def replay(ha_dir: str, lease_s: float = DEFAULT_LEASE_S):
    """Full recovery read: cursor segment (or oldest surviving) to
    the end of the log. Returns ``(queue, meta, info)`` where info
    counts segments/records consumed and torn tails skipped. Safe on
    an empty/missing journal (fresh cluster -> empty queue)."""
    queue = RequestQueue(lease_s=lease_s)
    meta = MetaTracker()
    info = {"segments": 0, "records": 0, "torn": 0}
    segs = segments(ha_dir)
    cur = read_cursor(ha_dir)
    start = segs.index(cur) if cur in segs else 0
    for name in segs[start:]:
        path = os.path.join(journal_dir(ha_dir), name)
        try:
            recs, _end, status = read_records(path)
        except FileNotFoundError:
            continue          # compacted away under us
        info["segments"] += 1
        for verb, rec in recs:
            apply_one(queue, meta, verb, rec)
        info["records"] += len(recs)
        if status != "ok":
            # a dead writer's torn tail: nothing after it in THIS
            # file can be valid; later segments are later epochs
            info["torn"] += 1
    queue.finalize_replay()
    if info["records"]:
        obs.count("fleet.journal.replayed", info["records"])
    if info["torn"]:
        obs.count("fleet.journal.torn", info["torn"])
    return queue, meta, info


class JournalTail:
    """Incremental reader — the warm standby's replica. ``poll()``
    applies whatever landed since the last call; an incomplete or
    suspect tail is retried (the writer may be mid-append) until a
    NEWER segment exists or ``finalize=True`` declares the writer
    dead, at which point the bad tail is counted torn and the reader
    moves on. Compaction deleting the reader's segment is handled by
    jumping to the cursor segment, whose leading snap record
    supersedes everything missed."""

    def __init__(self, ha_dir: str, lease_s: float = DEFAULT_LEASE_S):
        self.ha_dir = ha_dir
        self.queue = RequestQueue(lease_s=lease_s)
        self.meta = MetaTracker()
        self.records = 0
        self.torn = 0
        self._seg = None
        self._offset = 0

    def poll(self, finalize: bool = False) -> int:
        applied = 0
        while True:
            segs = segments(self.ha_dir)
            if not segs:
                return applied
            if self._seg is None or self._seg not in segs:
                cur = read_cursor(self.ha_dir)
                self._seg = cur if cur in segs else segs[0]
                self._offset = 0
            path = os.path.join(journal_dir(self.ha_dir), self._seg)
            try:
                recs, end, status = read_records(path, self._offset)
            except FileNotFoundError:
                self._seg = None
                continue
            for verb, rec in recs:
                apply_one(self.queue, self.meta, verb, rec)
            applied += len(recs)
            self.records += len(recs)
            self._offset = end
            idx = segs.index(self._seg)
            has_newer = idx + 1 < len(segs)
            if status == "ok":
                if not has_newer:
                    return applied
                self._seg = segs[idx + 1]
                self._offset = 0
                continue
            # partial/torn tail: only a dead writer leaves one for
            # good — wait unless the writer provably moved on (a
            # newer segment exists) or the caller says it is dead
            if not (has_newer or finalize):
                return applied
            self.torn += 1
            obs.count("fleet.journal.torn")
            if has_newer:
                self._seg = segs[idx + 1]
                self._offset = 0
                continue
            return applied

    def finish(self):
        """Final drain + promote-ready (queue, meta): the standby
        calls this once the lease says the leader is gone."""
        self.poll(finalize=True)
        self.queue.finalize_replay()
        return self.queue, self.meta
