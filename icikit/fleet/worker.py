"""Fleet engine worker process: ``python -m icikit.fleet.worker cfg.json``.

One OS process = one engine. The config file carries the coordinator
address, the engine's identity/role, the model recipe, and the serve
geometry. The model is built DETERMINISTICALLY from the recipe
(``init_params(jax.random.key(init_seed))`` over the preset config):
every worker — and the coordinator-side identity audit — holds bitwise
the same weights without any weight shipping, which is what makes the
fleet's exit bar ("every completed request bitwise identical to
single-request generate") checkable from the driving process.

Chaos arming rides the ordinary ``ICIKIT_CHAOS`` env var per worker
process (the soak arms ``die:fleet.engine.die`` on victims and
``corrupt:serve.kv.page`` on the defective-engine drill's target), and
observability rides ``ICIKIT_OBS`` (per-process trace/metrics files).

On a clean drain the worker prints one ``FLEET_WORKER_OK {json}``
line (the parent's structured handshake, like the multihost bring-up
worker's ``WORKER_OK``) and exits 0; an injected death propagates and
exits nonzero holding its leases — the reaper's problem, by design.
"""

from __future__ import annotations

import json
import sys


def build_model(spec: dict):
    """``(params, mesh, cfg)`` from a model recipe dict — shared by
    workers and the coordinator-side audit so both construct bitwise
    identical weights. Keys: ``preset`` (bench.train.PRESETS name),
    ``overrides`` (TransformerConfig field overrides, e.g. max_seq),
    ``compute_dtype``, ``decode_quant``, ``dp``/``tp``,
    ``init_seed``."""
    import jax

    from icikit.bench.train import PRESETS
    from icikit.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from icikit.models.transformer.model import make_model_mesh

    over = dict(PRESETS[spec.get("preset", "tiny")])
    over.update(spec.get("overrides") or {})
    if spec.get("compute_dtype"):
        over["compute_dtype"] = spec["compute_dtype"]
    cfg = TransformerConfig(
        **over, decode_quant=spec.get("decode_quant", "none"))
    mesh = make_model_mesh(dp=int(spec.get("dp", 1)),
                           tp=int(spec.get("tp", 1)), sp=1)
    params = init_params(
        jax.random.key(int(spec.get("init_seed", 0))), cfg, mesh)
    return params, mesh, cfg


def run_worker(config: dict) -> dict:
    from icikit import obs
    from icikit.fleet.roles import EngineWorker, engine_stats
    from icikit.serve.engine import ServeConfig

    tele = None
    tcfg = config.get("telemetry")
    if tcfg:
        # fleet obs plane armed: local trace buffer + metrics feed the
        # forwarder, which ships deltas to the coordinator's collector
        # on its own connection — started BEFORE the engine so compile
        # and admission telemetry is captured too
        from icikit.fleet.telemetry import TelemetryForwarder
        obs.enable_metrics()
        obs.start_tracing()
        client = None
        if tcfg.get("ha_dir"):
            # HA fleet: forward to whoever currently leads — the
            # lease-resolving client retargets across failovers, and
            # the forwarder re-handshakes the clock on send failure
            from icikit.fleet.ha import LeaderClient
            client = LeaderClient(tcfg["ha_dir"],
                                  resolve_timeout_s=2.0)
        tele = TelemetryForwarder(
            tuple(tcfg["addr"]) if tcfg.get("addr") else None,
            source=config["engine_id"], role=config["role"],
            client=client,
            flush_s=float(tcfg.get("flush_s", 0.25))).start()
    params, mesh, cfg = build_model(config.get("model") or {})
    serve_cfg = ServeConfig(**(config.get("serve") or {}))
    worker = EngineWorker(tuple(config["addr"])
                          if config.get("addr") else None,
                          config["engine_id"], config["role"],
                          params, mesh, cfg, serve_cfg,
                          rewarm=bool(config.get("rewarm")),
                          ha_dir=config.get("ha_dir"),
                          token=config.get("token"))
    try:
        completed = worker.run(
            max_steps=config.get("max_steps"))
    finally:
        worker.close()
        if tele is not None:
            tele.stop()
    out = {"completed": completed, **engine_stats(worker)}
    if tele is not None:
        out["telemetry"] = tele.stats()
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m icikit.fleet.worker CONFIG.json",
              file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        config = json.load(f)
    stats = run_worker(config)
    print("FLEET_WORKER_OK " + json.dumps(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
