"""Fleet engine worker process: ``python -m icikit.fleet.worker cfg.json``.

One OS process = one engine. The config file carries the coordinator
address, the engine's identity/role, the model recipe, and the serve
geometry. The model is built DETERMINISTICALLY from the recipe
(``init_params(jax.random.key(init_seed))`` over the preset config):
every worker — and the coordinator-side identity audit — holds bitwise
the same weights without any weight shipping, which is what makes the
fleet's exit bar ("every completed request bitwise identical to
single-request generate") checkable from the driving process.

Chaos arming rides the ordinary ``ICIKIT_CHAOS`` env var per worker
process (the soak arms ``die:fleet.engine.die`` on victims and
``corrupt:serve.kv.page`` on the defective-engine drill's target), and
observability rides ``ICIKIT_OBS`` (per-process trace/metrics files).

On a clean drain the worker prints one ``FLEET_WORKER_OK {json}``
line (the parent's structured handshake, like the multihost bring-up
worker's ``WORKER_OK``) and exits 0; an injected death propagates and
exits nonzero holding its leases — the reaper's problem, by design.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading

# scale-up TTFT was weight-rebuild dominated (3.4 s in r18, CPU):
# every joiner re-derived the SAME deterministic weights from the
# recipe. Two cache layers fix it without ever shipping weights:
# an in-process memo (the bench driver + test fixtures rebuild one
# recipe many times), and an on-disk host-array cache shared between
# worker processes (``ICIKIT_WEIGHT_CACHE``) so a joiner skips the
# init computation entirely. Both are keyed by the canonical recipe
# JSON; the disk payload carries a content digest re-verified at
# load — a torn or rotten cache file falls back to the honest
# rebuild, never into wrong weights (recompute beats misread).
_BUILD_MEMO: dict = {}
_BUILD_LOCK = threading.Lock()
_WEIGHT_FORMAT = 1


def _spec_key(spec: dict) -> str:
    return json.dumps(spec or {}, sort_keys=True)


def _weights_digest(host_arrays) -> str:
    h = hashlib.blake2b(digest_size=16)
    for a in host_arrays:
        h.update(a.tobytes())
    return h.hexdigest()


def _weight_cache_path(cache_dir: str, key: str) -> str:
    tag = hashlib.blake2b(key.encode(), digest_size=12).hexdigest()
    return os.path.join(cache_dir, f"weights-{tag}.npz")


def _load_cached_params(path: str, shapes_tree):
    """Rebuild the params pytree from a cached host-array file, or
    None when the file is absent/torn/rotten/shape-mismatched (any
    failure means rebuild — the file is removed so the next spawn
    doesn't re-trip)."""
    import numpy as np

    import jax

    if not os.path.exists(path):
        return None
    leaves, treedef = jax.tree_util.tree_flatten(shapes_tree)
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"].tobytes()))
            if (meta.get("format") != _WEIGHT_FORMAT
                    or meta.get("n") != len(leaves)):
                raise ValueError("weight cache layout mismatch")
            arrs = [z[f"a{i}"] for i in range(len(leaves))]
        if _weights_digest(arrs) != meta.get("digest"):
            raise ValueError("weight cache digest mismatch")
        for a, leaf in zip(arrs, leaves):
            if (tuple(a.shape) != tuple(leaf.shape)
                    or a.dtype != leaf.dtype):
                raise ValueError("weight cache leaf mismatch")
    except Exception:  # noqa: BLE001 - any rot -> rebuild honestly
        try:
            os.unlink(path)
        except OSError:
            pass
        return None
    return jax.tree_util.tree_unflatten(
        treedef, [jax.device_put(a) for a in arrs])


def _save_cached_params(path: str, params) -> None:
    import numpy as np

    import jax

    host = [np.asarray(x) for x in
            jax.tree_util.tree_leaves(params)]
    meta = json.dumps({"format": _WEIGHT_FORMAT, "n": len(host),
                       "digest": _weights_digest(host)}).encode()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, meta=np.frombuffer(meta, np.uint8),
                     **{f"a{i}": a for i, a in enumerate(host)})
        os.replace(tmp, path)   # last-writer-wins: identical content
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def build_model(spec: dict, weight_cache: str | None = None):
    """``(params, mesh, cfg)`` from a model recipe dict — shared by
    workers and the coordinator-side audit so both construct bitwise
    identical weights. Keys: ``preset`` (bench.train.PRESETS name),
    ``overrides`` (TransformerConfig field overrides, e.g. max_seq),
    ``compute_dtype``, ``decode_quant``, ``dp``/``tp``,
    ``init_seed``. ``weight_cache`` (or ``ICIKIT_WEIGHT_CACHE``)
    names a directory of cached host arrays for cross-process spawn
    acceleration; determinism is unaffected either way because the
    cache stores exactly the bytes the recipe derives."""
    key = _spec_key(spec)
    with _BUILD_LOCK:
        hit = _BUILD_MEMO.get(key)
    if hit is not None:
        return hit

    import jax

    from icikit.bench.train import PRESETS
    from icikit.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from icikit.models.transformer.model import make_model_mesh

    over = dict(PRESETS[spec.get("preset", "tiny")])
    over.update(spec.get("overrides") or {})
    if spec.get("compute_dtype"):
        over["compute_dtype"] = spec["compute_dtype"]
    cfg = TransformerConfig(
        **over, decode_quant=spec.get("decode_quant", "none"))
    dp, tp = int(spec.get("dp", 1)), int(spec.get("tp", 1))
    mesh = make_model_mesh(dp=dp, tp=tp, sp=1)
    init_key = jax.random.key(int(spec.get("init_seed", 0)))
    cache_dir = weight_cache or os.environ.get("ICIKIT_WEIGHT_CACHE")
    params = None
    path = None
    if cache_dir and dp == 1 and tp == 1:
        # single-device placement only: a sharded pytree's layout is
        # the mesh's business, not a flat npz's
        os.makedirs(cache_dir, exist_ok=True)
        path = _weight_cache_path(cache_dir, key)
        try:
            # abstract trace only — the treedef + leaf shapes the
            # cached flat arrays are validated against, at zero FLOPs
            shapes = jax.eval_shape(
                lambda k: init_params(k, cfg, mesh), init_key)
            params = _load_cached_params(path, shapes)
        except Exception:  # noqa: BLE001 - cache is best-effort
            params = None
    if params is None:
        params = init_params(init_key, cfg, mesh)
        if path is not None:
            _save_cached_params(path, params)
    out = (params, mesh, cfg)
    with _BUILD_LOCK:
        _BUILD_MEMO[key] = out
    return out


def run_worker(config: dict) -> dict:
    from icikit import obs
    from icikit.fleet.roles import EngineWorker, engine_stats
    from icikit.serve.engine import ServeConfig

    tele = None
    tcfg = config.get("telemetry")
    if tcfg:
        # fleet obs plane armed: local trace buffer + metrics feed the
        # forwarder, which ships deltas to the coordinator's collector
        # on its own connection — started BEFORE the engine so compile
        # and admission telemetry is captured too
        from icikit.fleet.telemetry import TelemetryForwarder
        obs.enable_metrics()
        obs.start_tracing()
        client = None
        if tcfg.get("ha_dir"):
            # HA fleet: forward to whoever currently leads — the
            # lease-resolving client retargets across failovers, and
            # the forwarder re-handshakes the clock on send failure
            from icikit.fleet.ha import LeaderClient
            client = LeaderClient(tcfg["ha_dir"],
                                  resolve_timeout_s=2.0)
        tele = TelemetryForwarder(
            tuple(tcfg["addr"]) if tcfg.get("addr") else None,
            source=config["engine_id"], role=config["role"],
            client=client,
            flush_s=float(tcfg.get("flush_s", 0.25))).start()
    params, mesh, cfg = build_model(
        config.get("model") or {},
        weight_cache=config.get("weight_cache"))
    serve_cfg = ServeConfig(**(config.get("serve") or {}))
    worker = EngineWorker(tuple(config["addr"])
                          if config.get("addr") else None,
                          config["engine_id"], config["role"],
                          params, mesh, cfg, serve_cfg,
                          rewarm=bool(config.get("rewarm")),
                          ha_dir=config.get("ha_dir"),
                          token=config.get("token"))
    try:
        completed = worker.run(
            max_steps=config.get("max_steps"))
    finally:
        worker.close()
        if tele is not None:
            tele.stop()
    out = {"completed": completed, **engine_stats(worker)}
    if tele is not None:
        out["telemetry"] = tele.stats()
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m icikit.fleet.worker CONFIG.json",
              file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        config = json.load(f)
    stats = run_worker(config)
    print("FLEET_WORKER_OK " + json.dumps(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
