"""``python -m icikit`` — discovery surface.

Prints the registered algorithm families (the runtime answer to the
reference's compile-time ``#define`` selection, SURVEY.md §5.6), the
visible devices, and the CLI entry points. The reference required
reading three Makefiles and the source to learn what could run; here
one command lists every selectable variant.
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    # Importing the family modules populates the registry.
    import icikit.models.sort  # noqa: F401
    import icikit.parallel  # noqa: F401
    from icikit import __version__
    from icikit.utils.registry import list_algorithms

    print(f"icikit {__version__} — TPU-native parallel-computing "
          "framework\n")
    print("Algorithm families (runtime-selectable; 'xla' = the native "
          "ICI collective playing the vendor-MPI role):")
    for family in list_algorithms():
        algs = ", ".join(sorted(list_algorithms(family)))
        print(f"  {family:<14} {algs}")
    try:
        import jax
        devs = jax.devices()
        print(f"\nDevices: {len(devs)} x {devs[0].platform} "
              f"({devs[0].device_kind})")
    except Exception as e:  # no backend in this environment
        print(f"\nDevices: unavailable ({e})")
    print("""
CLI entry points:
  python -m icikit.bench.run        collective sweep (--family, --simulate)
  python -m icikit.bench.sort       the four-sort study
  python -m icikit.bench.attention  dense/flash/ring/ulysses/zigzag
  python -m icikit.bench.train      training tokens/s + MFU
  python -m icikit.bench.decode     inference tokens/s
  python -m icikit.bench.scaling    strong scaling over device counts
  python -m icikit.bench.northstar  every BASELINE.md target
  python -m icikit.bench.report     render JSONL records to markdown
  python -m icikit.models.transformer.train   end-to-end LM trainer
  python -m icikit.models.solitaire.run       dynamic-load-balancing study""")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
