"""Paged KV cache: fixed-size blocks over one preallocated buffer.

The decode stack so far allocates one contiguous ``(total,)`` cache per
generate call, sized for the worst case — which is exactly what a
multi-request engine cannot afford: requests arrive with unknown output
lengths, and reserving max-length contiguous stripes per request either
caps concurrency at a handful of rows or wastes most of the buffer on
padding. This module is the vLLM/PagedAttention move specialized to the
repo's decode core: the cache is **one** preallocated arena of
fixed-size *blocks* (``block_size`` token columns each), requests own
*block tables* (ordered lists of block ids), and the engine's attention
gathers each row's blocks back into a contiguous view under a per-row
causal mask — so physical placement is arbitrary while the math stays
the ``_DecodeCtx`` math, token-identically.

Two layers, deliberately separable:

- :class:`BlockAllocator` — pure host-side metadata: a free list over
  block ids plus per-request block tables. No device state, so the
  property/fuzz suite (``tests/test_kvpool.py``) can hammer random
  alloc/extend/free interleavings and assert the invariants (live
  blocks never alias, the free list conserves capacity, exhaustion
  raises :class:`PoolExhausted` without partial allocation) at high
  iteration counts.
- :class:`KVPool` — the device arena: per-layer K and V buffers of
  shape ``(dp, n_blocks + 1, block_size, kv_heads, d_head)`` sharded
  ``P(dp, None, None, tp, None)``, one :class:`BlockAllocator` per dp
  shard (rows on shard *s* allocate from shard *s*'s block space), and
  occupancy/fragmentation gauges on the obs bus.

Block 0 of every shard is the **trash block**: engine rows that are
inactive (empty slots) still execute the step program — their writes
are routed to block 0, whose contents are garbage by contract and are
never read unmasked. Allocations therefore hand out ids from
``[1, n_blocks]``.

Integrity: the pool can remember a checksum per *sealed* block (every
slot committed — the engine seals block ``j`` of a request once its
committed frontier passes ``(j + 1) * block_size``) and re-verify the
request's sealed blocks later; a mismatch is the detection mechanism
behind the KV-page corruption chaos drill (a corrupted page fails its
*owning* request only — co-batched requests never gather it).
"""

from __future__ import annotations

import collections
import hashlib
import threading

from icikit import obs


class PoolExhausted(RuntimeError):
    """The free list cannot satisfy an allocation.

    Loud by design: silent admission of a request the pool cannot hold
    would stall every co-batched request behind an un-extendable row.
    The engine's policy on catching this is preempt-and-requeue, not
    crash — but the *allocator* never hands out partial allocations.
    """

    def __init__(self, requested: int, free: int, capacity: int):
        super().__init__(
            f"KV pool exhausted: requested {requested} blocks, "
            f"{free} free of {capacity}")
        self.requested = requested
        self.free = free
        self.capacity = capacity


class BlockAllocator:
    """Free-list allocator over ``n_blocks`` fixed-size blocks.

    Block ids are ``1..n_blocks`` (0 is the engine's trash block and is
    never allocated). ``alloc``/``ensure`` are all-or-nothing: on
    exhaustion they raise :class:`PoolExhausted` with the allocator
    state unchanged. Thread-safe — the engine is single-threaded today,
    but the scheduler discipline elsewhere in this repo (``_LeaseQueue``)
    is that shared metadata takes a lock rather than an assumption.
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.capacity = n_blocks
        self.block_size = block_size
        self._free = collections.deque(range(1, n_blocks + 1))
        self._tables: dict = {}          # owner -> list[int]
        self._lock = threading.Lock()

    # -- queries -----------------------------------------------------

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_used(self) -> int:
        return self.capacity - self.n_free

    def owners(self) -> tuple:
        with self._lock:
            return tuple(self._tables)

    def table(self, owner) -> tuple:
        """The owner's block table (ordered; () for unknown owners)."""
        with self._lock:
            return tuple(self._tables.get(owner, ()))

    # -- mutation ----------------------------------------------------

    def alloc(self, owner, n: int) -> tuple:
        """Append ``n`` fresh blocks to ``owner``'s table; returns the
        new block ids. All-or-nothing on exhaustion."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        with self._lock:
            if n > len(self._free):
                raise PoolExhausted(n, len(self._free), self.capacity)
            got = [self._free.popleft() for _ in range(n)]
            self._tables.setdefault(owner, []).extend(got)
        return tuple(got)

    def ensure(self, owner, n_tokens: int) -> tuple:
        """Grow ``owner``'s table until it covers ``n_tokens`` cache
        positions; returns the blocks *added* (possibly ())."""
        need = -(-n_tokens // self.block_size)  # ceil
        have = len(self._tables.get(owner, ()))
        return self.alloc(owner, max(0, need - have)) if need > have \
            else ()

    def free(self, owner) -> int:
        """Release every block owned by ``owner`` back to the free
        list; returns how many. Unknown owners free 0 (idempotent —
        a retried eviction must not corrupt the free list)."""
        with self._lock:
            blocks = self._tables.pop(owner, [])
            self._free.extend(blocks)
            return len(blocks)


def _page_digest(arrays) -> str:
    """Checksum of one block's K and V content across layers (host
    bytes in layer order) — the sealed-page integrity fingerprint."""
    h = hashlib.blake2b(digest_size=16)
    for a in arrays:
        h.update(a.tobytes())
    return h.hexdigest()


class KVPool:
    """The device arena + per-dp-shard allocators + obs gauges.

    ``kc``/``vc`` are per-layer tuples of jax arrays, each of global
    shape ``(dp, n_blocks + 1, block_size, kv_heads, d_head)`` sharded
    ``P(dp, None, None, tp, None)`` — engine step programs carry them
    as carry-style inputs/outputs (the decode.py cache discipline) and
    write them back via :meth:`update`.

    ``quant`` selects the arena set (the int8 serving path, DECODE.md
    "Quantized decode"):

    - ``"none"`` — the historical compute-dtype arenas only;
    - ``"int8"`` — int8 arenas ``qkc``/``qvc`` plus per-slot fp32
      *scale pages* ``ksc``/``vsc`` of shape ``(dp, n_blocks + 1,
      block_size, kv_heads)``; **no** high-precision KV arena exists
      on this path (``make check`` lints the invariant);
    - ``"mixed"`` — both sets over ONE allocator and one block table
      per request: a block id addresses the same slot in every arena,
      each row reads only its own side, so fp32 co-batched requests
      are bitwise untouched by int8 neighbors (the containment pin).

    Sealing checksums the payload a request actually serves from: the
    int8 side hashes the quantized blocks AND their scale pages (a
    flipped scale corrupts tokens exactly like a flipped int8 byte).
    """

    SIDES = ("fp", "q8")

    def __init__(self, cfg, mesh, n_blocks: int, block_size: int,
                 quant: str = "none"):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from icikit.models.transformer.model import DP_AXIS, TP_AXIS

        if quant not in ("none", "int8", "mixed"):
            raise ValueError(f"unknown pool quant {quant!r} "
                             "(known: none, int8, mixed)")
        self.cfg = cfg
        self.mesh = mesh
        self.n_blocks = n_blocks
        self.block_size = block_size
        self.quant = quant
        self.dp = mesh.shape[DP_AXIS]
        kv_heads = cfg.n_kv_heads or cfg.n_heads
        shape = (self.dp, n_blocks + 1, block_size, kv_heads, cfg.d_head)
        sshape = shape[:-1]
        sh = NamedSharding(mesh, P(DP_AXIS, None, None, TP_AXIS, None))
        ssh = NamedSharding(mesh, P(DP_AXIS, None, None, TP_AXIS))
        cdt = jnp.dtype(cfg.compute_dtype)

        def arena(shp, dtype, shd):
            # one DISTINCT buffer per layer/side: the engine donates
            # these into its step program (in-place pool updates), and
            # donation rejects aliased inputs
            return jax.device_put(jnp.zeros(shp, dtype), shd)

        L = cfg.n_layers
        self.kc = self.vc = None
        self.qkc = self.qvc = self.ksc = self.vsc = None
        if quant in ("none", "mixed"):
            self.kc = tuple(arena(shape, cdt, sh) for _ in range(L))
            self.vc = tuple(arena(shape, cdt, sh) for _ in range(L))
        if quant in ("int8", "mixed"):
            self.qkc = tuple(arena(shape, jnp.int8, sh)
                             for _ in range(L))
            self.qvc = tuple(arena(shape, jnp.int8, sh)
                             for _ in range(L))
            self.ksc = tuple(arena(sshape, jnp.float32, ssh)
                             for _ in range(L))
            self.vsc = tuple(arena(sshape, jnp.float32, ssh)
                             for _ in range(L))
        self.allocators = tuple(BlockAllocator(n_blocks, block_size)
                                for _ in range(self.dp))
        # (owner, shard, block_index_in_table) -> (side, digest) of the
        # sealed page's payload bytes across layers
        self._seals: dict = {}
        self._gauges()

    def _default_side(self) -> str:
        return "q8" if self.quant == "int8" else "fp"

    # -- device-side content -----------------------------------------

    def buffers(self) -> dict:
        """The arena pytree the step/prefill programs thread through
        (and donate): keys present depend on the quant mode."""
        out = {}
        if self.kc is not None:
            out["kc"], out["vc"] = self.kc, self.vc
        if self.qkc is not None:
            out.update(qkc=self.qkc, qvc=self.qvc,
                       ksc=self.ksc, vsc=self.vsc)
        return out

    def buffer_specs(self, pool_spec, scale_spec) -> dict:
        """PartitionSpec pytree matching :meth:`buffers`."""
        L = self.cfg.n_layers
        out = {}
        if self.kc is not None:
            out["kc"] = out["vc"] = (pool_spec,) * L
        if self.qkc is not None:
            out["qkc"] = out["qvc"] = (pool_spec,) * L
            out["ksc"] = out["vsc"] = (scale_spec,) * L
        return out

    def update(self, bufs: dict) -> None:
        """Install the step program's updated buffers (the engine calls
        this once per step with the program outputs)."""
        for k, v in bufs.items():
            setattr(self, k, tuple(v))

    def page_bytes(self, shard: int, page: int,
                   side: str | None = None) -> list:
        """Host copies of one physical block's payload for every layer
        — the integrity read-back (one device read per layer per call;
        sealing is a per-block, not per-step, event). The ``"q8"``
        side returns the QUANTIZED blocks plus their scale pages: the
        checksum covers exactly the bytes the request decodes from."""
        import numpy as np
        side = side or self._default_side()
        out = []
        for li in range(self.cfg.n_layers):
            if side == "fp":
                out.append(np.asarray(self.kc[li][shard, page]))
                out.append(np.asarray(self.vc[li][shard, page]))
            else:
                out.append(np.asarray(self.qkc[li][shard, page]))
                out.append(np.asarray(self.qvc[li][shard, page]))
                out.append(np.asarray(self.ksc[li][shard, page]))
                out.append(np.asarray(self.vsc[li][shard, page]))
        return out

    def poke_page(self, shard: int, page: int, layer: int,
                  array, side: str | None = None) -> None:
        """Overwrite one physical K block's content (the chaos drill's
        write-back path — a deterministic stand-in for an in-memory
        bit flip)."""
        import jax.numpy as jnp
        side = side or self._default_side()
        attr = "kc" if side == "fp" else "qkc"
        bufs = list(getattr(self, attr))
        bufs[layer] = bufs[layer].at[shard, page].set(
            jnp.asarray(array, bufs[layer].dtype))
        setattr(self, attr, tuple(bufs))

    def read_page(self, shard: int, page: int, layer: int,
                  side: str | None = None):
        """One K block's host copy (the chaos drill's read side)."""
        import numpy as np
        side = side or self._default_side()
        src = self.kc if side == "fp" else self.qkc
        return np.asarray(src[layer][shard, page])

    # -- sealing / integrity -----------------------------------------

    def seal(self, owner, shard: int, block_index: int, page: int,
             side: str | None = None) -> None:
        """Record the checksum of a just-completed (fully committed)
        block so :meth:`verify` can detect later corruption."""
        side = side or self._default_side()
        self._seals[(owner, shard, block_index)] = (
            side, _page_digest(self.page_bytes(shard, page, side)))

    def verify(self, owner, shard: int) -> list:
        """Re-hash every sealed block of ``owner`` against its recorded
        digest; returns the list of block indices that FAIL (empty ==
        intact)."""
        table = self.allocators[shard].table(owner)
        bad = []
        for (o, s, bi), (side, digest) in self._seals.items():
            if o != owner or s != shard:
                continue
            if bi >= len(table):
                continue
            if _page_digest(
                    self.page_bytes(s, table[bi], side)) != digest:
                bad.append(bi)
        return sorted(bad)

    def drop_seals(self, owner, shard: int) -> None:
        self._seals = {k: v for k, v in self._seals.items()
                       if not (k[0] == owner and k[1] == shard)}

    # -- bookkeeping shared with the engine --------------------------

    def free(self, owner, shard: int) -> int:
        """Release the owner's blocks (and seals) on one shard."""
        self.drop_seals(owner, shard)
        n = self.allocators[shard].free(owner)
        self._gauges()
        return n

    def ensure(self, owner, shard: int, n_tokens: int) -> tuple:
        added = self.allocators[shard].ensure(owner, n_tokens)
        if added:
            self._gauges()
        return added

    def occupancy(self) -> float:
        """Fraction of allocatable blocks currently owned (mean over
        dp shards)."""
        used = sum(a.n_used for a in self.allocators)
        return used / (self.n_blocks * self.dp)

    def fragmentation(self, used_tokens: dict) -> float:
        """Internal fragmentation: 1 − used-token-slots / allocated
        slots, given ``{(owner, shard): committed token count}``. Fixed
        blocks have no external fragmentation; the waste is the
        partially-filled tail block per request."""
        alloc_slots = sum(
            len(self.allocators[s].table(o)) * self.block_size
            for (o, s) in used_tokens)
        if not alloc_slots:
            return 0.0
        used = sum(min(v, len(self.allocators[s].table(o))
                       * self.block_size)
                   for (o, s), v in used_tokens.items())
        return 1.0 - used / alloc_slots

    def _gauges(self) -> None:
        obs.gauge("serve.kv.occupancy", self.occupancy())
        obs.gauge("serve.kv.blocks_free",
                  sum(a.n_free for a in self.allocators))
