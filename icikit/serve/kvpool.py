"""Paged KV cache: fixed-size blocks over one preallocated buffer.

The decode stack so far allocates one contiguous ``(total,)`` cache per
generate call, sized for the worst case — which is exactly what a
multi-request engine cannot afford: requests arrive with unknown output
lengths, and reserving max-length contiguous stripes per request either
caps concurrency at a handful of rows or wastes most of the buffer on
padding. This module is the vLLM/PagedAttention move specialized to the
repo's decode core: the cache is **one** preallocated arena of
fixed-size *blocks* (``block_size`` token columns each), requests own
*block tables* (ordered lists of block ids), and the engine's attention
gathers each row's blocks back into a contiguous view under a per-row
causal mask — so physical placement is arbitrary while the math stays
the ``_DecodeCtx`` math, token-identically.

Round 11 grows the pool **prefix-aware** (the vLLM block-sharing /
SGLang radix-reuse move on this machinery):

- blocks are **refcounted**: ``share`` attaches an existing block to a
  second owner's table instead of copying it, ``release`` decrements
  and only a refcount-0 block leaves circulation. K/V at a position is
  a pure function of the token prefix, so two requests whose prompts
  agree on a block-aligned prefix can serve attention from the *same*
  physical pages;
- a **content-addressed index** maps chain hashes of full-block token
  runs to resident pages. The chain (``h_j = H(h_{j-1}, tokens_j,
  side)``) makes a flat dict equivalent to a radix trie over block
  paths: an entry's key commits to its entire prefix, so the longest
  cached prefix is the longest chain of consecutive hits. Entries are
  side-aware — an int8 block never serves an fp reader;
- **copy-on-write**: a block with refcount > 1 is immutable; a writer
  must ``cow`` it first (fresh page, device copy, table swap). A
  partially-filled tail block is never shared — only full, finalized
  blocks enter the index;
- refcount-0 blocks whose content is indexed are retained in an **LRU
  cached set** rather than freed; allocation takes free pages first
  and evicts cached pages (dropping their index entries) only under
  pressure. :class:`PoolExhausted` now means live + cached together
  cannot satisfy the request.

Round 16 grows the pool **tiered** (the SGLang/Mooncake multi-tier
move rebuilt on this allocator — ROADMAP item 2): the block state
machine gains a fourth state, **spilled**. When allocation pressure
would evict an indexed LRU page, a pool with a host tier attached
(``host_blocks > 0``) copies the page's arena bytes (and, on the q8
side, its scale pages) out to host memory *before* the device page is
reused, and demotes the index entry to *spilled* instead of dropping
it — the content survives, only its residence changed. A prefix
lookup that lands on a spilled chain swaps the blocks back in through
``restore_block``: a fresh device page is adopted, the payload's
content digest (recorded at capture, before the bytes ever left the
arena) is **re-verified at swap-in**, and a mismatch quarantines the
content from every tier — a corrupt swap-in is recomputed, never
trusted. Below the host tier sits an optional persistent
content-addressed store (``serve/store.py``), fed OFF the serving hot
path — host-tier LRU overflow demotes entries to disk (the device ->
host -> disk cascade) and the engine flushes every surviving sealed
block at queue drain — so a *restarted* engine re-warms from disk
instead of recomputing prefill (same swap-in verify, same
quarantine). Conservation: device pages still partition exactly into
free / cached / live (free + cached + live == capacity); spilled
entries hold **no device page** — they are reclaimable *capacity*
(bounded by ``host_blocks``, LRU) but not device-resident, which is
why ``occupancy`` stays live-only and :class:`PoolExhausted` reports
the spilled count distinctly.

Block 0 of every shard is the **trash block**: engine rows that are
inactive (empty slots, padded chunk positions) still execute the step
program — their writes are routed to block 0, whose contents are
garbage by contract and are never read unmasked. Allocations therefore
hand out ids from ``[1, n_blocks]``.

Integrity: sealed-page checksums are keyed by ``(shard, page)`` — a
property of the *content*, not of one owner — so every request whose
table maps a shared page re-verifies the same digest, and one
corrupted shared page is detected by every reader. The engine
quarantines such a page from the index so retries re-prefill on fresh
blocks (drilled in ``tests/test_serve_chaos.py``).
"""

from __future__ import annotations

import collections
import hashlib
import threading

import numpy as np

from icikit import chaos, obs

# tier-boundary probe sites (r16): spill = the eviction-time copy-out
# to the host tier (corrupt drills in-host-memory rot AFTER the digest
# was recorded, so the swap-in verify must catch it); restore = the
# swap-in boundary (delay/die — a die here is an engine crash mid-
# restore, healed by lease reissue). The disk tier's sites live in
# icikit/serve/store.py.
chaos.register_site("serve.kv.spill", "serve.kv.restore")


class PoolExhausted(RuntimeError):
    """The free list + evictable cached blocks cannot satisfy an
    allocation.

    Loud by design: silent admission of a request the pool cannot hold
    would stall every co-batched request behind an un-extendable row.
    The engine's policy on catching this is preempt-and-requeue, not
    crash — but the *allocator* never hands out partial allocations.
    ``free`` counts every DEVICE-reclaimable page (free list +
    refcount-0 cached): only *live* blocks are unreclaimable.
    ``spilled`` content is reported distinctly — a spilled block is
    reclaimable *capacity* (its content survives in the host tier) but
    holds no device page, so conflating it with ``free`` would
    overstate what an allocation can actually take.
    """

    def __init__(self, requested: int, free: int, capacity: int,
                 spilled: int = 0):
        msg = (f"KV pool exhausted: requested {requested} blocks, "
               f"{free} reclaimable of {capacity} device-resident")
        if spilled:
            msg += (f" ({spilled} more spilled to the host tier — "
                    "reclaimable capacity, not device pages)")
        super().__init__(msg)
        self.requested = requested
        self.free = free
        self.capacity = capacity
        self.spilled = spilled


def chain_seed(side: str = "fp") -> bytes:
    """The chain-hash seed (block -1 state) for one arena side."""
    return side.encode()


def chain_extend(prev: bytes, tokens) -> tuple:
    """Extend a chain-hash state by ONE full block of tokens; returns
    ``(hexdigest, digest)`` — the index key and the next chain state.
    O(block) per call, which is what lets the engine finalize block
    ``j`` without re-hashing blocks ``0..j-1``."""
    h = hashlib.blake2b(digest_size=16)
    h.update(prev)
    h.update(np.ascontiguousarray(
        np.asarray(tokens, np.int32)).tobytes())
    return h.hexdigest(), h.digest()


def block_hashes(tokens, block_size: int, side: str = "fp") -> list:
    """Chain hashes of every FULL block of ``tokens`` — the
    content-address of the prefix index. ``h_j`` commits to blocks
    ``0..j`` (and the arena side), so a dict over these hashes is a
    radix trie over block paths: matching ``h_j`` implies the whole
    prefix matched."""
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32)).reshape(-1)
    out = []
    prev = chain_seed(side)
    for j in range(toks.size // block_size):
        hx, prev = chain_extend(
            prev, toks[j * block_size:(j + 1) * block_size])
        out.append(hx)
    return out


class BlockAllocator:
    """Refcounted free-list allocator over ``n_blocks`` fixed blocks.

    Block ids are ``1..n_blocks`` (0 is the engine's trash block and is
    never allocated). Mutations are all-or-nothing: on exhaustion they
    raise :class:`PoolExhausted` with the allocator state unchanged.
    Thread-safe — the engine is single-threaded today, but the
    scheduler discipline elsewhere in this repo (``_LeaseQueue``) is
    that shared metadata takes a lock rather than an assumption.

    Every DEVICE page is in exactly one of three places (their counts
    conserve: free + cached + live == capacity, fuzz-pinned):

    - **live** — refcount >= 1, mapped by >= 1 block table;
    - **cached** — refcount 0 but content-indexed (``register``), held
      in LRU order awaiting either a ``share`` (cache hit revives it)
      or eviction under allocation pressure;
    - **free** — on the free list, content unknown.

    With a host tier attached (``host_blocks > 0`` and ``spill_cb``
    set) there is a fourth CONTENT state, **spilled**: an evicted
    cached page whose payload the pool captured to host memory before
    the device page was reused. A spilled entry is a chain hash with
    no device page — it leaves the index at eviction and re-enters it
    through ``adopt`` (restore: fresh page, payload re-verified by the
    pool) or through ``register`` (a recompute raced the restore; the
    stale host copy is dropped — content-addressing makes them
    identical, but one source of truth is the rule). The spilled set
    is LRU-bounded at ``host_blocks``; overflow drops the oldest entry
    via ``drop_cb`` (whose payload may still live in the disk tier
    below — that lookup is the pool's, not the allocator's).
    """

    def __init__(self, n_blocks: int, block_size: int,
                 host_blocks: int = 0):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if host_blocks < 0:
            raise ValueError(
                f"host_blocks must be >= 0, got {host_blocks}")
        self.capacity = n_blocks
        self.block_size = block_size
        self.host_blocks = host_blocks
        # tier callbacks (set by KVPool when a host tier is attached):
        # spill_cb([(page, h), ...]) -> set of hashes captured to the
        # host tier (an uncaptured entry drops like the untiered
        # path) — ONE call per eviction batch so the capture is one
        # device dispatch, not one per page; drop_cb(h) releases a
        # captured payload (LRU overflow, restore consumption, or
        # re-registration). Called UNDER the allocator lock: both are
        # dispatch + dict ops (no host sync), and the engine's pool
        # mutations are single-threaded by design — the lock is the
        # safety net, not a contention point.
        self.spill_cb = None
        self.drop_cb = None
        self._free = collections.deque(range(1, n_blocks + 1))
        self._tables: dict = {}          # owner -> list[int]
        self._refs: dict = {}            # page -> live refcount
        self._index: dict = {}           # chain hash -> page
        self._hash_of: dict = {}         # page -> chain hash
        # refcount-0 pages kept for reuse, LRU -> MRU order
        self._cached: collections.OrderedDict = collections.OrderedDict()
        # spilled CONTENT (no device page): chain hash -> True, LRU ->
        # MRU, bounded by host_blocks
        self._spilled: collections.OrderedDict = \
            collections.OrderedDict()
        # in-flight prefill announcements (r12 dedup): chain hash ->
        # announcing owner, for blocks an admitted request is
        # CURRENTLY computing but has not yet finalized/registered.
        # A concurrent identical/prefix admission that finds its next
        # needed hash here attaches as a WAITER instead of computing;
        # entries drain into the index via register() (which clears
        # them) or vanish with their owner via withdraw() — so a
        # waiter can never wait on content nobody will produce.
        self._inflight: dict = {}        # chain hash -> owner
        self._lock = threading.Lock()
        self.n_evictions = 0
        self.n_spills = 0
        self.n_restores = 0

    # -- queries -----------------------------------------------------

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_cached(self) -> int:
        with self._lock:
            return len(self._cached)

    @property
    def n_used(self) -> int:
        """LIVE blocks (refcount >= 1). Cached refcount-0 blocks are
        reclaimable on demand and do not count as used."""
        with self._lock:
            return len(self._refs)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs.get(page, 0)

    def owners(self) -> tuple:
        with self._lock:
            return tuple(self._tables)

    def table(self, owner) -> tuple:
        """The owner's block table (ordered; () for unknown owners)."""
        with self._lock:
            return tuple(self._tables.get(owner, ()))

    @property
    def n_spilled(self) -> int:
        with self._lock:
            return len(self._spilled)

    def indexed(self, h: str):
        """The page registered under chain hash ``h`` (None = miss)."""
        with self._lock:
            return self._index.get(h)

    def indexed_hashes(self) -> list:
        """Every chain hash with a registered page — the residency
        set fleet heartbeats summarize for cache-aware routing."""
        with self._lock:
            return list(self._index.keys())

    def spilled(self, h: str) -> bool:
        """Is ``h``'s content in the host spill tier (no device page)?"""
        with self._lock:
            return h in self._spilled

    # -- mutation ----------------------------------------------------

    def _take(self, n: int) -> list:
        """Pop ``n`` pages (free list first, then LRU-evict cached),
        lock held. All-or-nothing; evicted pages lose their index
        entry — with a host tier attached, their content SPILLS (the
        payload is captured to host memory via ``spill_cb`` *before*
        the device page is handed out for reuse, and the chain hash
        demotes to the spilled set instead of vanishing). Returns the
        pages; caller assigns refcounts."""
        if n > len(self._free) + len(self._cached):
            raise PoolExhausted(
                n, len(self._free) + len(self._cached), self.capacity,
                spilled=len(self._spilled))
        got = []
        while len(got) < n and self._free:
            got.append(self._free.popleft())
        evicted = []
        while len(got) < n:
            page, _ = self._cached.popitem(last=False)   # LRU victim
            h = self._hash_of.pop(page)
            del self._index[h]
            # counted here, EMITTED by the public callers once the
            # lock drops (the mark_dead discipline: a slow metrics
            # sink must never stall the allocation path)
            self.n_evictions += 1
            evicted.append((page, h))
            got.append(page)
        if (evicted and self.host_blocks > 0
                and self.spill_cb is not None):
            # ONE capture call for the whole eviction batch (the pool
            # snapshots every victim page in one device dispatch —
            # per-page capture calls were measured dominating the
            # admission path); returns the hashes actually captured
            captured = self.spill_cb(evicted)
            for _, h in evicted:
                if h not in captured:
                    continue
                self._spilled[h] = True
                self._spilled.move_to_end(h)
                self.n_spills += 1
            while len(self._spilled) > self.host_blocks:
                old, _ = self._spilled.popitem(last=False)
                if self.drop_cb is not None:
                    self.drop_cb(old)
        return got

    def alloc(self, owner, n: int) -> tuple:
        """Append ``n`` fresh exclusive blocks to ``owner``'s table;
        returns the new block ids. All-or-nothing on exhaustion; may
        evict LRU cached blocks under pressure."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        with self._lock:
            ev0 = self.n_evictions
            got = self._take(n)
            evicted = self.n_evictions - ev0
            for p in got:
                self._refs[p] = 1
            self._tables.setdefault(owner, []).extend(got)
        if evicted:
            obs.count("serve.kv.evictions", evicted)
        return tuple(got)

    def ensure(self, owner, n_tokens: int) -> tuple:
        """Grow ``owner``'s table until it covers ``n_tokens`` cache
        positions; returns the blocks *added* (possibly ())."""
        need = -(-n_tokens // self.block_size)  # ceil
        have = len(self._tables.get(owner, ()))
        return self.alloc(owner, max(0, need - have)) if need > have \
            else ()

    def share(self, owner, pages) -> None:
        """Append existing ``pages`` to ``owner``'s table, bumping
        refcounts — the cache-hit attach. A cached (refcount-0) page
        revives to live; pages must be live or cached (a free-list
        page has unknown content and cannot be shared)."""
        with self._lock:
            for p in pages:
                if self._refs.get(p, 0) == 0 and p not in self._cached:
                    raise ValueError(
                        f"cannot share page {p}: neither live nor "
                        "cached")
            t = self._tables.setdefault(owner, [])
            for p in pages:
                self._cached.pop(p, None)
                self._refs[p] = self._refs.get(p, 0) + 1
                t.append(p)

    def release(self, owner) -> tuple:
        """Drop every reference ``owner`` holds; returns ``(n_released,
        pages_freed)`` where ``pages_freed`` are the pages that left
        circulation entirely (refcount hit 0 and no index entry keeps
        them cached) — the pool drops their seals. Unknown owners
        release 0 (idempotent — a retried eviction must not corrupt
        the free list)."""
        freed = []
        with self._lock:
            pages = self._tables.pop(owner, [])
            # cache in REVERSE table order so the chain root lands at
            # the MRU end: LRU eviction then takes the deepest block
            # first, and a truncated chain stays walkable from its
            # root instead of orphaning its tail (see lookup)
            for p in reversed(pages):
                self._refs[p] -= 1
                if self._refs[p]:
                    continue
                del self._refs[p]
                if p in self._hash_of:
                    self._cached[p] = None      # MRU end
                else:
                    self._free.append(p)
                    freed.append(p)
        return len(pages), freed

    def free(self, owner) -> int:
        """Back-compat shim over :meth:`release` (single-owner call
        sites and the property suite predate sharing)."""
        return self.release(owner)[0]

    def cow(self, owner, index: int):
        """Copy-on-write guard for ``owner``'s table entry ``index``:
        a block mapped by other owners (refcount > 1) is swapped for a
        fresh exclusive page; returns ``(old_page, new_page)`` so the
        pool can copy the device bytes, or None when the block is
        already exclusive (no fork needed). The fork is NOT indexed —
        its content address stays with the original."""
        with self._lock:
            table = self._tables.get(owner)
            if table is None or not 0 <= index < len(table):
                raise ValueError(f"cow: no block {index} for {owner!r}")
            old = table[index]
            if self._refs[old] <= 1:
                return None
            ev0 = self.n_evictions
            [new] = self._take(1)
            evicted = self.n_evictions - ev0
            self._refs[old] -= 1
            self._refs[new] = 1
            table[index] = new
        if evicted:
            obs.count("serve.kv.evictions", evicted)
        return old, new

    # -- prefix index ------------------------------------------------

    def lookup(self, hashes) -> list:
        """Longest chain of consecutively-indexed pages for ``hashes``
        (the block-aligned cached prefix). Touches hits to MRU in
        DEEPEST-first order, leaving the chain ROOT most recent:
        lookup can only walk a chain from its root, so evicting a
        root orphans every deeper cached block of that prefix —
        victims must come leaf-first (the radix-cache discipline)."""
        out = []
        with self._lock:
            for h in hashes:
                p = self._index.get(h)
                if p is None:
                    break
                out.append(p)
            for p in reversed(out):
                if p in self._cached:
                    self._cached.move_to_end(p)
        return out

    def register(self, page: int, h: str) -> bool:
        """Content-address a LIVE page. First registration wins: a
        duplicate hash (same content already resident) or an
        already-hashed page is refused — the duplicate page simply
        stays anonymous and is freed on release. Either way any
        in-flight announcement of ``h`` is settled: the content is
        now findable through the index, so nobody should keep waiting
        on it."""
        with self._lock:
            self._inflight.pop(h, None)
            if h in self._index or page in self._hash_of:
                return False
            if self._refs.get(page, 0) < 1:
                raise ValueError(
                    f"register: page {page} is not live")
            # a recompute raced a spilled copy of the same content:
            # the device page wins (content-addressing guarantees the
            # two are bitwise identical, but the index must have ONE
            # source of truth per hash — a later restore overwriting
            # this registration would alias)
            if h in self._spilled:
                del self._spilled[h]
                if self.drop_cb is not None:
                    self.drop_cb(h, False)   # resident again: no demote
            self._index[h] = page
            self._hash_of[page] = h
            return True

    def adopt(self, owner, h: str):
        """Re-materialize spilled/persisted content ``h`` onto a fresh
        device page owned by ``owner`` — the allocator half of a
        restore (the pool verifies the payload digest BEFORE calling
        this, then writes the bytes after). The page comes out live
        (refcount 1), appended to the owner's table, and registered
        under ``h`` so the chain is index-resident again for every
        later sharer; any spilled entry for ``h`` is consumed (its
        host payload released via ``drop_cb``). Returns the page, or
        None when ``h`` is already index-resident (a recompute or a
        concurrent restore won the race — share that page instead).
        Raises :class:`PoolExhausted` like any allocation."""
        with self._lock:
            if h in self._index:
                return None
            ev0 = self.n_evictions
            [page] = self._take(1)
            evicted = self.n_evictions - ev0
            self._refs[page] = 1
            self._tables.setdefault(owner, []).append(page)
            self._index[h] = page
            self._hash_of[page] = h
            if h in self._spilled:
                del self._spilled[h]
                if self.drop_cb is not None:
                    self.drop_cb(h, False)   # consumed by the restore
            self.n_restores += 1
        if evicted:
            obs.count("serve.kv.evictions", evicted)
        return page

    def purge_spilled(self, h: str) -> bool:
        """Quarantine one spilled entry (the swap-in verify-failure
        path): the content leaves the host tier and no future lookup
        can plan a restore from it. Idempotent."""
        with self._lock:
            if h not in self._spilled:
                return False
            del self._spilled[h]
            if self.drop_cb is not None:
                # quarantine: the content is suspect — never demote it
                self.drop_cb(h, False)
            return True

    # -- in-flight prefill announcements (r12 dedup) -----------------

    def announce(self, owner, hashes) -> None:
        """Declare that ``owner`` is about to compute the blocks behind
        ``hashes`` (chain hashes of full prompt blocks, in order).
        First announcer wins per hash — a later identical admission is
        exactly the waiter the registry exists to create, and it must
        keep seeing the ORIGINAL announcement until the block lands in
        the index."""
        with self._lock:
            for h in hashes:
                if h not in self._index:
                    self._inflight.setdefault(h, owner)

    def withdraw(self, owner) -> None:
        """Drop every announcement ``owner`` still holds (eviction /
        preemption / completion cleanup) — waiters on those hashes
        stop waiting at their next poll and compute the blocks
        themselves. Idempotent."""
        with self._lock:
            stale = [h for h, o in self._inflight.items() if o == owner]
            for h in stale:
                del self._inflight[h]

    def announced(self, h: str) -> bool:
        """Is ``h`` currently being computed by some admitted row?"""
        with self._lock:
            return h in self._inflight

    def deregister(self, page: int) -> bool:
        """Remove a page's index entry (the corruption quarantine): no
        new request can share it, and once its refcount drains it goes
        to the free list instead of the cached set."""
        with self._lock:
            h = self._hash_of.pop(page, None)
            if h is None:
                return False
            del self._index[h]
            if page in self._cached:
                del self._cached[page]
                self._free.append(page)
            return True


_COPY_FN = None


def _page_copy(buf, shard: int, old: int, new: int):
    """Copy one physical page within an arena buffer via a donated
    jitted program: donation lets XLA update the buffer in place, so
    forking one block costs one page of traffic — not a full-arena
    materialization per layer per arena (jit caches one executable
    per (shape, dtype, sharding); indices are traced)."""
    global _COPY_FN
    if _COPY_FN is None:
        import jax

        def cp(b, s, o, n):
            zeros = (0,) * (b.ndim - 2)
            page = jax.lax.dynamic_slice(
                b, (s, o) + zeros, (1, 1) + b.shape[2:])
            return jax.lax.dynamic_update_slice(
                b, page, (s, n) + zeros)

        _COPY_FN = jax.jit(cp, donate_argnums=(0,))
    import jax.numpy as jnp
    i32 = jnp.int32
    return _COPY_FN(buf, i32(shard), i32(old), i32(new))


_SNAP_FNS: dict = {}


def _snap_width(n: int) -> int:
    """Pad an eviction batch to the next power of two (min 4): the
    snapshot program compiles once per (geometry, width), so variable
    batch sizes must bucket — padding gathers the trash page 0, whose
    snapshot is discarded."""
    w = 4
    while w < n:
        w *= 2
    return w


def _pages_snapshot(bufs_by_name: dict, shard: int, pages) -> dict:
    """Snapshot a batch of physical pages out of the arenas in ONE
    jitted dispatch (no donation — a pure read): returns arena name
    -> device array (L, width, *page_shape). The result is a
    consistent copy by jax immutability — later writes to (and
    donation of) the arenas cannot touch it — and nothing syncs to
    host here; ``_SpillBatch`` materializes lazily."""
    import jax
    import jax.numpy as jnp
    width = _snap_width(len(pages))
    pg = np.zeros(width, np.int32)
    pg[:len(pages)] = pages
    names = tuple(sorted(bufs_by_name))
    key = tuple((n, len(bufs_by_name[n]), bufs_by_name[n][0].shape,
                 str(bufs_by_name[n][0].dtype), width)
                for n in names)
    fn = _SNAP_FNS.get(key)
    if fn is None:
        def snap(bufs, s, p):
            return {n: jnp.stack([b[s][p] for b in bufs[n]])
                    for n in bufs}

        fn = _SNAP_FNS[key] = jax.jit(snap)
    return fn(dict(bufs_by_name), jnp.int32(shard),
              jnp.asarray(pg, jnp.int32))


class _SpillBatch:
    """One eviction batch's device-side snapshot, shared by every
    spilled page it captured: host materialization happens ONCE for
    the batch, on the first consumer's path."""

    def __init__(self, snaps: dict, names: tuple, n_layers: int):
        self.snaps = snaps
        self.names = names
        self.n_layers = n_layers
        self._np = None

    def settle(self) -> bool:
        """Materialize the snapshot to host bytes and release the
        device copies; returns False when already settled."""
        if self._np is not None:
            return False
        self._np = {n: np.asarray(a) for n, a in self.snaps.items()}
        self.snaps = None             # release the device copies
        return True

    def page(self, idx: int) -> list:
        self.settle()
        return [np.array(self._np[n][li, idx])
                for li in range(self.n_layers) for n in self.names]


_WRITE_FNS: dict = {}


def _pages_write(bufs_by_name: dict, shard: int, pages,
                 blocks_by_name: dict) -> dict:
    """Overwrite a batch of physical pages' content from host blocks
    (the restore path's arena write): ONE donated jitted scatter for
    the WHOLE run — every arena, every layer, all blocks in a single
    dispatch. Restoring a chunk-width run of blocks must cost less
    than recomputing it, and on CPU the per-dispatch overhead of
    per-block (or even per-arena) writes exceeds the tiny-model
    recompute it replaces — measured while scoping the r16 study.
    Callers pad short runs to a fixed width with page 0 — the trash
    block, whose contents are garbage by contract — so the compiled
    program count is one per (arena geometry, run width).

    ``bufs_by_name``: arena name -> per-layer buffer tuple (donated);
    ``blocks_by_name``: arena name -> ndarray (L, width, *page_shape).
    Returns the updated per-layer tuples by name."""
    import jax
    import jax.numpy as jnp
    names = tuple(sorted(bufs_by_name))
    key = tuple((n, len(bufs_by_name[n]), bufs_by_name[n][0].shape,
                 str(bufs_by_name[n][0].dtype),
                 blocks_by_name[n].shape) for n in names)
    fn = _WRITE_FNS.get(key)
    if fn is None:
        def wr(bufs, s, pg, blks):
            return {n: tuple(b.at[s, pg].set(blks[n][li])
                             for li, b in enumerate(bufs[n]))
                    for n in bufs}

        fn = _WRITE_FNS[key] = jax.jit(wr, donate_argnums=(0,))
    blocks = {n: jnp.asarray(blocks_by_name[n],
                             bufs_by_name[n][0].dtype)
              for n in names}
    return fn(dict(bufs_by_name), jnp.int32(shard),
              jnp.asarray(pages, jnp.int32), blocks)


def _page_digest(arrays) -> str:
    """Checksum of one block's K and V content across layers (host
    bytes in layer order) — the sealed-page integrity fingerprint. On
    the q8 side the array list interleaves the quantized payload AND
    its scale pages: a flipped scale corrupts decoded tokens exactly
    like a flipped int8 byte, so it must flip the digest too."""
    h = hashlib.blake2b(digest_size=16)
    for a in arrays:
        h.update(a.tobytes())
    return h.hexdigest()


class KVPool:
    """The device arena + per-dp-shard allocators + obs gauges.

    ``kc``/``vc`` are per-layer tuples of jax arrays, each of global
    shape ``(dp, n_blocks + 1, block_size, kv_heads, d_head)`` sharded
    ``P(dp, None, None, tp, None)`` — engine step programs carry them
    as carry-style inputs/outputs (the decode.py cache discipline) and
    write them back via :meth:`update`.

    ``quant`` selects the arena set (the int8 serving path, DECODE.md
    "Quantized decode"):

    - ``"none"`` — the historical compute-dtype arenas only;
    - ``"int8"`` — int8 arenas ``qkc``/``qvc`` plus per-slot fp32
      *scale pages* ``ksc``/``vsc`` of shape ``(dp, n_blocks + 1,
      block_size, kv_heads)``; **no** high-precision KV arena exists
      on this path (``make check`` lints the invariant);
    - ``"mixed"`` — both sets over ONE allocator and one block table
      per request: a block id addresses the same slot in every arena,
      each row reads only its own side, so fp32 co-batched requests
      are bitwise untouched by int8 neighbors (the containment pin).

    Sealing checksums the payload a request actually serves from: the
    int8 side hashes the quantized blocks AND their scale pages (a
    flipped scale corrupts tokens exactly like a flipped int8 byte).
    Seals are keyed ``(shard, page)`` — shared pages carry ONE digest
    every reader re-verifies.
    """

    SIDES = ("fp", "q8")

    def __init__(self, cfg, mesh, n_blocks: int, block_size: int,
                 quant: str = "none", host_blocks: int = 0,
                 store=None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from icikit.models.transformer.model import DP_AXIS, TP_AXIS

        if quant not in ("none", "int8", "mixed"):
            raise ValueError(f"unknown pool quant {quant!r} "
                             "(known: none, int8, mixed)")
        self.cfg = cfg
        self.mesh = mesh
        self.n_blocks = n_blocks
        self.block_size = block_size
        self.quant = quant
        self.dp = mesh.shape[DP_AXIS]
        kv_heads = cfg.n_kv_heads or cfg.n_heads
        shape = (self.dp, n_blocks + 1, block_size, kv_heads, cfg.d_head)
        sshape = shape[:-1]
        sh = NamedSharding(mesh, P(DP_AXIS, None, None, TP_AXIS, None))
        ssh = NamedSharding(mesh, P(DP_AXIS, None, None, TP_AXIS))
        cdt = jnp.dtype(cfg.compute_dtype)

        def arena(shp, dtype, shd):
            # one DISTINCT buffer per layer/side: the engine donates
            # these into its step program (in-place pool updates), and
            # donation rejects aliased inputs
            return jax.device_put(jnp.zeros(shp, dtype), shd)

        L = cfg.n_layers
        self.kc = self.vc = None
        self.qkc = self.qvc = self.ksc = self.vsc = None
        if quant in ("none", "mixed"):
            self.kc = tuple(arena(shape, cdt, sh) for _ in range(L))
            self.vc = tuple(arena(shape, cdt, sh) for _ in range(L))
        if quant in ("int8", "mixed"):
            self.qkc = tuple(arena(shape, jnp.int8, sh)
                             for _ in range(L))
            self.qvc = tuple(arena(shape, jnp.int8, sh)
                             for _ in range(L))
            self.ksc = tuple(arena(sshape, jnp.float32, ssh)
                             for _ in range(L))
            self.vsc = tuple(arena(sshape, jnp.float32, ssh)
                             for _ in range(L))
        self.allocators = tuple(
            BlockAllocator(n_blocks, block_size,
                           host_blocks=host_blocks)
            for _ in range(self.dp))
        # tiered KV (r16): the host spill tier — (shard, chain hash)
        # -> (side, digest, payload arrays) captured at eviction —
        # and the optional persistent content-addressed block store
        # beneath it (serve/store.py). The allocators' spilled-set LRU
        # is the ONE bookkeeper of what the host dict holds: every
        # mutation of _host goes through the spill/drop callbacks.
        self.host_blocks = host_blocks
        self.store = store
        self._host: dict = {}
        # demotion queue: host-tier records evicted by the LRU while
        # a store is attached, awaiting their disk write — moved here
        # under the allocator lock (dict ops only), flushed OFF-lock
        # a bounded amount per engine loop pass (flush_demotions) and
        # completely at drain (persist_tiers). Still restorable while
        # queued (restore consults it after the host tier).
        self._demote: dict = {}
        # spill batches whose device snapshots have not yet
        # materialized to host bytes: settled opportunistically one
        # per engine pass (settle_spills) so spilled content does not
        # pin device memory indefinitely when it is never re-hit
        self._unsettled: collections.deque = collections.deque()
        if host_blocks > 0:
            for s, a in enumerate(self.allocators):
                a.spill_cb = self._make_spill_cb(s)
                a.drop_cb = self._make_drop_cb(s)
        # (shard, page) -> (side, digest) of the sealed page's payload
        # bytes across layers — content-keyed so shared pages carry
        # exactly one digest that every reader re-verifies
        self._seals: dict = {}
        self._gauges()

    def _default_side(self) -> str:
        return "q8" if self.quant == "int8" else "fp"

    # -- device-side content -----------------------------------------

    def buffers(self) -> dict:
        """The arena pytree the step/prefill programs thread through
        (and donate): keys present depend on the quant mode."""
        out = {}
        if self.kc is not None:
            out["kc"], out["vc"] = self.kc, self.vc
        if self.qkc is not None:
            out.update(qkc=self.qkc, qvc=self.qvc,
                       ksc=self.ksc, vsc=self.vsc)
        return out

    def buffer_specs(self, pool_spec, scale_spec) -> dict:
        """PartitionSpec pytree matching :meth:`buffers`."""
        L = self.cfg.n_layers
        out = {}
        if self.kc is not None:
            out["kc"] = out["vc"] = (pool_spec,) * L
        if self.qkc is not None:
            out["qkc"] = out["qvc"] = (pool_spec,) * L
            out["ksc"] = out["vsc"] = (scale_spec,) * L
        return out

    def update(self, bufs: dict) -> None:
        """Install the step program's updated buffers (the engine calls
        this once per step with the program outputs)."""
        for k, v in bufs.items():
            setattr(self, k, tuple(v))

    def page_bytes(self, shard: int, page: int,
                   side: str | None = None) -> list:
        """Host copies of one physical block's payload for every layer
        — the integrity read-back (one device read per layer per call;
        sealing is a per-block, not per-step, event). The ``"q8"``
        side returns the QUANTIZED blocks plus their scale pages: the
        checksum covers exactly the bytes the request decodes from."""
        import numpy as np
        side = side or self._default_side()
        out = []
        for li in range(self.cfg.n_layers):
            if side == "fp":
                out.append(np.asarray(self.kc[li][shard, page]))
                out.append(np.asarray(self.vc[li][shard, page]))
            else:
                out.append(np.asarray(self.qkc[li][shard, page]))
                out.append(np.asarray(self.qvc[li][shard, page]))
                out.append(np.asarray(self.ksc[li][shard, page]))
                out.append(np.asarray(self.vsc[li][shard, page]))
        return out

    def poke_page(self, shard: int, page: int, layer: int,
                  array, side: str | None = None) -> None:
        """Overwrite one physical K block's content (the chaos drill's
        write-back path — a deterministic stand-in for an in-memory
        bit flip)."""
        import jax.numpy as jnp
        side = side or self._default_side()
        attr = "kc" if side == "fp" else "qkc"
        bufs = list(getattr(self, attr))
        bufs[layer] = bufs[layer].at[shard, page].set(
            jnp.asarray(array, bufs[layer].dtype))
        setattr(self, attr, tuple(bufs))

    def read_page(self, shard: int, page: int, layer: int,
                  side: str | None = None):
        """One K block's host copy (the chaos drill's read side)."""
        import numpy as np
        side = side or self._default_side()
        src = self.kc if side == "fp" else self.qkc
        return np.asarray(src[layer][shard, page])

    # -- sealing / integrity -----------------------------------------

    def seal(self, shard: int, page: int,
             side: str | None = None) -> None:
        """Record the checksum of a just-completed (fully committed)
        block so :meth:`verify` can detect later corruption. Keyed by
        content location, not owner — every sharer verifies the same
        digest."""
        side = side or self._default_side()
        self._seals[(shard, page)] = (
            side, _page_digest(self.page_bytes(shard, page, side)))

    def sealed(self, shard: int, page: int) -> bool:
        return (shard, page) in self._seals

    def verify(self, owner, shard: int) -> list:
        """Re-hash every sealed page in ``owner``'s table against its
        recorded digest; returns the list of block indices that FAIL
        (empty == intact)."""
        table = self.allocators[shard].table(owner)
        bad = []
        for bi, page in enumerate(table):
            rec = self._seals.get((shard, page))
            if rec is None:
                continue
            side, digest = rec
            if _page_digest(
                    self.page_bytes(shard, page, side)) != digest:
                bad.append(bi)
        return bad

    def _drop_seal(self, shard: int, page: int) -> None:
        self._seals.pop((shard, page), None)

    # -- bookkeeping shared with the engine --------------------------

    def release(self, owner, shard: int) -> int:
        """Drop the owner's references on one shard. Pages that leave
        circulation (refcount 0 and unindexed) lose their seals;
        cached pages KEEP theirs — a later sharer re-verifies the same
        digest."""
        n, freed = self.allocators[shard].release(owner)
        for p in freed:
            self._drop_seal(shard, p)
        self._gauges()
        return n

    # back-compat name (pre-sharing call sites)
    free = release

    def ensure(self, owner, shard: int, n_tokens: int) -> tuple:
        added = self.allocators[shard].ensure(owner, n_tokens)
        for p in added:
            # a freshly handed-out page may be a recycled one — any
            # stale digest from its previous life must not survive
            self._drop_seal(shard, p)
        if added:
            self._gauges()
        return added

    def share(self, owner, shard: int, pages) -> None:
        self.allocators[shard].share(owner, pages)
        self._gauges()

    def lookup(self, shard: int, hashes) -> list:
        return self.allocators[shard].lookup(hashes)

    def register(self, shard: int, page: int, h: str) -> bool:
        return self.allocators[shard].register(page, h)

    def announce(self, shard: int, owner, hashes) -> None:
        self.allocators[shard].announce(owner, hashes)

    def withdraw(self, shard: int, owner) -> None:
        self.allocators[shard].withdraw(owner)

    def announced(self, shard: int, h: str) -> bool:
        return self.allocators[shard].announced(h)

    def quarantine(self, owner, shard: int, block_index: int) -> bool:
        """Evict one of ``owner``'s pages from the prefix index (the
        verify-failure path): no future admission can share the
        corrupted content, and the page drains to the free list once
        its current readers release. Idempotent."""
        table = self.allocators[shard].table(owner)
        if not 0 <= block_index < len(table):
            return False
        out = self.allocators[shard].deregister(table[block_index])
        if out:
            obs.count("serve.prefix.quarantined")
        return out

    def cow(self, owner, shard: int, block_index: int,
            side: str | None = None):
        """Copy-on-write fork of a shared page: fresh exclusive page,
        device copy of the page's bytes, seal carried over (the copy
        IS the sealed content — a caller that then writes different
        bytes must re-seal). ``side`` restricts the copy to the
        arenas that actually serve the forking row (sharing is
        fp-only today, so a mixed engine's fork need not touch the
        q8 arenas); None copies every arena. Returns ``(old, new)``
        or None when the page was already exclusive."""
        pair = self.allocators[shard].cow(owner, block_index)
        if pair is None:
            return None
        old, new = pair
        names = {"fp": ("kc", "vc"),
                 "q8": ("qkc", "qvc", "ksc", "vsc")}.get(
            side, ("kc", "vc", "qkc", "qvc", "ksc", "vsc"))
        for name in names:
            bufs = getattr(self, name)
            if bufs is None:
                continue
            setattr(self, name, tuple(
                _page_copy(b, shard, old, new) for b in bufs))
        rec = self._seals.get((shard, old))
        if rec is not None:
            self._seals[(shard, new)] = rec
        else:
            self._drop_seal(shard, new)
        obs.count("serve.prefix.cow")
        self._gauges()
        return pair

    # -- tiered KV (r16): host spill tier + persistent store ---------

    def _make_spill_cb(self, shard: int):
        """The eviction-time copy-out, ASYNCHRONOUS by construction:
        capture every victim page of the eviction batch as ONE
        device-side gather BEFORE the pages are reused — jax arrays
        are immutable, so the dispatched snapshot reads the old
        buffers, untouched by later arena writes (and donation into
        the step program), and NO host sync happens on the eviction
        path (per-page synchronous read-back was measured dominating
        admission TTFT while scoping the r16 study; on TPU this
        capture point is where the async D2H DMA goes). The bytes
        materialize to host memory lazily at first use
        (:meth:`_materialize` — swap-in or persist), which is also
        where the content digest settles: a sealed page reuses its
        SEALED digest (recorded at finalization, so the whole
        device->host->device round trip is covered); an unsealed one
        hashes at materialization (the host-tier dwell is covered;
        arm ``integrity="pages"`` to cover the capture window too).
        Runs under the allocator lock (dispatch + dict ops only)."""
        def spill(pairs) -> set:
            chaos.maybe_delay("serve.kv.spill")
            by_side: dict = {}
            for page, h in pairs:
                rec = self._seals.get((shard, page))
                side = (rec[0] if rec is not None
                        else self._default_side())
                names = (("kc", "vc") if side == "fp"
                         else ("qkc", "qvc", "ksc", "vsc"))
                if getattr(self, names[0]) is None:
                    continue          # no such arena: drop like untiered
                by_side.setdefault((side, names), []).append(
                    (page, h, rec[1] if rec is not None else None))
            captured: set = set()
            for (side, names), group in by_side.items():
                pages = [page for page, _, _ in group]
                batch = _SpillBatch(
                    _pages_snapshot(
                        {n: getattr(self, n) for n in names}, shard,
                        pages), names, self.cfg.n_layers)
                self._unsettled.append(batch)
                for i, (page, h, digest) in enumerate(group):
                    self._host[(shard, h)] = [side, digest,
                                              (batch, i), False]
                    captured.add(h)
                obs.count("serve.kv.spills", len(group))
            return captured
        return spill

    def _settle_rec(self, rec: list) -> list:
        """Settle one tier record to verified-shape host bytes IN
        PLACE: device snapshot -> np arrays (the one sync, paid off
        the admission path — on a consumer's path where it replaces
        recompute, or in the bounded per-pass settle/demotion
        flushes), digest settled (sealed digest, or hashed now), and
        only THEN the ``serve.kv.spill`` corruption probe — injected
        rot models the host copy decaying after capture, which the
        swap-in verify must catch. Idempotent."""
        side, digest, payload, settled = rec
        if settled:
            return rec
        batch, idx = payload
        payload = batch.page(idx)
        if digest is None:
            digest = _page_digest(payload)
        payload[0] = chaos.maybe_corrupt("serve.kv.spill", payload[0])
        rec[0:4] = [side, digest, payload, True]
        return rec

    def _materialize(self, shard: int, h: str):
        rec = self._host.get((shard, h))
        return None if rec is None else self._settle_rec(rec)

    def _make_drop_cb(self, shard: int):
        """Host-tier LRU overflow: with a store attached, a dropped
        entry DEMOTES toward disk (the device -> host -> disk
        cascade) rather than vanishing. The callback runs under the
        allocator lock on the allocation path, so it does NO
        materialization and NO I/O — the record just moves to the
        demotion queue, which the engine flushes off-lock a bounded
        amount per loop pass (:meth:`flush_demotions`; the drain
        flush catches stragglers). Consumption drops
        (restore/re-registration/quarantine) skip the demotion: the
        content is resident again, or suspect."""
        def drop(h: str, demote: bool = True) -> None:
            rec = self._host.pop((shard, h), None)
            if (demote and rec is not None and self.store is not None
                    and not self.store.has(h)):
                self._demote[(shard, h)] = rec
        return drop

    def tier_plan(self, shard: int, hashes) -> list:
        """The longest consecutive run of ``hashes`` restorable from
        the tiers below the device (host spill set first, then the
        persistent store) — the admission-time continuation of
        ``lookup``'s device walk. Chain discipline applies: a gap
        breaks the run (a block whose predecessor is absent is
        unreachable K/V)."""
        a = self.allocators[shard]
        out = []
        for h in hashes:
            if (a.spilled(h) or (shard, h) in self._demote
                    or (self.store is not None
                        and self.store.has(h))):
                out.append(h)
            else:
                break
        return out

    def _restore_one(self, owner, shard: int, h: str, side: str,
                     staged: list):
        """One block of a restore run: fetch (host tier first, then
        store), verify the content digest, adopt a device page —
        DEFERRING the arena write onto ``staged`` so a run of blocks
        flushes as one batched scatter per arena (``_flush_restores``).
        Returns ``"shared"`` when the content is index-resident again
        (raced recompute/restore — attached through the share path),
        a ``{"src", "nbytes"}`` record on success, or None when the
        content is gone or FAILED its swap-in verify — quarantined
        from every tier, the caller recomputes fresh. Raises
        :class:`PoolExhausted` like any allocation."""
        a = self.allocators[shard]
        page = a.indexed(h)
        if page is not None:
            a.share(owner, [page])
            return "shared"
        chaos.maybe_delay("serve.kv.restore")
        chaos.maybe_die("serve.kv.restore")
        rec = self._materialize(shard, h)
        src = "host"
        if rec is None:
            # demotion limbo: dropped from the host LRU, disk write
            # not yet flushed — still restorable, still "host"
            rec = self._demote.get((shard, h))
            if rec is not None:
                rec = self._settle_rec(rec)
        if rec is None and self.store is not None:
            rec = self.store.get(h)
            src = "store"
        if rec is None:
            return None
        rside, digest, payload = rec[0], rec[1], rec[2]
        if rside != side:
            return None               # side-aware, like the index
        if _page_digest(payload) != digest:
            # a corrupt swap-in is quarantined, never trusted: the
            # content leaves every tier so no retry re-reads it
            a.purge_spilled(h)
            self._demote.pop((shard, h), None)
            if self.store is not None:
                self.store.quarantine(h)
            obs.count("serve.prefix.quarantined")
            obs.emit("serve.kv.restore_failed", shard=shard,
                     hash=h, src=src)
            return None
        page = a.adopt(owner, h)
        if page is None:
            a.share(owner, [a.indexed(h)])
            return "shared"
        staged.append((page, payload))
        # the payload IS the sealed content — seal carries over
        self._seals[(shard, page)] = (side, digest)
        nbytes = int(sum(p.nbytes for p in payload))
        obs.count("serve.prefix.restores")
        obs.count("serve.prefix.restore_bytes", nbytes)
        return {"src": src, "nbytes": nbytes}

    def _flush_restores(self, shard: int, side: str, staged: list,
                        width: int) -> None:
        """Write a run of restored blocks into the arenas: one
        batched donated scatter per (layer, arena), the run padded to
        ``width`` with trash-page-0 writes so the compiled program
        count stays one per (arena shape, width)."""
        if not staged:
            return
        width = max(width, len(staged))
        names = (("kc", "vc") if side == "fp"
                 else ("qkc", "qvc", "ksc", "vsc"))
        stride = len(names)
        pages = np.zeros(width, np.int32)
        for i, (pg, _) in enumerate(staged):
            pages[i] = pg
        blocks_by_name = {}
        for j, name in enumerate(names):
            per_layer = []
            for li in range(self.cfg.n_layers):
                blocks = [pay[li * stride + j] for _, pay in staged]
                pad = width - len(blocks)
                if pad:
                    blocks += [np.zeros_like(blocks[0])] * pad
                per_layer.append(np.stack(blocks))
            blocks_by_name[name] = np.stack(per_layer)
        out = _pages_write(
            {n: getattr(self, n) for n in names}, shard, pages,
            blocks_by_name)
        for n, bufs in out.items():
            setattr(self, n, tuple(bufs))

    def restore_run(self, owner, shard: int, hashes,
                    n_max: int, side: str | None = None) -> tuple:
        """Swap up to ``n_max`` consecutive blocks back in for
        ``owner`` (the engine's one-pass restore budget). Returns
        ``(results, fell_back)``: ``results`` holds one
        "shared"/record entry per block actually attached (in chain
        order), ``fell_back`` is True when a block vanished or failed
        its swap-in verify — the caller recomputes everything past
        ``results``. Device writes for the whole run flush as ONE
        batched scatter per arena per layer."""
        side = side or self._default_side()
        staged: list = []
        results: list = []
        fell_back = False
        try:
            for h in list(hashes)[:n_max]:
                out = self._restore_one(owner, shard, h, side, staged)
                if out is None:
                    fell_back = True
                    break
                results.append(out)
        finally:
            self._flush_restores(shard, side, staged, n_max)
            self._gauges()
        return results, fell_back

    def restore_block(self, owner, shard: int, h: str,
                      side: str | None = None):
        """Single-block restore (the pool-level unit surface and the
        rewarm path): ``restore_run`` of one."""
        results, _ = self.restore_run(owner, shard, [h], 1, side=side)
        return results[0] if results else None

    def warm_restore(self, width: int, max_evict: int | None = None,
                     side: str | None = None) -> None:
        """Compile the tier programs outside any timed window (the
        engine calls this at setup when a host tier is armed): the
        batched restore-write at ``width`` via an all-trash-page run
        of zero blocks, and the eviction-snapshot gather at every
        width bucket up to ``max_evict`` — page 0's contents are
        garbage by contract, so the warm calls are no-ops
        semantically and full compile+execute mechanically. Without
        this, the FIRST spill/restore pays XLA compiles inside a
        request's TTFT."""
        side = side or self._default_side()
        names = (("kc", "vc") if side == "fp"
                 else ("qkc", "qvc", "ksc", "vsc"))
        if getattr(self, names[0]) is None:
            return
        zero = [(0, [np.zeros(getattr(self, n)[0].shape[2:],
                              getattr(self, n)[0].dtype)
                     for _ in range(self.cfg.n_layers)
                     for n in names])]
        # one page-0 "restore" per shard covers every input sharding
        for shard in range(self.dp):
            self._flush_restores(shard, side, zero, width)
            if max_evict is None or self.host_blocks <= 0:
                continue    # store-only: nothing ever snapshots
            w = 4
            while True:
                _pages_snapshot({n: getattr(self, n) for n in names},
                                shard, [0] * min(w, max_evict))
                if w >= max_evict:
                    break
                w *= 2

    def persist(self, shard: int, page: int, h: str,
                side: str | None = None) -> bool:
        """Persist one indexed block to the store (content-addressed:
        already-present hashes are a no-op). The digest is recorded
        from the device bytes at write time — restores (this process
        or a restarted one) re-verify it at swap-in. NOT called on
        the serving hot path: a per-finalize write-through was
        measured costing admission TTFT its tier win, so persistence
        happens at the two off-path moments instead — host-tier LRU
        demotion (the drop callback) and :meth:`persist_tiers` at
        engine drain."""
        if self.store is None:
            return False
        if self.store.has(h):
            return False
        side = side or self._default_side()
        payload = self.page_bytes(shard, page, side)
        rec = self._seals.get((shard, page))
        digest = (rec[1] if rec is not None and rec[0] == side
                  else _page_digest(payload))
        return self.store.put(h, side, digest, payload)

    def persist_tiers(self) -> int:
        """Flush every surviving sealed block to the persistent store:
        all index-resident pages (cached AND live-with-hash) plus
        every host-tier entry — the engine calls this when its queue
        drains, so a clean run's whole prefix corpus survives restart
        without the hot path ever paying a disk write (a crashed
        run's store still holds whatever the demotion cascade flushed
        — partial rewarm beats no rewarm). Returns blocks written."""
        if self.store is None:
            return 0
        n = self.flush_demotions()
        for shard, a in enumerate(self.allocators):
            with a._lock:
                resident = list(a._hash_of.items())
                spilled = list(a._spilled)
            for page, h in resident:
                if self.persist(shard, page, h):
                    n += 1
            for h in spilled:
                if self.store.has(h):
                    continue
                rec = self._materialize(shard, h)
                if rec is not None and self.store.put(
                        h, rec[0], rec[1], rec[2]):
                    n += 1
        return n

    def flush_demotions(self, max_n: int | None = None) -> int:
        """Write queued host-tier demotions through to the store, OFF
        the allocator lock — the engine calls this once per loop pass
        with a small ``max_n`` so the demotion cascade costs bounded,
        predictable time per pass instead of fsync-ing under an
        allocation; ``persist_tiers`` (drain) flushes the remainder.
        Entries consumed by a restore in the meantime were already
        removed from the queue. Returns blocks written."""
        if self.store is None:
            self._demote.clear()
            return 0
        n = 0
        while self._demote and (max_n is None or n < max_n):
            (shard, h), rec = next(iter(self._demote.items()))
            del self._demote[(shard, h)]
            if self.store.has(h):
                continue
            rec = self._settle_rec(rec)
            if self.store.put(h, rec[0], rec[1], rec[2]):
                n += 1
        return n

    def settle_spills(self, max_batches: int = 1) -> int:
        """Opportunistically materialize pending spill batches
        (device snapshot -> host bytes), bounded per call — the
        engine probes this once per loop pass so spilled content
        stops pinning device memory even when it is never re-hit,
        without the capture path ever paying a host sync. Batches
        already settled by a consumer skip for free."""
        n = 0
        while self._unsettled and n < max_batches:
            batch = self._unsettled.popleft()
            if batch.settle():
                n += 1
        return n

    def rewarm_chain(self, hashes, width: int,
                     side: str = "fp") -> int:
        """Eagerly restore one prompt's chain from the tiers into the
        CACHED state on every dp shard (restart rewarm: refcount-0,
        indexed, awaiting hits) — batched through the same
        ``restore_run`` width the demand path uses, so the arena
        writes stay one dispatch per run. Stops at the first gap or
        failure (deeper blocks are unreachable K/V). Returns
        (shard, block) restores performed."""
        n = 0
        for shard in range(self.dp):
            a = self.allocators[shard]
            todo = [h for h in hashes if a.indexed(h) is None]
            owner = f"__rewarm.{shard}"
            try:
                while todo:
                    try:
                        results, fell = self.restore_run(
                            owner, shard, todo, width, side=side)
                    except PoolExhausted:
                        break     # pool full: demand paging takes over
                    n += sum(1 for r in results
                             if isinstance(r, dict))
                    todo = todo[len(results):]
                    if fell or not results:
                        break
            finally:
                self.release(owner, shard)
        return n

    def occupancy(self) -> float:
        """Fraction of allocatable blocks currently LIVE (mean over dp
        shards). Cached refcount-0 blocks are reclaimable on demand
        and do not count."""
        used = sum(a.n_used for a in self.allocators)
        return used / (self.n_blocks * self.dp)

    def fragmentation(self, used_tokens: dict) -> float:
        """Internal fragmentation: 1 − used-token-slots / allocated
        slots, given ``{(owner, shard): committed token count}``. Fixed
        blocks have no external fragmentation; the waste is the
        partially-filled tail block per request."""
        alloc_slots = sum(
            len(self.allocators[s].table(o)) * self.block_size
            for (o, s) in used_tokens)
        if not alloc_slots:
            return 0.0
        used = sum(min(v, len(self.allocators[s].table(o))
                       * self.block_size)
                   for (o, s), v in used_tokens.items())
        return 1.0 - used / alloc_slots

    def spilled_blocks(self) -> int:
        """Host-tier entries across shards — reclaimable CAPACITY but
        not device-resident, hence reported beside (never inside) the
        occupancy/cached gauges."""
        return sum(a.n_spilled for a in self.allocators)

    def _gauges(self) -> None:
        obs.gauge("serve.kv.occupancy", self.occupancy())
        obs.gauge("serve.kv.blocks_free",
                  sum(a.n_free for a in self.allocators))
        obs.gauge("serve.kv.blocks_cached",
                  sum(a.n_cached for a in self.allocators))
        if self.host_blocks > 0:
            obs.gauge("serve.kv.spilled", self.spilled_blocks())
