"""Paged KV cache: fixed-size blocks over one preallocated buffer.

The decode stack so far allocates one contiguous ``(total,)`` cache per
generate call, sized for the worst case — which is exactly what a
multi-request engine cannot afford: requests arrive with unknown output
lengths, and reserving max-length contiguous stripes per request either
caps concurrency at a handful of rows or wastes most of the buffer on
padding. This module is the vLLM/PagedAttention move specialized to the
repo's decode core: the cache is **one** preallocated arena of
fixed-size *blocks* (``block_size`` token columns each), requests own
*block tables* (ordered lists of block ids), and the engine's attention
gathers each row's blocks back into a contiguous view under a per-row
causal mask — so physical placement is arbitrary while the math stays
the ``_DecodeCtx`` math, token-identically.

Round 11 grows the pool **prefix-aware** (the vLLM block-sharing /
SGLang radix-reuse move on this machinery):

- blocks are **refcounted**: ``share`` attaches an existing block to a
  second owner's table instead of copying it, ``release`` decrements
  and only a refcount-0 block leaves circulation. K/V at a position is
  a pure function of the token prefix, so two requests whose prompts
  agree on a block-aligned prefix can serve attention from the *same*
  physical pages;
- a **content-addressed index** maps chain hashes of full-block token
  runs to resident pages. The chain (``h_j = H(h_{j-1}, tokens_j,
  side)``) makes a flat dict equivalent to a radix trie over block
  paths: an entry's key commits to its entire prefix, so the longest
  cached prefix is the longest chain of consecutive hits. Entries are
  side-aware — an int8 block never serves an fp reader;
- **copy-on-write**: a block with refcount > 1 is immutable; a writer
  must ``cow`` it first (fresh page, device copy, table swap). A
  partially-filled tail block is never shared — only full, finalized
  blocks enter the index;
- refcount-0 blocks whose content is indexed are retained in an **LRU
  cached set** rather than freed; allocation takes free pages first
  and evicts cached pages (dropping their index entries) only under
  pressure. :class:`PoolExhausted` now means live + cached together
  cannot satisfy the request.

Block 0 of every shard is the **trash block**: engine rows that are
inactive (empty slots, padded chunk positions) still execute the step
program — their writes are routed to block 0, whose contents are
garbage by contract and are never read unmasked. Allocations therefore
hand out ids from ``[1, n_blocks]``.

Integrity: sealed-page checksums are keyed by ``(shard, page)`` — a
property of the *content*, not of one owner — so every request whose
table maps a shared page re-verifies the same digest, and one
corrupted shared page is detected by every reader. The engine
quarantines such a page from the index so retries re-prefill on fresh
blocks (drilled in ``tests/test_serve_chaos.py``).
"""

from __future__ import annotations

import collections
import hashlib
import threading

import numpy as np

from icikit import obs


class PoolExhausted(RuntimeError):
    """The free list + evictable cached blocks cannot satisfy an
    allocation.

    Loud by design: silent admission of a request the pool cannot hold
    would stall every co-batched request behind an un-extendable row.
    The engine's policy on catching this is preempt-and-requeue, not
    crash — but the *allocator* never hands out partial allocations.
    ``free`` counts every reclaimable page (free list + refcount-0
    cached): only *live* blocks are unreclaimable.
    """

    def __init__(self, requested: int, free: int, capacity: int):
        super().__init__(
            f"KV pool exhausted: requested {requested} blocks, "
            f"{free} reclaimable of {capacity}")
        self.requested = requested
        self.free = free
        self.capacity = capacity


def chain_seed(side: str = "fp") -> bytes:
    """The chain-hash seed (block -1 state) for one arena side."""
    return side.encode()


def chain_extend(prev: bytes, tokens) -> tuple:
    """Extend a chain-hash state by ONE full block of tokens; returns
    ``(hexdigest, digest)`` — the index key and the next chain state.
    O(block) per call, which is what lets the engine finalize block
    ``j`` without re-hashing blocks ``0..j-1``."""
    h = hashlib.blake2b(digest_size=16)
    h.update(prev)
    h.update(np.ascontiguousarray(
        np.asarray(tokens, np.int32)).tobytes())
    return h.hexdigest(), h.digest()


def block_hashes(tokens, block_size: int, side: str = "fp") -> list:
    """Chain hashes of every FULL block of ``tokens`` — the
    content-address of the prefix index. ``h_j`` commits to blocks
    ``0..j`` (and the arena side), so a dict over these hashes is a
    radix trie over block paths: matching ``h_j`` implies the whole
    prefix matched."""
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32)).reshape(-1)
    out = []
    prev = chain_seed(side)
    for j in range(toks.size // block_size):
        hx, prev = chain_extend(
            prev, toks[j * block_size:(j + 1) * block_size])
        out.append(hx)
    return out


class BlockAllocator:
    """Refcounted free-list allocator over ``n_blocks`` fixed blocks.

    Block ids are ``1..n_blocks`` (0 is the engine's trash block and is
    never allocated). Mutations are all-or-nothing: on exhaustion they
    raise :class:`PoolExhausted` with the allocator state unchanged.
    Thread-safe — the engine is single-threaded today, but the
    scheduler discipline elsewhere in this repo (``_LeaseQueue``) is
    that shared metadata takes a lock rather than an assumption.

    Every page is in exactly one of three places:

    - **live** — refcount >= 1, mapped by >= 1 block table;
    - **cached** — refcount 0 but content-indexed (``register``), held
      in LRU order awaiting either a ``share`` (cache hit revives it)
      or eviction under allocation pressure;
    - **free** — on the free list, content unknown.
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.capacity = n_blocks
        self.block_size = block_size
        self._free = collections.deque(range(1, n_blocks + 1))
        self._tables: dict = {}          # owner -> list[int]
        self._refs: dict = {}            # page -> live refcount
        self._index: dict = {}           # chain hash -> page
        self._hash_of: dict = {}         # page -> chain hash
        # refcount-0 pages kept for reuse, LRU -> MRU order
        self._cached: collections.OrderedDict = collections.OrderedDict()
        # in-flight prefill announcements (r12 dedup): chain hash ->
        # announcing owner, for blocks an admitted request is
        # CURRENTLY computing but has not yet finalized/registered.
        # A concurrent identical/prefix admission that finds its next
        # needed hash here attaches as a WAITER instead of computing;
        # entries drain into the index via register() (which clears
        # them) or vanish with their owner via withdraw() — so a
        # waiter can never wait on content nobody will produce.
        self._inflight: dict = {}        # chain hash -> owner
        self._lock = threading.Lock()
        self.n_evictions = 0

    # -- queries -----------------------------------------------------

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_cached(self) -> int:
        with self._lock:
            return len(self._cached)

    @property
    def n_used(self) -> int:
        """LIVE blocks (refcount >= 1). Cached refcount-0 blocks are
        reclaimable on demand and do not count as used."""
        with self._lock:
            return len(self._refs)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs.get(page, 0)

    def owners(self) -> tuple:
        with self._lock:
            return tuple(self._tables)

    def table(self, owner) -> tuple:
        """The owner's block table (ordered; () for unknown owners)."""
        with self._lock:
            return tuple(self._tables.get(owner, ()))

    def indexed(self, h: str):
        """The page registered under chain hash ``h`` (None = miss)."""
        with self._lock:
            return self._index.get(h)

    # -- mutation ----------------------------------------------------

    def _take(self, n: int) -> list:
        """Pop ``n`` pages (free list first, then LRU-evict cached),
        lock held. All-or-nothing; evicted pages lose their index
        entry. Returns the pages; caller assigns refcounts."""
        if n > len(self._free) + len(self._cached):
            raise PoolExhausted(
                n, len(self._free) + len(self._cached), self.capacity)
        got = []
        while len(got) < n and self._free:
            got.append(self._free.popleft())
        while len(got) < n:
            page, _ = self._cached.popitem(last=False)   # LRU victim
            h = self._hash_of.pop(page)
            del self._index[h]
            self.n_evictions += 1
            got.append(page)
        return got

    def alloc(self, owner, n: int) -> tuple:
        """Append ``n`` fresh exclusive blocks to ``owner``'s table;
        returns the new block ids. All-or-nothing on exhaustion; may
        evict LRU cached blocks under pressure."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        with self._lock:
            got = self._take(n)
            for p in got:
                self._refs[p] = 1
            self._tables.setdefault(owner, []).extend(got)
        return tuple(got)

    def ensure(self, owner, n_tokens: int) -> tuple:
        """Grow ``owner``'s table until it covers ``n_tokens`` cache
        positions; returns the blocks *added* (possibly ())."""
        need = -(-n_tokens // self.block_size)  # ceil
        have = len(self._tables.get(owner, ()))
        return self.alloc(owner, max(0, need - have)) if need > have \
            else ()

    def share(self, owner, pages) -> None:
        """Append existing ``pages`` to ``owner``'s table, bumping
        refcounts — the cache-hit attach. A cached (refcount-0) page
        revives to live; pages must be live or cached (a free-list
        page has unknown content and cannot be shared)."""
        with self._lock:
            for p in pages:
                if self._refs.get(p, 0) == 0 and p not in self._cached:
                    raise ValueError(
                        f"cannot share page {p}: neither live nor "
                        "cached")
            t = self._tables.setdefault(owner, [])
            for p in pages:
                self._cached.pop(p, None)
                self._refs[p] = self._refs.get(p, 0) + 1
                t.append(p)

    def release(self, owner) -> tuple:
        """Drop every reference ``owner`` holds; returns ``(n_released,
        pages_freed)`` where ``pages_freed`` are the pages that left
        circulation entirely (refcount hit 0 and no index entry keeps
        them cached) — the pool drops their seals. Unknown owners
        release 0 (idempotent — a retried eviction must not corrupt
        the free list)."""
        freed = []
        with self._lock:
            pages = self._tables.pop(owner, [])
            # cache in REVERSE table order so the chain root lands at
            # the MRU end: LRU eviction then takes the deepest block
            # first, and a truncated chain stays walkable from its
            # root instead of orphaning its tail (see lookup)
            for p in reversed(pages):
                self._refs[p] -= 1
                if self._refs[p]:
                    continue
                del self._refs[p]
                if p in self._hash_of:
                    self._cached[p] = None      # MRU end
                else:
                    self._free.append(p)
                    freed.append(p)
        return len(pages), freed

    def free(self, owner) -> int:
        """Back-compat shim over :meth:`release` (single-owner call
        sites and the property suite predate sharing)."""
        return self.release(owner)[0]

    def cow(self, owner, index: int):
        """Copy-on-write guard for ``owner``'s table entry ``index``:
        a block mapped by other owners (refcount > 1) is swapped for a
        fresh exclusive page; returns ``(old_page, new_page)`` so the
        pool can copy the device bytes, or None when the block is
        already exclusive (no fork needed). The fork is NOT indexed —
        its content address stays with the original."""
        with self._lock:
            table = self._tables.get(owner)
            if table is None or not 0 <= index < len(table):
                raise ValueError(f"cow: no block {index} for {owner!r}")
            old = table[index]
            if self._refs[old] <= 1:
                return None
            [new] = self._take(1)
            self._refs[old] -= 1
            self._refs[new] = 1
            table[index] = new
        return old, new

    # -- prefix index ------------------------------------------------

    def lookup(self, hashes) -> list:
        """Longest chain of consecutively-indexed pages for ``hashes``
        (the block-aligned cached prefix). Touches hits to MRU in
        DEEPEST-first order, leaving the chain ROOT most recent:
        lookup can only walk a chain from its root, so evicting a
        root orphans every deeper cached block of that prefix —
        victims must come leaf-first (the radix-cache discipline)."""
        out = []
        with self._lock:
            for h in hashes:
                p = self._index.get(h)
                if p is None:
                    break
                out.append(p)
            for p in reversed(out):
                if p in self._cached:
                    self._cached.move_to_end(p)
        return out

    def register(self, page: int, h: str) -> bool:
        """Content-address a LIVE page. First registration wins: a
        duplicate hash (same content already resident) or an
        already-hashed page is refused — the duplicate page simply
        stays anonymous and is freed on release. Either way any
        in-flight announcement of ``h`` is settled: the content is
        now findable through the index, so nobody should keep waiting
        on it."""
        with self._lock:
            self._inflight.pop(h, None)
            if h in self._index or page in self._hash_of:
                return False
            if self._refs.get(page, 0) < 1:
                raise ValueError(
                    f"register: page {page} is not live")
            self._index[h] = page
            self._hash_of[page] = h
            return True

    # -- in-flight prefill announcements (r12 dedup) -----------------

    def announce(self, owner, hashes) -> None:
        """Declare that ``owner`` is about to compute the blocks behind
        ``hashes`` (chain hashes of full prompt blocks, in order).
        First announcer wins per hash — a later identical admission is
        exactly the waiter the registry exists to create, and it must
        keep seeing the ORIGINAL announcement until the block lands in
        the index."""
        with self._lock:
            for h in hashes:
                if h not in self._index:
                    self._inflight.setdefault(h, owner)

    def withdraw(self, owner) -> None:
        """Drop every announcement ``owner`` still holds (eviction /
        preemption / completion cleanup) — waiters on those hashes
        stop waiting at their next poll and compute the blocks
        themselves. Idempotent."""
        with self._lock:
            stale = [h for h, o in self._inflight.items() if o == owner]
            for h in stale:
                del self._inflight[h]

    def announced(self, h: str) -> bool:
        """Is ``h`` currently being computed by some admitted row?"""
        with self._lock:
            return h in self._inflight

    def deregister(self, page: int) -> bool:
        """Remove a page's index entry (the corruption quarantine): no
        new request can share it, and once its refcount drains it goes
        to the free list instead of the cached set."""
        with self._lock:
            h = self._hash_of.pop(page, None)
            if h is None:
                return False
            del self._index[h]
            if page in self._cached:
                del self._cached[page]
                self._free.append(page)
            return True


_COPY_FN = None


def _page_copy(buf, shard: int, old: int, new: int):
    """Copy one physical page within an arena buffer via a donated
    jitted program: donation lets XLA update the buffer in place, so
    forking one block costs one page of traffic — not a full-arena
    materialization per layer per arena (jit caches one executable
    per (shape, dtype, sharding); indices are traced)."""
    global _COPY_FN
    if _COPY_FN is None:
        import jax

        def cp(b, s, o, n):
            zeros = (0,) * (b.ndim - 2)
            page = jax.lax.dynamic_slice(
                b, (s, o) + zeros, (1, 1) + b.shape[2:])
            return jax.lax.dynamic_update_slice(
                b, page, (s, n) + zeros)

        _COPY_FN = jax.jit(cp, donate_argnums=(0,))
    import jax.numpy as jnp
    i32 = jnp.int32
    return _COPY_FN(buf, i32(shard), i32(old), i32(new))


def _page_digest(arrays) -> str:
    """Checksum of one block's K and V content across layers (host
    bytes in layer order) — the sealed-page integrity fingerprint. On
    the q8 side the array list interleaves the quantized payload AND
    its scale pages: a flipped scale corrupts decoded tokens exactly
    like a flipped int8 byte, so it must flip the digest too."""
    h = hashlib.blake2b(digest_size=16)
    for a in arrays:
        h.update(a.tobytes())
    return h.hexdigest()


class KVPool:
    """The device arena + per-dp-shard allocators + obs gauges.

    ``kc``/``vc`` are per-layer tuples of jax arrays, each of global
    shape ``(dp, n_blocks + 1, block_size, kv_heads, d_head)`` sharded
    ``P(dp, None, None, tp, None)`` — engine step programs carry them
    as carry-style inputs/outputs (the decode.py cache discipline) and
    write them back via :meth:`update`.

    ``quant`` selects the arena set (the int8 serving path, DECODE.md
    "Quantized decode"):

    - ``"none"`` — the historical compute-dtype arenas only;
    - ``"int8"`` — int8 arenas ``qkc``/``qvc`` plus per-slot fp32
      *scale pages* ``ksc``/``vsc`` of shape ``(dp, n_blocks + 1,
      block_size, kv_heads)``; **no** high-precision KV arena exists
      on this path (``make check`` lints the invariant);
    - ``"mixed"`` — both sets over ONE allocator and one block table
      per request: a block id addresses the same slot in every arena,
      each row reads only its own side, so fp32 co-batched requests
      are bitwise untouched by int8 neighbors (the containment pin).

    Sealing checksums the payload a request actually serves from: the
    int8 side hashes the quantized blocks AND their scale pages (a
    flipped scale corrupts tokens exactly like a flipped int8 byte).
    Seals are keyed ``(shard, page)`` — shared pages carry ONE digest
    every reader re-verifies.
    """

    SIDES = ("fp", "q8")

    def __init__(self, cfg, mesh, n_blocks: int, block_size: int,
                 quant: str = "none"):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from icikit.models.transformer.model import DP_AXIS, TP_AXIS

        if quant not in ("none", "int8", "mixed"):
            raise ValueError(f"unknown pool quant {quant!r} "
                             "(known: none, int8, mixed)")
        self.cfg = cfg
        self.mesh = mesh
        self.n_blocks = n_blocks
        self.block_size = block_size
        self.quant = quant
        self.dp = mesh.shape[DP_AXIS]
        kv_heads = cfg.n_kv_heads or cfg.n_heads
        shape = (self.dp, n_blocks + 1, block_size, kv_heads, cfg.d_head)
        sshape = shape[:-1]
        sh = NamedSharding(mesh, P(DP_AXIS, None, None, TP_AXIS, None))
        ssh = NamedSharding(mesh, P(DP_AXIS, None, None, TP_AXIS))
        cdt = jnp.dtype(cfg.compute_dtype)

        def arena(shp, dtype, shd):
            # one DISTINCT buffer per layer/side: the engine donates
            # these into its step program (in-place pool updates), and
            # donation rejects aliased inputs
            return jax.device_put(jnp.zeros(shp, dtype), shd)

        L = cfg.n_layers
        self.kc = self.vc = None
        self.qkc = self.qvc = self.ksc = self.vsc = None
        if quant in ("none", "mixed"):
            self.kc = tuple(arena(shape, cdt, sh) for _ in range(L))
            self.vc = tuple(arena(shape, cdt, sh) for _ in range(L))
        if quant in ("int8", "mixed"):
            self.qkc = tuple(arena(shape, jnp.int8, sh)
                             for _ in range(L))
            self.qvc = tuple(arena(shape, jnp.int8, sh)
                             for _ in range(L))
            self.ksc = tuple(arena(sshape, jnp.float32, ssh)
                             for _ in range(L))
            self.vsc = tuple(arena(sshape, jnp.float32, ssh)
                             for _ in range(L))
        self.allocators = tuple(BlockAllocator(n_blocks, block_size)
                                for _ in range(self.dp))
        # (shard, page) -> (side, digest) of the sealed page's payload
        # bytes across layers — content-keyed so shared pages carry
        # exactly one digest that every reader re-verifies
        self._seals: dict = {}
        self._gauges()

    def _default_side(self) -> str:
        return "q8" if self.quant == "int8" else "fp"

    # -- device-side content -----------------------------------------

    def buffers(self) -> dict:
        """The arena pytree the step/prefill programs thread through
        (and donate): keys present depend on the quant mode."""
        out = {}
        if self.kc is not None:
            out["kc"], out["vc"] = self.kc, self.vc
        if self.qkc is not None:
            out.update(qkc=self.qkc, qvc=self.qvc,
                       ksc=self.ksc, vsc=self.vsc)
        return out

    def buffer_specs(self, pool_spec, scale_spec) -> dict:
        """PartitionSpec pytree matching :meth:`buffers`."""
        L = self.cfg.n_layers
        out = {}
        if self.kc is not None:
            out["kc"] = out["vc"] = (pool_spec,) * L
        if self.qkc is not None:
            out["qkc"] = out["qvc"] = (pool_spec,) * L
            out["ksc"] = out["vsc"] = (scale_spec,) * L
        return out

    def update(self, bufs: dict) -> None:
        """Install the step program's updated buffers (the engine calls
        this once per step with the program outputs)."""
        for k, v in bufs.items():
            setattr(self, k, tuple(v))

    def page_bytes(self, shard: int, page: int,
                   side: str | None = None) -> list:
        """Host copies of one physical block's payload for every layer
        — the integrity read-back (one device read per layer per call;
        sealing is a per-block, not per-step, event). The ``"q8"``
        side returns the QUANTIZED blocks plus their scale pages: the
        checksum covers exactly the bytes the request decodes from."""
        import numpy as np
        side = side or self._default_side()
        out = []
        for li in range(self.cfg.n_layers):
            if side == "fp":
                out.append(np.asarray(self.kc[li][shard, page]))
                out.append(np.asarray(self.vc[li][shard, page]))
            else:
                out.append(np.asarray(self.qkc[li][shard, page]))
                out.append(np.asarray(self.qvc[li][shard, page]))
                out.append(np.asarray(self.ksc[li][shard, page]))
                out.append(np.asarray(self.vsc[li][shard, page]))
        return out

    def poke_page(self, shard: int, page: int, layer: int,
                  array, side: str | None = None) -> None:
        """Overwrite one physical K block's content (the chaos drill's
        write-back path — a deterministic stand-in for an in-memory
        bit flip)."""
        import jax.numpy as jnp
        side = side or self._default_side()
        attr = "kc" if side == "fp" else "qkc"
        bufs = list(getattr(self, attr))
        bufs[layer] = bufs[layer].at[shard, page].set(
            jnp.asarray(array, bufs[layer].dtype))
        setattr(self, attr, tuple(bufs))

    def read_page(self, shard: int, page: int, layer: int,
                  side: str | None = None):
        """One K block's host copy (the chaos drill's read side)."""
        import numpy as np
        side = side or self._default_side()
        src = self.kc if side == "fp" else self.qkc
        return np.asarray(src[layer][shard, page])

    # -- sealing / integrity -----------------------------------------

    def seal(self, shard: int, page: int,
             side: str | None = None) -> None:
        """Record the checksum of a just-completed (fully committed)
        block so :meth:`verify` can detect later corruption. Keyed by
        content location, not owner — every sharer verifies the same
        digest."""
        side = side or self._default_side()
        self._seals[(shard, page)] = (
            side, _page_digest(self.page_bytes(shard, page, side)))

    def sealed(self, shard: int, page: int) -> bool:
        return (shard, page) in self._seals

    def verify(self, owner, shard: int) -> list:
        """Re-hash every sealed page in ``owner``'s table against its
        recorded digest; returns the list of block indices that FAIL
        (empty == intact)."""
        table = self.allocators[shard].table(owner)
        bad = []
        for bi, page in enumerate(table):
            rec = self._seals.get((shard, page))
            if rec is None:
                continue
            side, digest = rec
            if _page_digest(
                    self.page_bytes(shard, page, side)) != digest:
                bad.append(bi)
        return bad

    def _drop_seal(self, shard: int, page: int) -> None:
        self._seals.pop((shard, page), None)

    # -- bookkeeping shared with the engine --------------------------

    def release(self, owner, shard: int) -> int:
        """Drop the owner's references on one shard. Pages that leave
        circulation (refcount 0 and unindexed) lose their seals;
        cached pages KEEP theirs — a later sharer re-verifies the same
        digest."""
        n, freed = self.allocators[shard].release(owner)
        for p in freed:
            self._drop_seal(shard, p)
        self._gauges()
        return n

    # back-compat name (pre-sharing call sites)
    free = release

    def ensure(self, owner, shard: int, n_tokens: int) -> tuple:
        added = self.allocators[shard].ensure(owner, n_tokens)
        for p in added:
            # a freshly handed-out page may be a recycled one — any
            # stale digest from its previous life must not survive
            self._drop_seal(shard, p)
        if added:
            self._gauges()
        return added

    def share(self, owner, shard: int, pages) -> None:
        self.allocators[shard].share(owner, pages)
        self._gauges()

    def lookup(self, shard: int, hashes) -> list:
        return self.allocators[shard].lookup(hashes)

    def register(self, shard: int, page: int, h: str) -> bool:
        return self.allocators[shard].register(page, h)

    def announce(self, shard: int, owner, hashes) -> None:
        self.allocators[shard].announce(owner, hashes)

    def withdraw(self, shard: int, owner) -> None:
        self.allocators[shard].withdraw(owner)

    def announced(self, shard: int, h: str) -> bool:
        return self.allocators[shard].announced(h)

    def quarantine(self, owner, shard: int, block_index: int) -> bool:
        """Evict one of ``owner``'s pages from the prefix index (the
        verify-failure path): no future admission can share the
        corrupted content, and the page drains to the free list once
        its current readers release. Idempotent."""
        table = self.allocators[shard].table(owner)
        if not 0 <= block_index < len(table):
            return False
        out = self.allocators[shard].deregister(table[block_index])
        if out:
            obs.count("serve.prefix.quarantined")
        return out

    def cow(self, owner, shard: int, block_index: int,
            side: str | None = None):
        """Copy-on-write fork of a shared page: fresh exclusive page,
        device copy of the page's bytes, seal carried over (the copy
        IS the sealed content — a caller that then writes different
        bytes must re-seal). ``side`` restricts the copy to the
        arenas that actually serve the forking row (sharing is
        fp-only today, so a mixed engine's fork need not touch the
        q8 arenas); None copies every arena. Returns ``(old, new)``
        or None when the page was already exclusive."""
        pair = self.allocators[shard].cow(owner, block_index)
        if pair is None:
            return None
        old, new = pair
        names = {"fp": ("kc", "vc"),
                 "q8": ("qkc", "qvc", "ksc", "vsc")}.get(
            side, ("kc", "vc", "qkc", "qvc", "ksc", "vsc"))
        for name in names:
            bufs = getattr(self, name)
            if bufs is None:
                continue
            setattr(self, name, tuple(
                _page_copy(b, shard, old, new) for b in bufs))
        rec = self._seals.get((shard, old))
        if rec is not None:
            self._seals[(shard, new)] = rec
        else:
            self._drop_seal(shard, new)
        obs.count("serve.prefix.cow")
        self._gauges()
        return pair

    def occupancy(self) -> float:
        """Fraction of allocatable blocks currently LIVE (mean over dp
        shards). Cached refcount-0 blocks are reclaimable on demand
        and do not count."""
        used = sum(a.n_used for a in self.allocators)
        return used / (self.n_blocks * self.dp)

    def fragmentation(self, used_tokens: dict) -> float:
        """Internal fragmentation: 1 − used-token-slots / allocated
        slots, given ``{(owner, shard): committed token count}``. Fixed
        blocks have no external fragmentation; the waste is the
        partially-filled tail block per request."""
        alloc_slots = sum(
            len(self.allocators[s].table(o)) * self.block_size
            for (o, s) in used_tokens)
        if not alloc_slots:
            return 0.0
        used = sum(min(v, len(self.allocators[s].table(o))
                       * self.block_size)
                   for (o, s), v in used_tokens.items())
        return 1.0 - used / alloc_slots

    def _gauges(self) -> None:
        obs.gauge("serve.kv.occupancy", self.occupancy())
        obs.gauge("serve.kv.blocks_free",
                  sum(a.n_free for a in self.allocators))
        obs.gauge("serve.kv.blocks_cached",
                  sum(a.n_cached for a in self.allocators))
