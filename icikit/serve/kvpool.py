"""Paged KV cache: fixed-size blocks over one preallocated buffer.

The decode stack so far allocates one contiguous ``(total,)`` cache per
generate call, sized for the worst case — which is exactly what a
multi-request engine cannot afford: requests arrive with unknown output
lengths, and reserving max-length contiguous stripes per request either
caps concurrency at a handful of rows or wastes most of the buffer on
padding. This module is the vLLM/PagedAttention move specialized to the
repo's decode core: the cache is **one** preallocated arena of
fixed-size *blocks* (``block_size`` token columns each), requests own
*block tables* (ordered lists of block ids), and the engine's attention
gathers each row's blocks back into a contiguous view under a per-row
causal mask — so physical placement is arbitrary while the math stays
the ``_DecodeCtx`` math, token-identically.

Two layers, deliberately separable:

- :class:`BlockAllocator` — pure host-side metadata: a free list over
  block ids plus per-request block tables. No device state, so the
  property/fuzz suite (``tests/test_kvpool.py``) can hammer random
  alloc/extend/free interleavings and assert the invariants (live
  blocks never alias, the free list conserves capacity, exhaustion
  raises :class:`PoolExhausted` without partial allocation) at high
  iteration counts.
- :class:`KVPool` — the device arena: per-layer K and V buffers of
  shape ``(dp, n_blocks + 1, block_size, kv_heads, d_head)`` sharded
  ``P(dp, None, None, tp, None)``, one :class:`BlockAllocator` per dp
  shard (rows on shard *s* allocate from shard *s*'s block space), and
  occupancy/fragmentation gauges on the obs bus.

Block 0 of every shard is the **trash block**: engine rows that are
inactive (empty slots) still execute the step program — their writes
are routed to block 0, whose contents are garbage by contract and are
never read unmasked. Allocations therefore hand out ids from
``[1, n_blocks]``.

Integrity: the pool can remember a checksum per *sealed* block (every
slot committed — the engine seals block ``j`` of a request once its
committed frontier passes ``(j + 1) * block_size``) and re-verify the
request's sealed blocks later; a mismatch is the detection mechanism
behind the KV-page corruption chaos drill (a corrupted page fails its
*owning* request only — co-batched requests never gather it).
"""

from __future__ import annotations

import collections
import hashlib
import threading

from icikit import obs


class PoolExhausted(RuntimeError):
    """The free list cannot satisfy an allocation.

    Loud by design: silent admission of a request the pool cannot hold
    would stall every co-batched request behind an un-extendable row.
    The engine's policy on catching this is preempt-and-requeue, not
    crash — but the *allocator* never hands out partial allocations.
    """

    def __init__(self, requested: int, free: int, capacity: int):
        super().__init__(
            f"KV pool exhausted: requested {requested} blocks, "
            f"{free} free of {capacity}")
        self.requested = requested
        self.free = free
        self.capacity = capacity


class BlockAllocator:
    """Free-list allocator over ``n_blocks`` fixed-size blocks.

    Block ids are ``1..n_blocks`` (0 is the engine's trash block and is
    never allocated). ``alloc``/``ensure`` are all-or-nothing: on
    exhaustion they raise :class:`PoolExhausted` with the allocator
    state unchanged. Thread-safe — the engine is single-threaded today,
    but the scheduler discipline elsewhere in this repo (``_LeaseQueue``)
    is that shared metadata takes a lock rather than an assumption.
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.capacity = n_blocks
        self.block_size = block_size
        self._free = collections.deque(range(1, n_blocks + 1))
        self._tables: dict = {}          # owner -> list[int]
        self._lock = threading.Lock()

    # -- queries -----------------------------------------------------

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_used(self) -> int:
        return self.capacity - self.n_free

    def owners(self) -> tuple:
        with self._lock:
            return tuple(self._tables)

    def table(self, owner) -> tuple:
        """The owner's block table (ordered; () for unknown owners)."""
        with self._lock:
            return tuple(self._tables.get(owner, ()))

    # -- mutation ----------------------------------------------------

    def alloc(self, owner, n: int) -> tuple:
        """Append ``n`` fresh blocks to ``owner``'s table; returns the
        new block ids. All-or-nothing on exhaustion."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        with self._lock:
            if n > len(self._free):
                raise PoolExhausted(n, len(self._free), self.capacity)
            got = [self._free.popleft() for _ in range(n)]
            self._tables.setdefault(owner, []).extend(got)
        return tuple(got)

    def ensure(self, owner, n_tokens: int) -> tuple:
        """Grow ``owner``'s table until it covers ``n_tokens`` cache
        positions; returns the blocks *added* (possibly ())."""
        need = -(-n_tokens // self.block_size)  # ceil
        have = len(self._tables.get(owner, ()))
        return self.alloc(owner, max(0, need - have)) if need > have \
            else ()

    def free(self, owner) -> int:
        """Release every block owned by ``owner`` back to the free
        list; returns how many. Unknown owners free 0 (idempotent —
        a retried eviction must not corrupt the free list)."""
        with self._lock:
            blocks = self._tables.pop(owner, [])
            self._free.extend(blocks)
            return len(blocks)


def _page_digest(arrays) -> str:
    """Checksum of one block's K and V content across layers (host
    bytes in layer order) — the sealed-page integrity fingerprint."""
    h = hashlib.blake2b(digest_size=16)
    for a in arrays:
        h.update(a.tobytes())
    return h.hexdigest()


class KVPool:
    """The device arena + per-dp-shard allocators + obs gauges.

    ``kc``/``vc`` are per-layer tuples of jax arrays, each of global
    shape ``(dp, n_blocks + 1, block_size, kv_heads, d_head)`` sharded
    ``P(dp, None, None, tp, None)`` — engine step programs carry them
    as carry-style inputs/outputs (the decode.py cache discipline) and
    write them back via :meth:`update`.
    """

    def __init__(self, cfg, mesh, n_blocks: int, block_size: int):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from icikit.models.transformer.model import DP_AXIS, TP_AXIS

        self.cfg = cfg
        self.mesh = mesh
        self.n_blocks = n_blocks
        self.block_size = block_size
        self.dp = mesh.shape[DP_AXIS]
        kv_heads = cfg.n_kv_heads or cfg.n_heads
        shape = (self.dp, n_blocks + 1, block_size, kv_heads, cfg.d_head)
        sh = NamedSharding(mesh, P(DP_AXIS, None, None, TP_AXIS, None))
        cdt = jnp.dtype(cfg.compute_dtype)

        def arena():
            # one DISTINCT buffer per layer/side: the engine donates
            # these into its step program (in-place pool updates), and
            # donation rejects aliased inputs
            return jax.device_put(jnp.zeros(shape, cdt), sh)

        self.kc = tuple(arena() for _ in range(cfg.n_layers))
        self.vc = tuple(arena() for _ in range(cfg.n_layers))
        self.allocators = tuple(BlockAllocator(n_blocks, block_size)
                                for _ in range(self.dp))
        # (owner, shard, block_index_in_table) -> digest of the sealed
        # page's K/V bytes across layers
        self._seals: dict = {}
        self._gauges()

    # -- device-side content -----------------------------------------

    def update(self, kc, vc) -> None:
        """Install the step program's updated buffers (the engine calls
        this once per step with the program outputs)."""
        self.kc = tuple(kc)
        self.vc = tuple(vc)

    def page_bytes(self, shard: int, page: int) -> list:
        """Host copies of one physical block's K and V content for
        every layer — the integrity read-back (one device read per
        layer per call; sealing is a per-block, not per-step, event)."""
        import numpy as np
        out = []
        for li in range(self.cfg.n_layers):
            out.append(np.asarray(self.kc[li][shard, page]))
            out.append(np.asarray(self.vc[li][shard, page]))
        return out

    def poke_page(self, shard: int, page: int, layer: int,
                  array) -> None:
        """Overwrite one physical K block's content (the chaos drill's
        write-back path — a deterministic stand-in for an in-memory
        bit flip)."""
        import jax.numpy as jnp
        kc = list(self.kc)
        kc[layer] = kc[layer].at[shard, page].set(
            jnp.asarray(array, kc[layer].dtype))
        self.kc = tuple(kc)

    # -- sealing / integrity -----------------------------------------

    def seal(self, owner, shard: int, block_index: int, page: int) -> None:
        """Record the checksum of a just-completed (fully committed)
        block so :meth:`verify` can detect later corruption."""
        self._seals[(owner, shard, block_index)] = _page_digest(
            self.page_bytes(shard, page))

    def verify(self, owner, shard: int) -> list:
        """Re-hash every sealed block of ``owner`` against its recorded
        digest; returns the list of block indices that FAIL (empty ==
        intact)."""
        table = self.allocators[shard].table(owner)
        bad = []
        for (o, s, bi), digest in self._seals.items():
            if o != owner or s != shard:
                continue
            if bi >= len(table):
                continue
            if _page_digest(self.page_bytes(s, table[bi])) != digest:
                bad.append(bi)
        return sorted(bad)

    def drop_seals(self, owner, shard: int) -> None:
        self._seals = {k: v for k, v in self._seals.items()
                       if not (k[0] == owner and k[1] == shard)}

    # -- bookkeeping shared with the engine --------------------------

    def free(self, owner, shard: int) -> int:
        """Release the owner's blocks (and seals) on one shard."""
        self.drop_seals(owner, shard)
        n = self.allocators[shard].free(owner)
        self._gauges()
        return n

    def ensure(self, owner, shard: int, n_tokens: int) -> tuple:
        added = self.allocators[shard].ensure(owner, n_tokens)
        if added:
            self._gauges()
        return added

    def occupancy(self) -> float:
        """Fraction of allocatable blocks currently owned (mean over
        dp shards)."""
        used = sum(a.n_used for a in self.allocators)
        return used / (self.n_blocks * self.dp)

    def fragmentation(self, used_tokens: dict) -> float:
        """Internal fragmentation: 1 − used-token-slots / allocated
        slots, given ``{(owner, shard): committed token count}``. Fixed
        blocks have no external fragmentation; the waste is the
        partially-filled tail block per request."""
        alloc_slots = sum(
            len(self.allocators[s].table(o)) * self.block_size
            for (o, s) in used_tokens)
        if not alloc_slots:
            return 0.0
        used = sum(min(v, len(self.allocators[s].table(o))
                       * self.block_size)
                   for (o, s), v in used_tokens.items())
        return 1.0 - used / alloc_slots

    def _gauges(self) -> None:
        obs.gauge("serve.kv.occupancy", self.occupancy())
        obs.gauge("serve.kv.blocks_free",
                  sum(a.n_free for a in self.allocators))
