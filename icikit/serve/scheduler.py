"""Request admission brain: leases, retry-with-backoff, idempotent
commits.

This generalizes the lease-queue pattern that made ``solve_dynamic``
self-healing (``models/solitaire/scheduler.py:_LeaseQueue``) from
*chunks of a fixed dataset* to *requests arriving over time*:

- ``submit`` enqueues a request (optionally time-gated — the Poisson
  bench submits the whole trace up front with per-request
  ``visible_after`` offsets, so arrival timing is part of the workload,
  not of the feeding code);
- ``claim`` hands a queued request to an engine under a **lease**. An
  engine that keeps running renews the lease every step; an engine
  that dies stops renewing, the lease expires, and ``reap_expired``
  puts the request back at the queue head — the dead-request
  abandonment story, drill-tested in ``tests/test_serve_chaos.py``;
- ``fail`` re-queues with bounded exponential **backoff** (transient
  failures: pool preemption, injected faults, KV-integrity mismatch)
  until ``max_retries`` is spent, then parks the request in ``failed``
  with its error — a poisoned prompt skips retries entirely
  (``retry=False``): re-decoding garbage is not a recovery strategy;
- ``complete`` is **idempotent**: the first commit wins, a late
  duplicate (an abandoned engine finishing after its lease was
  reissued) changes nothing and is surfaced on the obs bus, exactly
  the ``_LeaseQueue.commit`` contract.

Deterministic ids, monotonic clocks (SLO math must survive wall-clock
steps), bus/metric emission outside the lock (the ``mark_dead``
discipline: a slow sink must never stall admission).

**Journal hooks (fleet HA, r18)**: when a durable journal is attached
(``self.journal = icikit.fleet.journal.Journal(...).append``), every
mutation verb appends one record describing its *effect* — resolved
ids, computed visibility instants, popped heap entries — from inside
the verb's final lock section, i.e. BEFORE the verb returns and
therefore before any RPC ack reaches an engine. Replay
(:meth:`apply_record`) re-applies effects verbatim and never
re-decides anything, so a journal prefix reconstructs the queue
bitwise (:meth:`state_digest`). Lease *deadlines* are deliberately
not journaled: they are leader-local liveness state, re-based to
``now + lease_s`` on restore — a replayed leader re-times every
in-flight claim and lets its own reaper settle the truth.
"""

from __future__ import annotations

import hashlib
import heapq
import itertools
import json
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from icikit import obs
from icikit.obs import trace_ctx

DEFAULT_LEASE_S = 30.0


def prompt_checksum(prompt) -> str:
    """Submit-time fingerprint the engine re-verifies at admission —
    any corruption of the prompt bytes in between is detected
    mechanically, not probabilistically. Stamped inside ``submit``
    BEFORE the request becomes claimable, so no engine can ever admit
    an unfingerprinted request."""
    return hashlib.blake2b(
        np.ascontiguousarray(np.asarray(prompt, np.int32)).tobytes(),
        digest_size=16).hexdigest()


class PoisonedPromptError(ValueError):
    """A request whose prompt fails admission validation (token ids
    out of vocabulary range, over-length, or a submit-time checksum
    mismatch — the SDC drill's detection path). Not retryable: the
    prompt itself is the fault."""


@dataclass
class Request:
    """One serving request plus its lifecycle telemetry. Timestamps are
    ``time.monotonic`` values; ``None`` until the event happens."""

    rid: str
    prompt: np.ndarray           # int32 (s,)
    n_new: int
    checksum: str | None = None  # prompt fingerprint (set by submit)
    eos_id: int | None = None
    # int8-KV routing: on a kv_quant="mixed" engine a quant request's
    # cache pages live in the int8 arena (its tokens may differ from
    # the fp path within the measured top-1-agreement bar) while
    # co-batched fp requests stay bitwise untouched; on an "int8"
    # engine every request is quantized regardless of the flag
    quant: bool = False
    # sampling contract (r12): temperature > 0 makes this a SAMPLED
    # request — its tokens are drawn from the temperature/top-k/top-p
    # filtered distribution under the schedule-invariant counter keys
    # fold_in(fold_in(key(0), seed), position), so the continuation is
    # a pure function of (prompt, seed, knobs): bitwise identical to
    # single-request sample_generate(key=key(0), seeds=[seed]) and
    # bitwise reproducible across lease-reap reissue to another
    # engine. temperature == 0 (default) is greedy, bitwise unchanged
    # from the pre-r12 engine.
    seed: int = 0
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    visible_after: float = 0.0   # arrival time (monotonic)
    max_retries: int = 2
    # prompt positions served from the prefix cache at the (latest)
    # admission — stamped by the engine so per-request SLO records
    # carry the cache's contribution next to the latency it bought
    prefix_hit_tokens: int = 0
    # lifecycle
    state: str = "queued"        # queued|running|done|failed
    attempts: int = 0
    # claim generation: bumped on every claim; engines capture it at
    # admission and stamp it on renew/complete/fail/release so a
    # stalled engine whose lease was reaped and reissued can no longer
    # act on the request (its stamp no longer matches the live lease)
    claim_seq: int = 0
    # tokens COMMITTED by a prefill->decode handoff (the fleet role
    # split): this prefix is already folded into the prompt and is
    # part of the request's answer — a later requeue (fail / release
    # / lease reap) resets the attempt's tokens back to this
    # frontier, never past it, or the reissued decode would recompute
    # one position too many and drop the handed-off token(s)
    handoff_tokens: int = 0
    tokens: list = field(default_factory=list)
    error: str | None = None
    preempted: int = 0
    # SLO marks
    arrival_t: float = 0.0
    admit_t: float | None = None
    first_token_t: float | None = None
    done_t: float | None = None
    # worst inter-token stall (ms), stamped by the engine at
    # completion: mean TPOT dilutes a one-off admission stall over
    # the whole decode; this is the stall itself — the metric the
    # chunked-prefill latency cap exists to bound
    max_gap_ms: float | None = None
    # request-scoped trace context (obs.trace_ctx.TraceCtx): minted at
    # submit, rides the request across engines — the ONE span tree per
    # request, attempts linked by reissued_from across lease reaps
    trace: trace_ctx.TraceCtx | None = None

    def slo(self) -> dict:
        """TTFT / TPOT / queue-wait in ms (None where the phase never
        happened). TPOT counts the steady-state tokens: total decode
        time after the first token over ``n_generated - 1``."""
        out = {"rid": self.rid, "state": self.state,
               "attempts": self.attempts, "preempted": self.preempted,
               "n_tokens": len(self.tokens),
               "prefix_hit_tokens": self.prefix_hit_tokens}
        if self.admit_t is not None:
            out["queue_wait_ms"] = (self.admit_t - self.arrival_t) * 1e3
        if self.first_token_t is not None:
            out["ttft_ms"] = (self.first_token_t - self.arrival_t) * 1e3
        if (self.done_t is not None and self.first_token_t is not None
                and len(self.tokens) > 1):
            out["tpot_ms"] = ((self.done_t - self.first_token_t)
                              / (len(self.tokens) - 1)) * 1e3
        if self.max_gap_ms is not None:
            out["max_gap_ms"] = self.max_gap_ms
        return out


class RequestQueue:
    """Arrival queue + lease table + terminal stores.

    Invariant (the ``_LeaseQueue`` discipline): every request is in
    exactly one of queued / leased / done / failed, so ``drained()``
    is simply "queued and leased both empty" — plus the transient
    requeue **limbo** (lease dropped, trace transitions settling
    outside the lock, heap entry not yet pushed), which ``drained()``
    and ``pending()`` count so no engine exits while a reissue is
    mid-flight.
    """

    def __init__(self, lease_s: float = DEFAULT_LEASE_S,
                 backoff_s: float = 0.05):
        self.lease_s = lease_s
        self.backoff_s = backoff_s
        self._lock = threading.Lock()
        self._ids = itertools.count()
        # high-water mark of minted heap seqs: rides every journal
        # record and snapshot so a REPLAYED queue resumes minting
        # strictly past everything the dead leader ever allocated
        # (rids are f"r{seq}" — a collision would alias two requests)
        self._seq_hwm = -1
        # journal hook (fleet HA): None, or a callable
        # ``(verb, record_dict) -> None`` that appends to a durable
        # log. Called via _journal() from inside each verb's final
        # lock section — append-before-ack by construction.
        self.journal = None
        # min-heap of (visible_after, seq, rid): time-gated FIFO
        self._queued: list = []
        self._requests: dict = {}     # rid -> Request
        self._leases: dict = {}       # rid -> deadline (monotonic)
        # requests mid-requeue (lease dropped, heap entry not yet
        # pushed): their trace transitions run outside the lock and
        # must FINISH before the rid is claimable again, so the
        # requeue is two-phase — this counter keeps drained()/pending()
        # honest inside that window
        self._limbo = 0
        self.done: dict = {}          # rid -> Request
        self.failed: dict = {}        # rid -> Request
        self.n_reissues = 0
        self.n_duplicate_commits = 0

    # -- journal plumbing --------------------------------------------

    def _next_seq(self) -> int:
        """Mint one heap seq (lock held) and advance the high-water
        mark the journal/snapshot carries."""
        seq = next(self._ids)
        if seq > self._seq_hwm:
            self._seq_hwm = seq
        return seq

    def _journal(self, verb: str, rec: dict) -> None:
        """Append one verb record to the attached journal (lock held —
        the append lands before the verb returns, so the RPC ack the
        coordinator sends afterwards is always covered). A plain
        callable indirection: the actual file I/O lives in
        ``icikit.fleet.journal`` so this module stays free of it.
        The ``journal-discipline`` analysis rule checks every mutating
        verb routes through here."""
        if self.journal is not None:
            self.journal(verb, rec)

    # -- producer side -----------------------------------------------

    def submit(self, prompt, n_new: int, eos_id: int | None = None,
               not_before: float | None = None,
               max_retries: int = 2, quant: bool = False,
               seed: int = 0, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0) -> str:
        """Enqueue one request; returns its id. ``not_before`` is an
        absolute ``time.monotonic`` instant (None = now) — the Poisson
        bench's arrival process. ``quant`` routes the request's KV
        pages to the int8 arena on a mixed-precision engine.
        ``temperature > 0`` makes the request sampled under its own
        ``seed`` stream (see :class:`Request`); the knobs are
        validated here so no engine can ever claim an ill-posed
        sampling contract."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if n_new < 1:
            raise ValueError(f"n_new must be >= 1, got {n_new}")
        if not temperature >= 0.0:       # also rejects NaN
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        now = time.monotonic()
        vis = now if not_before is None else float(not_before)
        with self._lock:
            seq = self._next_seq()
        rid = f"r{seq}"
        req = Request(rid=rid, prompt=prompt, n_new=int(n_new),
                      checksum=prompt_checksum(prompt),
                      eos_id=eos_id, visible_after=vis,
                      max_retries=max_retries, arrival_t=vis,
                      quant=bool(quant), seed=int(seed),
                      temperature=float(temperature),
                      top_k=int(top_k), top_p=float(top_p))
        req.trace = trace_ctx.mint(rid)
        # tree root + first queued segment open BEFORE the request
        # becomes claimable (and outside the lock — the mark_dead
        # discipline): a concurrent engine claiming the instant the
        # heap push lands must find the root already open, or its
        # attempt segment would sit UNDER the root in the LIFO stack
        # and the terminal close would pop the wrong spans
        req.trace.open("serve.req", n_new=int(n_new))
        req.trace.open("serve.req.queued")
        with self._lock:
            self._requests[rid] = req
            heapq.heappush(self._queued, (vis, seq, rid))
            self._journal("submit", {
                "rid": rid, "seq": seq,
                "prompt": [int(t) for t in prompt],
                "n_new": int(n_new),
                "eos_id": None if eos_id is None else int(eos_id),
                "vis": vis, "max_retries": int(max_retries),
                "quant": bool(quant), "seed": int(seed),
                "temperature": float(temperature),
                "top_k": int(top_k), "top_p": float(top_p),
                "trace_id": req.trace.trace_id})
        obs.count("serve.submitted")
        return rid

    # -- engine side -------------------------------------------------

    def claim(self, accept=None) -> Request | None:
        """Pop the oldest *visible* queued request under a fresh lease,
        or None (nothing visible right now — ``next_visible_in`` says
        how long until something is). Heap entries are lazily deleted:
        an entry whose request is no longer ``queued`` (a stale
        duplicate from a reap racing a stale engine's fail) is
        discarded, so one request can never be admitted twice.
        ``accept`` is an optional cheap pure predicate over the
        Request (it runs under the queue lock): requests it declines
        are skipped WITHOUT losing their heap position — the fleet
        coordinator's role-eligibility filter (a prefill-phase request
        is invisible to a decode-only engine and vice versa)."""
        now = time.monotonic()
        claimed = None
        claimed_entry = None
        skipped = []
        dropped = []
        with self._lock:
            while self._queued and self._queued[0][0] <= now:
                entry = heapq.heappop(self._queued)
                rid = entry[2]
                req = self._requests[rid]
                if req.state != "queued":
                    dropped.append(entry)   # stale duplicate entry
                    continue
                if accept is not None and not accept(req):
                    skipped.append(entry)   # ineligible, not stale
                    continue
                req.state = "running"
                req.attempts += 1
                req.claim_seq += 1
                self._leases[rid] = (now + self.lease_s, req.claim_seq)
                claimed = req
                claimed_entry = entry
                break
            for entry in skipped:
                heapq.heappush(self._queued, entry)
            if claimed is not None or dropped:
                # skipped entries went back untouched — only the
                # claim and the lazy deletions are state changes
                self._journal("claim", {
                    "rid": claimed.rid if claimed else None,
                    "claim_seq":
                        claimed.claim_seq if claimed else None,
                    "entry": list(claimed_entry)
                        if claimed_entry else None,
                    "dropped": [list(e) for e in dropped]})
        if claimed is not None:
            claimed.trace.close("serve.req.queued")
            claimed.trace.begin_attempt(claimed.claim_seq,
                                        attempt=claimed.attempts)
        return claimed

    def next_visible_in(self) -> float | None:
        """Seconds until the head of the queue becomes visible (<= 0 ==
        visible now); None when the queue is empty."""
        with self._lock:
            if not self._queued:
                return None
            head = self._queued[0][0]
        return head - time.monotonic()

    def _lease_live(self, rid: str, seq: int | None) -> bool:
        """Caller-holds-the-lease check (lock held): with a ``seq``
        stamp, the live lease must carry that exact claim generation —
        a stalled engine whose request was reaped/reissued fails this
        and its late mutation becomes a no-op."""
        if seq is None:
            return True   # legacy callers without a stamp
        lease = self._leases.get(rid)
        return lease is not None and lease[1] == seq

    def renew(self, rid: str, seq: int | None = None) -> None:
        """Heartbeat: push the lease deadline out (the engine calls
        this for every in-flight request at every step boundary).
        Deliberately NOT journaled: deadlines are leader-local
        liveness state (see the module docstring) — journaling every
        heartbeat would dominate the log for zero replay value."""
        now = time.monotonic()
        with self._lock:
            if rid in self._leases and self._lease_live(rid, seq):
                self._leases[rid] = (now + self.lease_s,
                                     self._leases[rid][1])

    def complete(self, rid: str, tokens,
                 seq: int | None = None) -> bool:
        """Idempotent terminal commit; True on the first commit. Late
        commits (request already terminal, or the caller's lease was
        reaped and reissued) change nothing — a ``failed`` request is
        never resurrected by a straggler."""
        now = time.monotonic()
        with self._lock:
            req = self._requests.get(rid)
            dup = (req is None or req.state in ("done", "failed")
                   or not self._lease_live(rid, seq))
            if not dup:
                self._leases.pop(rid, None)
                req.state = "done"
                req.tokens = [int(t) for t in tokens]
                req.done_t = now
                self.done[rid] = req
            self._journal("complete", {
                "rid": rid, "dup": bool(dup),
                "tokens": None if dup else list(req.tokens),
                "done_t": None if dup else now})
        if dup:
            self.n_duplicate_commits += 1
            obs.emit("serve.duplicate_commit", rid=rid)
            # the watch layer's zero-rate alarm consumes the counter
            # form (events are not windowable)
            obs.count("serve.duplicate_commits")
            return False
        obs.count("serve.completed")
        req.trace.end_attempt()
        req.trace.close("serve.req", state="done",
                        n_tokens=len(req.tokens))
        return True

    def handoff(self, rid: str, tokens, seq: int | None = None) -> str:
        """Prefill → decode handoff (the fleet's DistServe-style role
        split): commit this attempt's ``tokens`` (the prefill engine's
        first token(s)), EXTEND the prompt by them, and requeue the
        request so a decode-capable engine claims the continuation.
        Because sampled draws are keyed by *absolute position* under
        the per-request counter stream (r12), the continuation decoded
        from the extended prompt is bitwise the tail of the original
        request's stream — the handoff is invisible in the committed
        tokens. Returns the request's new state (``"done"`` when the
        handed-off tokens already finish it — n_new exhausted or EOS —
        ``"queued"`` otherwise, ``"stale"`` for fenced-out callers).
        Like ``release``, a handoff burns no retry (attempts counts
        *failures*, and this attempt succeeded); like ``complete``,
        a stale caller (lease reaped and reissued) is a no-op counted
        as a duplicate commit. One request stays ONE trace tree: the
        attempt segment closes with ``outcome="handoff"`` and the next
        queued segment opens under the same trace id."""
        tokens = [int(t) for t in tokens]
        now = time.monotonic()
        finished = False
        with self._lock:
            req = self._requests.get(rid)
            if req is None or req.state in ("done", "failed") \
                    or not self._lease_live(rid, seq):
                dup = True
                self._journal("handoff", {"rid": rid,
                                          "outcome": "stale"})
            else:
                dup = False
                self._leases.pop(rid, None)
                req.tokens = list(req.tokens) + tokens
                req.handoff_tokens = len(req.tokens)
                finished = (len(req.tokens) >= req.n_new
                            or (req.eos_id is not None and tokens
                                and tokens[-1] == req.eos_id))
                if finished:
                    req.state = "done"
                    req.done_t = now
                    self.done[rid] = req
                    self._journal("handoff", {
                        "rid": rid, "outcome": "done",
                        "tokens": tokens, "done_t": now})
                else:
                    # the committed tokens become prompt: the decode
                    # phase admits (prompt ++ tokens) and generates the
                    # remaining n_new - len(tokens) positions. The
                    # checksum re-stamps BEFORE the request is
                    # claimable again, preserving the submit-time
                    # fingerprint contract at the new prompt.
                    req.prompt = np.concatenate(
                        [req.prompt,
                         np.asarray(tokens, np.int32)])
                    req.checksum = prompt_checksum(req.prompt)
                    req.state = "queued"
                    req.attempts -= 1     # a handoff is not a failure
                    self._limbo += 1
        if dup:
            self.n_duplicate_commits += 1
            obs.emit("serve.duplicate_commit", rid=rid)
            obs.count("serve.duplicate_commits")
            return "stale"
        obs.count("serve.handoffs")
        obs.emit("serve.request_handoff", rid=rid,
                 n_tokens=len(tokens), finished=finished)
        req.trace.end_attempt(outcome="handoff")
        if finished:
            req.trace.close("serve.req", state="done",
                            n_tokens=len(req.tokens))
            obs.count("serve.completed")
            return "done"
        req.trace.instant("serve.req.handoff", n_tokens=len(tokens))
        req.trace.open("serve.req.queued")
        with self._lock:
            push_seq = self._next_seq()
            heapq.heappush(self._queued, (now, push_seq, rid))
            self._limbo -= 1
            # one record covers both lock phases: between them the rid
            # is out of the heap with its lease popped, so no other
            # verb can interleave a mutation of THIS request — the
            # record is still a serialization point for it
            self._journal("handoff", {
                "rid": rid, "outcome": "queued", "tokens": tokens,
                "vis": now, "push_seq": push_seq})
        return "queued"

    def fail(self, rid: str, exc: BaseException,
             retry: bool = True, seq: int | None = None) -> str:
        """Record a failed attempt. Retryable failures re-queue with
        exponential backoff until ``max_retries`` extra attempts are
        spent; returns the request's new state. Stale callers (lease
        reaped and reissued elsewhere) are no-ops."""
        requeued = False
        now = time.monotonic()
        with self._lock:
            req = self._requests.get(rid)
            if req is None or req.state in ("done", "failed") \
                    or not self._lease_live(rid, seq):
                return "stale"
            self._leases.pop(rid, None)
            req.error = repr(exc)
            if retry and req.attempts <= req.max_retries:
                delay = self.backoff_s * (2 ** (req.attempts - 1))
                vis = now + delay
                req.state = "queued"
                req.tokens = req.tokens[:req.handoff_tokens]
                if not req.handoff_tokens:
                    req.first_token_t = None
                self._limbo += 1    # claimable only after ctx settles
                requeued = True
            else:
                req.state = "failed"
                self.failed[rid] = req
                self._journal("fail", {
                    "rid": rid, "error": req.error,
                    "requeued": False})
        obs.emit("serve.request_failed", rid=rid, error=repr(exc),
                 requeued=requeued)
        obs.count("serve.retries" if requeued else "serve.failed")
        req.trace.end_attempt(outcome="failed")
        req.trace.instant("serve.req.retry" if requeued
                          else "serve.req.failed", error=repr(exc))
        if requeued:
            # two-phase requeue: the trace transitions above must be
            # on the buffer before a concurrent engine can claim the
            # rid and open the next attempt segment
            req.trace.open("serve.req.queued")
            with self._lock:
                push_seq = self._next_seq()
                heapq.heappush(self._queued, (vis, push_seq, rid))
                self._limbo -= 1
                self._journal("fail", {
                    "rid": rid, "error": req.error,
                    "requeued": True, "vis": vis,
                    "push_seq": push_seq})
        else:
            req.trace.close("serve.req", state="failed")
        return "queued" if requeued else "failed"

    def release(self, rid: str, delay: float = 0.0,
                seq: int | None = None) -> None:
        """Hand a claimed request back WITHOUT burning a retry — the
        preemption path (the pool filled up around the request; the
        request itself did nothing wrong). ``delay`` gates its next
        visibility so a full engine does not spin on re-claiming it."""
        with self._lock:
            req = self._requests.get(rid)
            if req is None or req.state in ("done", "failed") \
                    or not self._lease_live(rid, seq):
                return
            self._leases.pop(rid, None)
            req.state = "queued"
            req.attempts -= 1
            req.tokens = req.tokens[:req.handoff_tokens]
            if not req.handoff_tokens:
                req.first_token_t = None
            req.preempted += 1
            self._limbo += 1        # claimable only after ctx settles
        obs.emit("serve.request_preempted", rid=rid)
        obs.count("serve.preemptions")
        req.trace.end_attempt(outcome="preempted")
        req.trace.instant("serve.req.preempted")
        req.trace.open("serve.req.queued")
        vis = time.monotonic() + delay
        with self._lock:
            push_seq = self._next_seq()
            heapq.heappush(self._queued, (vis, push_seq, rid))
            self._limbo -= 1
            self._journal("release", {
                "rid": rid, "vis": vis, "push_seq": push_seq})

    def stamp_marks(self, rid: str, marks: dict | None) -> None:
        """Fold engine-side SLO marks (admit/first-token instants,
        worst inter-token gap, prefix-cache hits) onto the
        authoritative Request — the fleet coordinator's per-commit
        call, moved into the queue (r18) so the fold is journaled and
        a replayed leader reports the same SLO rows. The fold is
        idempotent and first-writer-wins for the instants, max() for
        the gap, so duplicate commits cannot skew the numbers."""
        if not marks:
            return
        with self._lock:
            req = self._requests.get(rid)
            if req is None:
                return
            if req.admit_t is None and \
                    marks.get("admit_t") is not None:
                req.admit_t = float(marks["admit_t"])
            if req.first_token_t is None and \
                    marks.get("first_token_t") is not None:
                req.first_token_t = float(marks["first_token_t"])
            if marks.get("max_gap_ms") is not None:
                req.max_gap_ms = max(req.max_gap_ms or 0.0,
                                     float(marks["max_gap_ms"]))
            if marks.get("prefix_hit_tokens"):
                # accumulates: a handoff chain's prefill AND decode
                # admissions both contribute cache hits
                req.prefix_hit_tokens += \
                    int(marks["prefix_hit_tokens"])
            self._journal("marks", {
                "rid": rid,
                "marks": {k: marks.get(k) for k in (
                    "admit_t", "first_token_t", "max_gap_ms",
                    "prefix_hit_tokens") if marks.get(k) is not None}})

    # -- monitor side ------------------------------------------------

    def reap_expired(self) -> list:
        """Re-queue every request whose lease outlived its engine (the
        dead-request abandonment path); returns the reaped rids."""
        now = time.monotonic()
        reaped = []
        reaped_reqs = []
        with self._lock:
            for rid, (deadline, seq) in list(self._leases.items()):
                if deadline > now:
                    continue
                del self._leases[rid]
                req = self._requests[rid]
                req.state = "queued"
                req.tokens = req.tokens[:req.handoff_tokens]
                if not req.handoff_tokens:
                    req.first_token_t = None
                reaped.append(rid)
                reaped_reqs.append((req, seq))
            self.n_reissues += len(reaped)
            self._limbo += len(reaped)
        if reaped:
            obs.emit("serve.lease_expired", rids=reaped)
            obs.count("serve.reissues", len(reaped))
            for req, seq in reaped_reqs:
                # the dead engine can no longer close what it opened:
                # abandon closes every open span of the tree (stamped
                # closed_by) and records the reaped claim generation —
                # the NEXT attempt opens with reissued_from=seq, the
                # one-request-one-tree continuity edge. Two-phase
                # requeue: these transitions land BEFORE the second
                # lock pushes the rid back into the heap, so a
                # concurrent engine cannot claim-and-begin the next
                # attempt while abandon is still closing the last one
                req.trace.abandon("lease_reaped", seq=seq)
                req.trace.instant("serve.req.reissued", from_seq=seq)
                req.trace.open("serve.req.queued")
            with self._lock:
                pushes = []
                for req, _ in reaped_reqs:
                    push_seq = self._next_seq()
                    heapq.heappush(self._queued,
                                   (now, push_seq, req.rid))
                    pushes.append([req.rid, push_seq])
                self._limbo -= len(reaped)
                self._journal("reap", {"reaped": pushes, "vis": now})
        return reaped

    def expire(self, rids) -> list:
        """Force the named leases to expire NOW and reap them — the
        fleet coordinator's move when it *knows* an engine is gone or
        defective (heartbeat stopped, or its results failed integrity
        verification): waiting out the natural lease deadline would
        just delay the reissue. Requests the caller names that hold no
        live lease are ignored. Returns the reaped rids (a superset
        may reap if other leases happen to be expired too — reap is
        global by design). The deadline poisoning itself is not
        journaled (deadlines never are); the ``reap`` record emitted
        by :meth:`reap_expired` carries the whole durable effect."""
        with self._lock:
            for rid in rids:
                if rid in self._leases:
                    self._leases[rid] = (float("-inf"),
                                         self._leases[rid][1])
        return self.reap_expired()

    def pending_prompts(self) -> list:
        """Prompts of every currently-queued request, in visibility
        order — the restart-rewarm hook (r16): a fresh engine pointed
        at a recovered queue (crash restart, lease reissue) hands this
        to ``Engine.rewarm`` so the persistent prefix store is warmed
        for exactly the work about to be served, before the first
        claim. Copies are cheap (prompt arrays are shared, the list is
        new); stale heap duplicates are filtered like ``claim`` does."""
        with self._lock:
            out = []
            seen = set()
            for _, _, rid in sorted(self._queued):
                req = self._requests[rid]
                if req.state == "queued" and rid not in seen:
                    seen.add(rid)
                    out.append(req.prompt)
            return out

    def drained(self) -> bool:
        with self._lock:
            return (not self._queued and not self._leases
                    and not self._limbo)

    def pending(self) -> int:
        with self._lock:
            return len(self._queued) + len(self._leases) + self._limbo

    def request(self, rid: str) -> Request:
        with self._lock:
            return self._requests[rid]

    # -- journal / HA side (fleet r18) -------------------------------
    #
    # Serialization, snapshot and replay live ON the queue (not in
    # icikit.fleet.journal) so every touch of the private containers
    # stays in this file — the journal-discipline rule bans the fleet
    # layer from reaching into queue internals. apply_record() is the
    # replay twin of the verbs above: it applies recorded EFFECTS
    # verbatim (no clocks consulted except to re-base lease deadlines,
    # no ids minted, no trace/obs emission re-fired) so that
    # state_digest() after replaying any record prefix equals the live
    # queue's digest at the same point — the property
    # tests/test_fleet_ha.py fuzzes.

    def _ser_req_locked(self, req: Request) -> dict:
        return {
            "rid": req.rid, "prompt": [int(t) for t in req.prompt],
            "n_new": req.n_new, "checksum": req.checksum,
            "eos_id": req.eos_id, "quant": req.quant,
            "seed": req.seed, "temperature": req.temperature,
            "top_k": req.top_k, "top_p": req.top_p,
            "visible_after": req.visible_after,
            "max_retries": req.max_retries,
            "prefix_hit_tokens": req.prefix_hit_tokens,
            "state": req.state, "attempts": req.attempts,
            "claim_seq": req.claim_seq,
            "handoff_tokens": req.handoff_tokens,
            "tokens": [int(t) for t in req.tokens],
            "error": req.error, "preempted": req.preempted,
            "arrival_t": req.arrival_t, "admit_t": req.admit_t,
            "first_token_t": req.first_token_t,
            "done_t": req.done_t, "max_gap_ms": req.max_gap_ms,
            "trace_id":
                req.trace.trace_id if req.trace else None,
        }

    def _serialize_locked(self) -> dict:
        """Canonical full-state dict (lock held). The heap is emitted
        SORTED: heapq's internal array order depends on push/pop
        history, but the set of entries plus the heap property is the
        whole semantic content — canonicalizing makes live-vs-replayed
        digests comparable."""
        return {
            "next_seq": self._seq_hwm + 1,
            "queued": [list(e) for e in sorted(self._queued)],
            "leases": {rid: lease[1]
                       for rid, lease in self._leases.items()},
            "limbo": self._limbo,
            "requests": {rid: self._ser_req_locked(req)
                         for rid, req in self._requests.items()},
            "done": sorted(self.done),
            "failed": sorted(self.failed),
            "n_reissues": self.n_reissues,
            "n_duplicate_commits": self.n_duplicate_commits,
        }

    def state_digest(self) -> str:
        """Order-independent fingerprint of the queue's durable state
        (lease deadlines excluded — leader-local). Bitwise equality of
        digests is the replay acceptance bar."""
        with self._lock:
            state = self._serialize_locked()
        blob = json.dumps(state, sort_keys=True,
                          separators=(",", ":"), allow_nan=False)
        return hashlib.blake2b(blob.encode(),
                               digest_size=16).hexdigest()

    def checkpoint(self, meta: dict | None = None) -> dict | None:
        """Append the full state as one ``snap`` journal record — the
        compaction point replay starts from. Refuses (returns None)
        while a two-phase requeue is settling: a snapshot taken inside
        that window would capture the first half of a verb whose
        single record then re-applies the whole effect on replay —
        the caller (the coordinator's reap loop) just retries next
        tick. ``meta`` carries coordinator-side state (phases, owners)
        that must ride the same compaction point."""
        with self._lock:
            if self._limbo:
                return None
            state = self._serialize_locked()
            self._journal("snap", {"state": state,
                                   "meta": meta or {}})
        return state

    def _restore_locked(self, state: dict, now: float) -> None:
        self._seq_hwm = int(state["next_seq"]) - 1
        self._ids = itertools.count(self._seq_hwm + 1)
        self._queued = [(e[0], e[1], e[2])
                        for e in state["queued"]]
        heapq.heapify(self._queued)
        self._limbo = int(state["limbo"])
        self._requests = {}
        for rid, s in state["requests"].items():
            req = Request(
                rid=rid,
                prompt=np.asarray(s["prompt"], np.int32),
                n_new=int(s["n_new"]), checksum=s["checksum"],
                eos_id=s["eos_id"], quant=bool(s["quant"]),
                seed=int(s["seed"]),
                temperature=float(s["temperature"]),
                top_k=int(s["top_k"]), top_p=float(s["top_p"]),
                visible_after=s["visible_after"],
                max_retries=int(s["max_retries"]),
                arrival_t=s["arrival_t"])
            req.prefix_hit_tokens = int(s["prefix_hit_tokens"])
            req.state = s["state"]
            req.attempts = int(s["attempts"])
            req.claim_seq = int(s["claim_seq"])
            req.handoff_tokens = int(s["handoff_tokens"])
            req.tokens = list(s["tokens"])
            req.error = s["error"]
            req.preempted = int(s["preempted"])
            req.admit_t = s["admit_t"]
            req.first_token_t = s["first_token_t"]
            req.done_t = s["done_t"]
            req.max_gap_ms = s["max_gap_ms"]
            req.trace = trace_ctx.adopt(rid, s["trace_id"],
                                        req.claim_seq)
            self._requests[rid] = req
        # deadlines re-based: the restoring leader re-times every
        # in-flight claim and lets its own reaper settle liveness
        self._leases = {rid: (now + self.lease_s, int(seq))
                        for rid, seq in state["leases"].items()}
        self.done = {rid: self._requests[rid]
                     for rid in state["done"]}
        self.failed = {rid: self._requests[rid]
                       for rid in state["failed"]}
        self.n_reissues = int(state["n_reissues"])
        self.n_duplicate_commits = int(state["n_duplicate_commits"])

    def _discard_entry_locked(self, e) -> None:
        """Remove one recorded heap entry during replay (the live verb
        popped it; lazy deletions and claims name entries exactly)."""
        entry = (e[0], e[1], e[2])
        try:
            self._queued.remove(entry)
        except ValueError:
            return
        heapq.heapify(self._queued)

    def apply_record(self, verb: str, rec: dict) -> None:
        """Replay one journal record (the standby/takeover path). Must
        only run on a queue that is not serving live traffic."""
        now = time.monotonic()   # lease re-basing only (not digested)
        with self._lock:
            if verb == "snap":
                self._restore_locked(rec["state"], now)
                return
            if verb == "submit":
                rid, seq = rec["rid"], int(rec["seq"])
                prompt = np.asarray(rec["prompt"], np.int32)
                req = Request(
                    rid=rid, prompt=prompt, n_new=int(rec["n_new"]),
                    checksum=prompt_checksum(prompt),
                    eos_id=rec["eos_id"], visible_after=rec["vis"],
                    max_retries=int(rec["max_retries"]),
                    arrival_t=rec["vis"], quant=bool(rec["quant"]),
                    seed=int(rec["seed"]),
                    temperature=float(rec["temperature"]),
                    top_k=int(rec["top_k"]),
                    top_p=float(rec["top_p"]))
                req.trace = trace_ctx.adopt(rid, rec["trace_id"], 0)
                self._requests[rid] = req
                heapq.heappush(self._queued,
                               (rec["vis"], seq, rid))
                if seq > self._seq_hwm:
                    self._seq_hwm = seq
            elif verb == "claim":
                for e in rec["dropped"]:
                    self._discard_entry_locked(e)
                if rec["rid"] is not None:
                    self._discard_entry_locked(rec["entry"])
                    req = self._requests[rec["rid"]]
                    req.state = "running"
                    req.attempts += 1
                    req.claim_seq = int(rec["claim_seq"])
                    self._leases[rec["rid"]] = (
                        now + self.lease_s, req.claim_seq)
            elif verb == "complete":
                if rec["dup"]:
                    self.n_duplicate_commits += 1
                else:
                    req = self._requests[rec["rid"]]
                    self._leases.pop(rec["rid"], None)
                    req.state = "done"
                    req.tokens = list(rec["tokens"])
                    req.done_t = rec["done_t"]
                    self.done[rec["rid"]] = req
            elif verb == "handoff":
                self._apply_handoff_locked(rec)
            elif verb == "fail":
                req = self._requests[rec["rid"]]
                self._leases.pop(rec["rid"], None)
                req.error = rec["error"]
                if rec["requeued"]:
                    self._requeue_locked(req, rec["vis"],
                                         int(rec["push_seq"]))
                else:
                    req.state = "failed"
                    self.failed[rec["rid"]] = req
            elif verb == "release":
                req = self._requests[rec["rid"]]
                self._leases.pop(rec["rid"], None)
                req.attempts -= 1
                req.preempted += 1
                self._requeue_locked(req, rec["vis"],
                                     int(rec["push_seq"]))
            elif verb == "reap":
                for rid, push_seq in rec["reaped"]:
                    req = self._requests[rid]
                    self._leases.pop(rid, None)
                    self._requeue_locked(req, rec["vis"],
                                         int(push_seq))
                self.n_reissues += len(rec["reaped"])
            elif verb == "marks":
                req = self._requests.get(rec["rid"])
                m = rec["marks"]
                if req is not None:
                    if req.admit_t is None and \
                            m.get("admit_t") is not None:
                        req.admit_t = float(m["admit_t"])
                    if req.first_token_t is None and \
                            m.get("first_token_t") is not None:
                        req.first_token_t = \
                            float(m["first_token_t"])
                    if m.get("max_gap_ms") is not None:
                        req.max_gap_ms = max(
                            req.max_gap_ms or 0.0,
                            float(m["max_gap_ms"]))
                    if m.get("prefix_hit_tokens"):
                        req.prefix_hit_tokens += \
                            int(m["prefix_hit_tokens"])
            else:
                raise ValueError(
                    f"unknown journal verb {verb!r}")

    def _requeue_locked(self, req: Request, vis, push_seq: int):
        req.state = "queued"
        req.tokens = req.tokens[:req.handoff_tokens]
        if not req.handoff_tokens:
            req.first_token_t = None
        heapq.heappush(self._queued, (vis, push_seq, req.rid))
        if push_seq > self._seq_hwm:
            self._seq_hwm = push_seq

    def _apply_handoff_locked(self, rec: dict) -> None:
        if rec["outcome"] == "stale":
            self.n_duplicate_commits += 1
            return
        rid = rec["rid"]
        req = self._requests[rid]
        self._leases.pop(rid, None)
        tokens = list(rec["tokens"])
        req.tokens = list(req.tokens) + tokens
        req.handoff_tokens = len(req.tokens)
        if rec["outcome"] == "done":
            req.state = "done"
            req.done_t = rec["done_t"]
            self.done[rid] = req
        else:
            req.prompt = np.concatenate(
                [req.prompt, np.asarray(tokens, np.int32)])
            req.checksum = prompt_checksum(req.prompt)
            req.state = "queued"
            req.attempts -= 1
            heapq.heappush(self._queued,
                           (rec["vis"], int(rec["push_seq"]), rid))
            if rec["push_seq"] > self._seq_hwm:
                self._seq_hwm = int(rec["push_seq"])

    def finalize_replay(self) -> None:
        """Re-seed the id mint past every seq the journal recorded —
        called once when a replayed queue is promoted to live duty, so
        fresh submits can never collide with a dead leader's rids."""
        with self._lock:
            self._ids = itertools.count(self._seq_hwm + 1)
