"""Persistent content-addressed prefix store — the disk tier under
the KV pool.

The r11 prefix index and the r16 host spill tier both die with the
process: a restarted engine recomputes every shared system prompt from
scratch, which at production scale (long few-shot headers shared by
millions of sessions) is exactly the prefill the cache existed to
remove. This store is the Mooncake-style bottom tier rebuilt on the
repo's integrity discipline:

- **content-addressed** — one file per sealed KV block, named by the
  block's chain hash (``kvpool.block_hashes``). The chain hash commits
  to the block's entire token prefix *and* arena side, so the filename
  IS the lookup key: no manifest, no index file, nothing to corrupt
  besides the blocks themselves. Identical content written twice is
  one file (**last-writer-wins**, the ``ChunkCheckpoint`` duplicate
  rule — every writer of hash ``h`` holds bitwise the same bytes,
  because K/V is a pure function of the token prefix).
- **digest-carrying** — each file stores the block's payload arrays
  (K and V per layer; the q8 side adds the scale pages) plus the
  content digest computed *before* the bytes ever left the device
  arena. A loaded block re-verifies that digest at swap-in
  (``KVPool.restore_block``): a flipped disk byte, a torn write, or a
  stale-format file is **quarantined** (file removed, counter bumped)
  and the engine simply recomputes — a corrupt page is never trusted
  (the "Cores that don't count" posture, extended to disks).
- **crash-tolerant by validation, not by ceremony** — writes go
  straight to the final path under the shared bounded-backoff I/O
  retry (``chaos.io_retry``, the one retry policy every checkpoint
  writer in this repo uses); a writer that dies mid-write leaves a
  torn file that fails validation on load and is skipped/removed,
  exactly like a torn ``ChunkCheckpoint`` tail line (drilled via the
  ``serve.store.write`` die probe in ``tests/test_serve_tiered.py``).

Rewarm protocol: a restarted engine needs no scan — the admission
path's tier lookup (``KVPool.tier_plan``) consults ``has()`` on
demand, so the first request for a persisted prefix pulls its chain
straight from disk through the chunked restore path. ``Engine.rewarm``
is the eager variant (prime the pool for the queue's pending prompts
before serving — ``RequestQueue.pending_prompts`` is the restart
hook); the cold-vs-rewarm A/B lives in ``tools/tiered_kv_study.py``.
"""

from __future__ import annotations

import io
import json
import os
import pathlib
import zipfile

import numpy as np

from icikit import chaos, obs

# the disk tier's probe sites: io (flaky filesystem, retried with
# bounded backoff), die (torn-file drill — a write killed mid-bytes
# must be skipped at rewarm), delay on reads (slow disk)
chaos.register_site("serve.store.write", "serve.store.read")

# bump when the on-disk payload layout changes: a version-mismatched
# file is quarantined like a torn one (recompute beats misread)
_FORMAT = 1


class PrefixStore:
    """One directory of chain-hash-named ``.npz`` block files.

    The store is deliberately dumb: no manifest, no background
    compaction, no locking beyond the OS's atomic directory ops —
    every entry is independently valid or independently quarantined.
    Capacity policy is the filesystem's problem (the host/device tiers
    above do the LRU work); ``n_blocks``/``nbytes`` exist so benches
    can report what a run persisted.
    """

    SUFFIX = ".npz"

    def __init__(self, root):
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.n_writes = 0
        self.n_reads = 0
        self.n_quarantined = 0

    def _path(self, h: str) -> pathlib.Path:
        return self.root / f"{h}{self.SUFFIX}"

    def has(self, h: str) -> bool:
        return self._path(h).exists()

    def n_blocks(self) -> int:
        return sum(1 for _ in self.root.glob(f"*{self.SUFFIX}"))

    def nbytes(self) -> int:
        return sum(p.stat().st_size
                   for p in self.root.glob(f"*{self.SUFFIX}"))

    # -- write --------------------------------------------------------

    def put(self, h: str, side: str, digest: str, arrays) -> bool:
        """Persist one block's payload under its chain hash; returns
        False when the content is already present (content-addressed:
        a second writer of ``h`` holds identical bytes, so the first
        file stands). The write is one buffered byte stream to the
        final path — a crash mid-write leaves a torn file that
        :meth:`get` quarantines, which is the honest recovery story
        (recompute) rather than a pretend-atomic one."""
        path = self._path(h)
        if path.exists():
            return False
        meta = json.dumps({"format": _FORMAT, "side": side,
                           "digest": digest,
                           "n_arrays": len(arrays)}).encode()
        buf = io.BytesIO()
        np.savez(buf, meta=np.frombuffer(meta, np.uint8),
                 **{f"a{i}": np.asarray(a) for i, a in
                    enumerate(arrays)})
        data = buf.getvalue()

        def write():
            with open(path, "wb") as f:
                f.write(data[:len(data) // 2])
                f.flush()
                # the torn-file drill boundary: a die here leaves a
                # half-written file on disk, which MUST be skipped
                # (and removed) by the next get() — proven in
                # tests/test_serve_tiered.py
                chaos.maybe_die("serve.store.write")
                f.write(data[len(data) // 2:])
                f.flush()
                os.fsync(f.fileno())

        chaos.io_retry("serve.store.write", write)
        self.n_writes += 1
        return True

    # -- read ---------------------------------------------------------

    def get(self, h: str):
        """Load one block: ``(side, digest, arrays)`` or None when the
        hash is absent or the file fails validation (torn write, wrong
        format, bad metadata) — invalid files are removed so rewarm
        does not re-trip on them. Digest verification against the
        payload happens at swap-in (``KVPool.restore_block``), AFTER
        the ``serve.store.read`` corruption probe below, so an
        injected flipped byte exercises the real detection path."""
        path = self._path(h)
        if not path.exists():
            return None
        chaos.maybe_delay("serve.store.read")
        try:
            def read():
                with open(path, "rb") as f:
                    return f.read()
            raw = chaos.io_retry("serve.store.read", read)
            with np.load(io.BytesIO(raw)) as z:
                meta = json.loads(bytes(z["meta"].tobytes()))
                if meta.get("format") != _FORMAT:
                    raise ValueError("format mismatch")
                arrays = [z[f"a{i}"]
                          for i in range(int(meta["n_arrays"]))]
        except (OSError, ValueError, KeyError, json.JSONDecodeError,
                zipfile.BadZipFile):
            self.quarantine(h)
            return None
        # the persisted-byte SDC drill: rot between disk and arena —
        # applied after the bytes parsed, before the swap-in digest
        # verify that must catch it
        arrays[0] = chaos.maybe_corrupt("serve.store.read", arrays[0])
        self.n_reads += 1
        return meta["side"], meta["digest"], arrays

    def quarantine(self, h: str) -> None:
        """Remove one entry (validation/digest failure): no future
        rewarm may re-read the bad bytes. Idempotent."""
        try:
            self._path(h).unlink()
        except OSError:
            pass
        self.n_quarantined += 1
        obs.count("serve.store.quarantined")
