"""Zero-model-cost n-gram drafter: longest-suffix-match proposals.

The r8 verdict left speculative decode at break-even with a *trained*
drafter whose acceptance is corpus-bound (DECODE.md round 8); ROADMAP
item 3b's answer is a fallback ladder whose first rung costs nothing at
all: propose the continuation that followed the **last occurrence of
the current suffix** in the request's own prompt + generated text
(prompt-lookup decoding). No parameters, no drafting forward passes,
no extra cache writes — the verify pass already prices one full-stack
window per iteration, so every accepted n-gram token is pure profit
and a fully-rejected proposal costs exactly what ``k=1`` decode costs
plus nothing (the draft side is a handful of integer compares).

Acceptance is workload-dependent by construction: repetitive /
extractive streams (code, quotes, structured text) accept long runs;
high-entropy streams accept ~0 and degrade gracefully to the baseline.
Token identity is unconditional either way — proposals only ever enter
the model through the verify-and-accept window, which commits the full
model's argmax regardless of what was proposed
(``tests/test_ngram_draft.py`` pins it).

The proposer is written in JAX so it runs *inside* the jitted
speculative while-loop (``speculative_generate(..., drafter="ngram")``)
— per-row dynamic suffix lengths, no host sync — and the serving
engine reuses the same function under a tiny jit wrapper for its
host-side step loop.

Round 12 note — sampled serving: both matchers propose
**deterministically**, which is exactly what makes rejection-sampled
verification (``speculative_sample_generate``, the engine's sampled
``speculate_k`` path) collapse to its simplest exact form. A
deterministic proposal is a one-hot distribution q, so the standard
accept rule ``min(1, p(t)/q(t))`` becomes "accept the draft with
probability p(draft)" and the residual resample ``(p − q)+`` is a
draw from p conditioned off the draft — both implemented at once by
drawing t ~ p under the position's counter key and accepting iff t
equals the proposal. Token-level EXACTNESS is therefore
unconditional for sampled traffic the same way identity was for
greedy: proposals gate only how many weight passes a window costs,
never which keyed draw commits. (A future *stochastic* drafter would
need the general q-ratio bookkeeping; these matchers never do.)

Round 11 adds the **suffix-automaton upgrade**
(:class:`SuffixAutomaton`): the n-gram matcher caps matches at ``n``
tokens and rescans the whole buffer per proposal; the automaton
maintains the *unbounded* longest suffix of the committed stream that
occurred earlier, online, at O(1) amortized host work per committed
token — the natural next rung of the ROADMAP 3b drafter ladder. It is
a host-side data structure (its transitions grow dynamically, which a
jitted while-loop cannot express), so it serves the engine's host
step loop (``ServeConfig(drafter="suffix")``); the in-jit
``speculative_generate`` path keeps the windowed matcher. Matching
semantics differ only in the drafter's *guess* (longest-then-first
occurrence vs ``n``-capped-then-latest): token identity is
unconditional for both, because proposals only ever enter the model
through the verify-and-accept window.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp

DEFAULT_N = 3

# suffix-link states top_b consults per depth beyond the matched one
# (the shorter-suffix alternatives ladder) — a CONSTANT, so per-token
# drafting work stays O(1) in the stream length
_TOPB_LINK_HOPS = 4


def ngram_propose(seq, valid, k: int, n: int = DEFAULT_N):
    """Propose ``k - 1`` draft tokens per row by longest-suffix match.

    Args:
      seq: int32 ``(b, S)`` token buffer — committed tokens first
        (prompt followed by decided continuation), anything beyond
        ``valid`` is ignorable garbage.
      valid: int32 ``(b,)`` committed token count per row (may be
        traced — this runs inside the speculative while-loop).
      k: verify-window width; ``k - 1`` tokens are proposed.
      n: maximum suffix length to match (static, small).

    Returns:
      int32 ``(b, k - 1)`` proposals. Matching rule: score candidate
      end-positions ``j`` by the longest ``ℓ <= n`` with
      ``seq[j-ℓ+1 .. j] == seq[v-ℓ .. v-1]``, prefer longer matches
      then later positions, and propose the tokens following the
      winner. Rows with no match (or fewer than 2 committed tokens)
      fall back to repeating their last token — a guess like any
      other, priced identically by verify.
    """
    if k < 2:
        raise ValueError(f"k must be >= 2 to draft, got {k}")
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    b, S = seq.shape
    idx = jnp.arange(S)

    def row(seq_r, v):
        matchlen = jnp.zeros((S,), jnp.int32)
        cum = jnp.ones((S,), bool)
        for i in range(1, n + 1):
            # i-th token back from the frontier; -1 when the suffix is
            # shorter than i (matches nothing — tokens are >= 0)
            last_i = jnp.where(v - i >= 0,
                               seq_r[jnp.clip(v - i, 0, S - 1)], -1)
            # sh[j] = seq_r[j - i + 1] (left-pad: out-of-range never eq)
            sh = (seq_r if i == 1 else jnp.concatenate(
                [jnp.full((i - 1,), -1, seq_r.dtype),
                 seq_r[:S - i + 1]]))
            cum = cum & (sh == last_i)
            matchlen = matchlen + cum.astype(jnp.int32)
        # candidates end strictly before the suffix itself
        score = jnp.where(idx <= v - 2, matchlen * S + idx, -1)
        j = jnp.argmax(score)
        ml = jnp.where(score[j] >= 0, matchlen[j], 0)
        # proposal reads clamp to the committed frontier: positions
        # j+1+i with index >= v would read the UNWRITTEN tail of the
        # buffer (zeros — a guaranteed-rejected guess); repeating the
        # last committed token instead keeps every slot a real token
        prop_idx = jnp.minimum(j + 1 + jnp.arange(k - 1), v - 1)
        props = jnp.take(seq_r, jnp.clip(prop_idx, 0, S - 1))
        fallback = jnp.full((k - 1,),
                            seq_r[jnp.clip(v - 1, 0, S - 1)])
        return jnp.where(ml > 0, props, fallback).astype(jnp.int32)

    return jax.vmap(row)(seq, valid)


def ngram_propose_b(seq, valid, k: int, n: int = DEFAULT_N,
                    nb: int = 2):
    """Ranked b-way proposals for the token-tree verify window
    (round 14): the ``nb`` best suffix matches each contribute a
    continuation chain, and the depth-``i`` rank-``r`` alternative is
    the ``i``-th token following the ``r``-th best match.

    Ranking is the scalar the 1-way matcher already maximizes —
    ``matchlen * S + position`` (longer match first, then later
    occurrence) — taken top-``nb`` instead of argmax, so rank 0 is
    bitwise :func:`ngram_propose`'s proposal and ranks are stable
    under recomputation (the score has no ties: position is a
    tiebreak by construction). Ranks beyond the available positive-
    score matches fall back to repeating the row's last committed
    token — a guess like any other, priced (and policed) by the
    verify window exactly like every proposal.

    Returns int32 ``(b, k - 1, nb)``. O(S·n) per row per call, the
    same asymptotics as the 1-way matcher — the extra ranks reuse the
    one scored scan."""
    if k < 2:
        raise ValueError(f"k must be >= 2 to draft, got {k}")
    if nb < 1:
        raise ValueError(f"nb must be >= 1, got {nb}")
    b, S = seq.shape
    if nb > S:
        raise ValueError(f"nb={nb} exceeds the token buffer ({S})")
    idx = jnp.arange(S)

    def row(seq_r, v):
        matchlen = jnp.zeros((S,), jnp.int32)
        cum = jnp.ones((S,), bool)
        for i in range(1, n + 1):
            last_i = jnp.where(v - i >= 0,
                               seq_r[jnp.clip(v - i, 0, S - 1)], -1)
            sh = (seq_r if i == 1 else jnp.concatenate(
                [jnp.full((i - 1,), -1, seq_r.dtype),
                 seq_r[:S - i + 1]]))
            cum = cum & (sh == last_i)
            matchlen = matchlen + cum.astype(jnp.int32)
        score = jnp.where(idx <= v - 2, matchlen * S + idx, -1)
        top_scores, js = jax.lax.top_k(score, nb)       # (nb,)
        ml = jnp.where(top_scores >= 0,
                       jnp.take(matchlen, js), 0)       # (nb,)
        prop_idx = jnp.minimum(js[:, None] + 1 + jnp.arange(k - 1)
                               [None, :], v - 1)        # (nb, k-1)
        props = jnp.take(seq_r, jnp.clip(prop_idx, 0, S - 1))
        fallback = jnp.full((nb, k - 1),
                            seq_r[jnp.clip(v - 1, 0, S - 1)])
        out = jnp.where((ml > 0)[:, None], props, fallback)
        return out.T.astype(jnp.int32)                  # (k-1, nb)

    return jax.vmap(row)(seq, valid)


@lru_cache(maxsize=None)
def _jitted_b(k: int, n: int, nb: int):
    return jax.jit(partial(ngram_propose_b, k=k, n=n, nb=nb))


def ngram_propose_b_host(seq, valid, k: int, n: int = DEFAULT_N,
                         nb: int = 2):
    """Host-friendly wrapper (numpy in, numpy out) over a cached jit
    of :func:`ngram_propose_b` — the serving engine's per-step
    tree-draft call."""
    import numpy as np
    out = _jitted_b(k, n, nb)(jnp.asarray(seq, jnp.int32),
                              jnp.asarray(valid, jnp.int32))
    return np.asarray(out)


class SuffixAutomaton:
    """Online suffix automaton over a committed token stream, with a
    delayed-by-one matcher for draft proposals.

    After ``feed(t)`` the matcher state is the longest suffix of the
    stream-so-far that also occurs *ending strictly earlier* (the feed
    order — match against the automaton of the stream minus the new
    token, then extend — guarantees the strictness). ``propose(m)``
    returns the ``m`` tokens that followed that earlier occurrence,
    clamped to the committed frontier; with no match it falls back to
    repeating the last token (a guess like any other, priced
    identically by the verify window).

    Construction is the classic online SAM (Blumer et al.): each state
    stores its transition map, suffix link, longest-string length, and
    the end position of its FIRST occurrence (clones inherit the
    original's — any end position of the matched class works for
    reading a continuation). Both feed and the matcher step are O(1)
    amortized, so per-row drafting cost is constant per committed
    token — no rescans, no bound ``n`` on the match length.
    """

    __slots__ = ("_next", "_link", "_len", "_end", "_last", "seq",
                 "_mstate", "_mlen", "last_topb_ops")

    def __init__(self):
        self._next = [{}]
        self._link = [-1]
        self._len = [0]
        self._end = [-1]
        self._last = 0
        self.seq: list = []
        self._mstate = 0
        self._mlen = 0
        self.last_topb_ops = 0   # transitions examined by top_b
        #                          (the O(1)/token cost pin's probe)

    def _extend(self, t: int) -> None:
        pos = len(self.seq) - 1          # t already appended
        cur = len(self._len)
        self._next.append({})
        self._len.append(self._len[self._last] + 1)
        self._link.append(0)
        self._end.append(pos)
        p = self._last
        while p != -1 and t not in self._next[p]:
            self._next[p][t] = cur
            p = self._link[p]
        if p != -1:
            q = self._next[p][t]
            if self._len[p] + 1 == self._len[q]:
                self._link[cur] = q
            else:
                clone = len(self._len)
                self._next.append(dict(self._next[q]))
                self._len.append(self._len[p] + 1)
                self._link.append(self._link[q])
                self._end.append(self._end[q])
                while p != -1 and self._next[p].get(t) == q:
                    self._next[p][t] = clone
                    p = self._link[p]
                self._link[q] = clone
                self._link[cur] = clone
        self._last = cur

    def feed(self, t: int) -> None:
        """Commit one token: advance the matcher against the automaton
        of the PREVIOUS stream (so matches end strictly earlier), then
        extend the automaton with the token."""
        t = int(t)
        st, ln = self._mstate, self._mlen
        while st != 0 and t not in self._next[st]:
            st = self._link[st]
            ln = self._len[st]
        if t in self._next[st]:
            st = self._next[st][t]
            ln += 1
        else:
            ln = 0
        self._mstate, self._mlen = st, ln
        self.seq.append(t)
        self._extend(t)

    @property
    def match_len(self) -> int:
        """Length of the current longest earlier-occurring suffix."""
        return self._mlen

    def propose(self, m: int):
        """``m`` draft tokens continuing the matched occurrence."""
        import numpy as np
        v = len(self.seq)
        if v == 0:
            return np.zeros(m, np.int32)
        if self._mlen == 0:
            return np.full(m, self.seq[-1], np.int32)
        e = self._end[self._mstate]
        out = np.empty(m, np.int32)
        for i in range(m):
            out[i] = self.seq[min(e + 1 + i, v - 1)]
        return out

    def top_b(self, m: int, nb: int):
        """Ranked ``(m, nb)`` proposals for the token-tree verify
        window (round 14): column 0 is bitwise :meth:`propose` (the
        canonical continuation of the matched occurrence); columns
        ``1..nb-1`` at depth ``i`` are the OTHER tokens the automaton
        has seen follow the context — read off the cursor state's
        outgoing transitions, then (ladder) off a bounded walk of its
        SUFFIX LINKS (the next-shorter matching suffixes: a context
        too specific to have alternatives defers to the contexts it
        ends with). Ranking is deterministic: longer matched suffix
        first (fewer link hops), within a state by the end position
        of the transition target's first occurrence (latest first,
        then token ascending) — a pure function of the fed stream,
        so ranks are stable under recomputation.

        Cost: O(outdegree·log outdegree) over at most
        ``1 + _TOPB_LINK_HOPS`` states per depth — automaton
        transitions only, NEVER a rescan of the stream, so per
        committed token the drafting cost stays O(1) in the stream
        length (``last_topb_ops`` counts the transitions examined;
        the unit test bounds it). Ranks with nothing to offer fall
        back to the primary token (a duplicate proposal — inert at
        accept time, since the sideways compare only fires after the
        primary already missed)."""
        import numpy as np
        self.last_topb_ops = 0
        v = len(self.seq)
        out = np.zeros((m, nb), np.int32)
        if v == 0:
            return out
        if self._mlen == 0:
            out[:] = self.seq[-1]
            return out
        st = self._mstate
        e = self._end[st]
        alive = True
        for i in range(m):
            prim = self.seq[min(e + 1 + i, v - 1)]
            out[i, :] = prim            # fallback filler = primary
            if alive and nb > 1:
                ranked: list = []
                seen = {prim}
                st2, hops = st, 0
                while (len(ranked) < nb - 1 and st2 > 0
                       and hops <= _TOPB_LINK_HOPS):
                    nxt2 = self._next[st2]
                    self.last_topb_ops += len(nxt2)
                    more = sorted(
                        ((t, self._end[s2]) for t, s2 in nxt2.items()
                         if t not in seen),
                        key=lambda te: (-te[1], te[0]))
                    for t, _ in more:
                        ranked.append(t)
                        seen.add(t)
                    st2 = self._link[st2]
                    hops += 1
                for r, t in enumerate(ranked[:nb - 1], start=1):
                    out[i, r] = t
            if alive and prim in self._next[st] and e + 1 + i < v:
                st = self._next[st][prim]
            else:
                # the primary chain ran off the automaton (clamped
                # repeat past the frontier): no structure left to
                # rank — deeper alternatives stay at the fallback
                alive = False
        return out


@lru_cache(maxsize=None)
def _jitted(k: int, n: int):
    return jax.jit(partial(ngram_propose, k=k, n=n))


def ngram_propose_host(seq, valid, k: int, n: int = DEFAULT_N):
    """Host-friendly wrapper (numpy in, numpy out) over a cached jit of
    :func:`ngram_propose` — the serving engine's per-step draft call."""
    import numpy as np
    out = _jitted(k, n)(jnp.asarray(seq, jnp.int32),
                        jnp.asarray(valid, jnp.int32))
    return np.asarray(out)
