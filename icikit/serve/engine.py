"""Continuous-batching serving engine over the shared decode core.

``greedy_generate`` is a *batch* program: B prompts in, B continuations
out, every row marching in lockstep until the slowest finishes. A
serving system faces the opposite shape — requests arrive one at a
time, finish at different lengths, and throughput is set by how full
the decode batch *stays*, not by how big one batch once was. This
engine is the Orca-style composition step over everything below it:

- **prefill/decode disaggregation with chunked prefill** — admission
  shares the longest cached block-aligned prefix of the prompt
  straight out of the pool's prefix index (no compute at all for those
  positions), then streams only the uncached suffix through
  fixed-width *chunk* programs interleaved with the decode step — one
  chunk per engine loop pass — so a long prompt inflicts at most one
  chunk of latency on co-batched decoders per step, and compilation is
  bounded to a small bucket ladder instead of one program per prompt
  length.
- **prefix caching** — full, finalized KV blocks are content-addressed
  by chain hashes of their token runs (``kvpool.block_hashes``); a new
  prompt attaches (refcount-shared) to every leading block it matches
  and pays prefill only for the remainder. Blocks are immutable while
  shared: the one write-into-shared case (the full-hit last-position
  recompute) forks the block copy-on-write first. K/V at a position is
  a pure function of the token prefix, so served tokens stay
  greedy-identical to single-request ``generate`` whether a prefix
  came from compute or from cache (pinned in
  ``tests/test_serve_engine.py``).
- **continuous batching** — one fixed-width step program (``B`` rows,
  paged attention over per-row block tables) runs forever; finished
  rows are evicted and their slots re-admitted from the queue at
  *step boundaries* (and, with ``speculate_k >= 2``, at
  speculative-verify boundaries — the step IS the verify window).
- **paged KV cache** — rows gather their own blocks back into a
  contiguous view under a per-row causal mask
  (``_window_masked_attention``); a corrupted page can only ever be
  read by requests whose tables map it — with sharing that is *every
  sharer*, which is why sealed-page digests are content-keyed and a
  failed verify quarantines the page from the prefix index
  (``tests/test_serve_chaos.py``).
- **token identity** — every committed token is the full model's
  argmax over the row's own committed prefix, computed by the same
  ``_DecodeCtx`` math as single-request decode; outputs are
  greedy-token-identical per request to ``greedy_generate`` (pinned
  across staggered admission, mixed prompt lengths, speculative
  on/off, dp/tp meshes, cache hit/partial-hit/miss/CoW admissions).
  The chunk program computes prompt positions with the shared
  window-einsum attention (the decode stack's one numerics source for
  every incremental position); ``generate``'s one-shot prefill may
  route through the flash kernel, whose fp32 reassociation the
  repo's identity bar already absorbs at the argmax level
  (``tests/test_decode.py`` pins greedy decode against a dense
  re-forward oracle under the same tolerance-free token comparison).
- **speculative serving** — ``speculate_k >= 2`` turns the step into a
  k-token verify window fed by a zero-model-cost drafter: the in-jit
  n-gram matcher (``serve/ngram_draft.ngram_propose_host``) or its
  suffix-automaton upgrade (``drafter="suffix"``, unbounded match
  length at O(1) amortized host cost per committed token); acceptance
  semantics are exactly ``speculative_generate``'s (longest prefix, m
  matches commit m+1 tokens) — proposals never change tokens.

The int8 KV path keeps its round-10 numerics untouched: quantized
admissions run the exact-length ``_prefill`` program (raw in-prompt
attention, quantize-at-store — the deployed-prefill semantics the r10
parity metric was corrected to honor), held in an LRU-bounded program
cache, and the prefix index never serves the q8 side (a cached
quantized block cannot reproduce the raw prompt-column attention int8
``generate`` computes, so sharing would break the engine≡generate
parity bar; mixed engines still cache their fp rows).

Scheduling rides :class:`icikit.serve.scheduler.RequestQueue` — leases
renewed per step, expiry reissue (dead-request abandonment), retry
with backoff on transient failures (pool preemption, KV-integrity
mismatch), idempotent completion commits.

SLO accounting flows through ``icikit.obs``: ``serve.ttft_ms`` /
``serve.tpot_ms`` / ``serve.queue_wait_ms`` / ``serve.max_gap_ms``
histograms, ``serve.occupancy_rows`` / ``serve.kv.*`` gauges,
``serve.tokens`` counters, ``serve.prefix.hit_tokens`` histograms +
``serve.prefix.{hits,misses,cow,quarantined}`` counters, a
``serve.request`` span per admission, a ``serve.prefill.chunk`` span
per chunk and a ``serve.engine.step`` span per step
(chrome-checker-valid). On top of the thread spans, every request
carries its own ASYNC span tree (``obs.trace_ctx``, minted at
``RequestQueue.submit``): queue-wait and attempt segments, per-chunk
spans, per-step participation instants with the verify-window accept
stats, CoW/dedup/quarantine marks — one tree per request across
lease reissue (``reissued_from`` edges), and the engine step span
records the co-batch roster of participant trace ids. See
docs/OBSERVABILITY.md.

Chaos sites (drilled in ``tests/test_serve_chaos.py``):

- ``serve.admit``         — delay/die at admission;
- ``serve.admit.prompt``  — SDC on the claimed prompt bytes; detection
  is the submit-time checksum → ``PoisonedPromptError`` → rejected
  without retry, engine keeps serving;
- ``serve.step``          — delay/die at the step boundary (a die is
  an engine crash: leases expire, requests reissue to the next
  engine);
- ``serve.prefill.chunk`` — delay/die at a chunk boundary;
- ``serve.kv.page``       — SDC on a sealed KV page; with
  ``integrity="pages"`` every request whose table maps the page fails
  its completion verify, the page is quarantined from the prefix
  index, and retries re-prefill on fresh blocks while non-sharing
  co-batched requests' outputs stay bitwise unchanged.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field

import numpy as np

from icikit import chaos, obs

# site registry (chaos satellite): the request-level drill sites.
# serve.spec.tree.fork (r14) is the host boundary of a tree verify
# window: the CoW guard + block-table ensure over the widened scratch
# window, drilled die/delay in tests/test_serve_chaos.py.
chaos.register_site("serve.admit", "serve.admit.prompt",
                    "serve.prefill.chunk", "serve.step",
                    "serve.kv.page", "serve.spec.tree.fork")

from icikit.serve.kvpool import (  # noqa: E402
    KVPool,
    PoolExhausted,
    block_hashes,
    chain_extend,
    chain_seed,
)
from icikit.serve.ngram_draft import (
    DEFAULT_N,
    SuffixAutomaton,
    ngram_propose_host,
)
from icikit.serve.scheduler import (
    PoisonedPromptError,
    Request,
    RequestQueue,
    prompt_checksum,
)

# quantized admissions compile one exact-length prefill program per
# distinct prompt length; this cap bounds the cache (LRU eviction =
# recompile on re-encounter, never unbounded growth). The fp path
# needs no cap — its chunk buckets are finitely many by construction.
PREFILL_PROGRAM_CAP = 8


class IntegrityError(RuntimeError):
    """A request's sealed KV pages failed their checksum re-verify."""


@dataclass(frozen=True)
class ServeConfig:
    """Engine geometry — all static (they shape the compiled step)."""

    max_rows: int = 4        # decode batch width B (divisible by dp)
    block_size: int = 8      # KV block = this many token columns
    n_blocks: int = 64       # allocatable blocks per dp shard
    max_prompt: int = 64     # admission ceilings (validation, buffers)
    max_new: int = 64
    speculate_k: int = 1     # 1 = single-token; >= 2 = drafted verify
    # ranked branches per draft position (round 14). 1 = the chain
    # verify window (the pre-tree program, bitwise). b >= 2 verifies a
    # caterpillar token tree of 1 + (k-1)*b linearized nodes per step
    # (tree-attention mask over the row's paged view): the drafter's
    # rank-0 chain plus b-1 ranked sibling leaves per depth, accepted
    # by the ONE shared rule (speculative._accept_tree, which runs
    # _accept_window for the primary chain verbatim) — so engine
    # output stays bitwise sample_generate / greedy generate per
    # request at every branch count. Needs speculate_k >= 2.
    tree_branch: int = 1
    ngram_n: int = DEFAULT_N
    # "ngram" = the in-jit bounded-suffix matcher (r9, measured r10);
    # "suffix" = its suffix-automaton upgrade: unbounded longest-suffix
    # match at O(1) amortized host cost per committed token (the
    # ROADMAP 3b ladder rung above ngram — same verify/accept contract,
    # so token identity is unconditional either way)
    drafter: str = "ngram"
    integrity: str = "none"  # "none" | "pages" (seal + verify)
    # automatic prefix caching (fp arenas): share cached block-aligned
    # prompt prefixes instead of recomputing them. Off = every
    # admission recomputes its full prompt (the A/B baseline arm).
    prefix_cache: bool = True
    # in-flight prefill dedup (r12): admission announces the chain
    # hashes of the blocks it is ABOUT to compute; a concurrent
    # identical/prefix admission whose next needed hash is announced
    # becomes a WAITER — it attaches to the blocks as the prefiller
    # finalizes them (progressive registration, riding the r11
    # refcount/CoW index) instead of computing them itself. If the
    # prefiller vanishes (eviction/preemption; an engine death takes
    # the waiter with it and both reissue through lease expiry), the
    # announcement vanishes and the waiter computes the remainder.
    # Requires prefix_cache (fp side): "auto" follows prefix_cache,
    # an explicit True without the cache is rejected loudly, and the
    # A/B baseline arm passes False.
    inflight_dedup: bool | str = "auto"
    # prefill chunk ceiling: uncached prompt suffixes stream through
    # bucket-width chunk programs (powers of two up to this value),
    # one chunk per engine loop pass. Set >= max_prompt for
    # whole-prompt (single-chunk) admission — the r11 A/B's "whole"
    # arm uses exactly that.
    prefill_chunk: int = 64
    # KV-arena precision: "auto" follows cfg.decode_quant (int8 decode
    # stores int8 KV — the pure bandwidth configuration, no fp arena
    # exists), "none"/"int8" force, "mixed" holds BOTH arenas over one
    # allocator and routes per request (Request.quant) — requires
    # decode_quant="none" so co-batched fp requests stay bitwise
    # identical to an unquantized engine (the containment pin in
    # tests/test_serve_quant.py)
    kv_quant: str = "auto"
    # tiered KV (r16). host_cache_blocks > 0 attaches the host-memory
    # spill tier: an indexed block evicted under allocation pressure
    # copies its arena bytes out (scale pages included on the q8 side)
    # and demotes to `spilled` instead of vanishing; a prefix lookup
    # landing on a spilled chain swaps the blocks back in through the
    # chunked-admission path (at most one chunk-width of blocks per
    # engine loop pass — restore stalls are bounded by prefill_chunk
    # exactly like compute stalls), each block re-verifying its
    # content digest at swap-in. Requires prefix_cache (only indexed
    # content can spill). 0 = off, the pre-r16 pool bitwise.
    host_cache_blocks: int = 0
    # persistent content-addressed block store directory: finalized
    # blocks write through at registration, and a restarted engine
    # re-warms from disk (demand-paged at admission, or eagerly via
    # Engine.rewarm) instead of recomputing prefill. A loaded block
    # that fails its digest verify is quarantined and recomputed.
    # None = off. Requires prefix_cache.
    store_dir: str | None = None


@dataclass
class _Row:
    """Host-side state of one occupied engine slot."""

    req: Request
    shard: int
    s_prompt: int
    n_done: int              # committed tokens (includes the pending)
    sealed: int              # leading table blocks finalized so far
    prefilled: int = 0       # prompt positions whose K/V is resident
    seq: int = 0             # claim generation captured at admission
    owner: str = ""          # pool-ownership token: rid + claim seq
    side: str = "fp"         # which KV arena serves this row (fp | q8)
    last_t: float = 0.0      # last token-delivery instant (monotonic)
    max_gap: float = 0.0     # worst inter-delivery stall so far (s)
    # chain-hash state at block `sealed - 1`: finalizing block j
    # extends this by ONE block (O(block), not a re-hash from zero).
    # Default = chain_seed("fp"); admission overrides for hits/sides.
    chain: bytes = b"fp"
    # the prompt's full-block chain hashes (fp/prefix-cache side only)
    # — kept for the waiter's per-pass re-lookup and for withdrawing
    # in-flight announcements on eviction
    hashes: list = field(default_factory=list)
    # in-flight dedup: True while this row is parked waiting for a
    # concurrent prefiller to finalize the blocks it announced
    waiting: bool = False
    # tiered KV (r16): chain hashes pending swap-in from the host
    # spill tier / persistent store (consecutive, starting at block
    # `sealed`) — drained at most one chunk-width of blocks per
    # engine loop pass so restore stalls stay bounded like compute
    # stalls. tier_base is the device-hit token count at admission
    # for a tier-planned row (-1 = no tier plan): the restored
    # tokens' hit accounting lands only once their swap-in verifies.
    restore: list = field(default_factory=list)
    tier_base: int = -1
    # tokens accumulate HERE, not on the shared Request object: the
    # claim-seq fence covers queue mutations, but a stalled engine
    # resuming after its lease was reaped must also be unable to
    # interleave host-side appends into the live claimant's output —
    # only the fenced complete() publishes a row's tokens
    tokens: list = field(default_factory=list)


class Engine:
    """One engine = one compiled step program + host admission loop.

    ``params`` / ``mesh`` / ``cfg`` are the model triple every decode
    entry point takes; ``serve`` the engine geometry; ``queue`` the
    shared :class:`RequestQueue` (created if omitted — multi-engine
    setups share one queue, which is what makes lease-expiry reissue
    across engines work).
    """

    def __init__(self, params, mesh, cfg, serve: ServeConfig,
                 queue: RequestQueue | None = None, store=None):
        from icikit.models.transformer.model import DP_AXIS
        if cfg.n_experts:
            raise ValueError(
                "the serving engine does not support MoE "
                "(n_experts > 0): expert dispatch is a dp all-to-all "
                "whose routing this engine's paged step has not been "
                "built for")
        if serve.speculate_k < 1:
            raise ValueError(
                f"speculate_k must be >= 1, got {serve.speculate_k}")
        if serve.tree_branch < 1:
            raise ValueError(
                f"tree_branch must be >= 1, got {serve.tree_branch}")
        if serve.tree_branch > 1 and serve.speculate_k < 2:
            raise ValueError(
                "tree_branch > 1 needs a draft window "
                f"(speculate_k >= 2), got "
                f"speculate_k={serve.speculate_k}")
        if serve.tree_branch > cfg.vocab:
            raise ValueError(
                f"tree_branch={serve.tree_branch} exceeds "
                f"vocab={cfg.vocab}")
        if serve.integrity not in ("none", "pages"):
            raise ValueError(
                f"unknown integrity {serve.integrity!r} "
                "(known: none, pages)")
        if serve.drafter not in ("ngram", "suffix"):
            raise ValueError(f"unknown drafter {serve.drafter!r} "
                             "(known: ngram, suffix)")
        if serve.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got "
                f"{serve.prefill_chunk}")
        dd = serve.inflight_dedup
        if dd not in (True, False, "auto"):
            raise ValueError(f"unknown inflight_dedup {dd!r} "
                             "(known: auto, True, False)")
        if dd == "auto":
            dd = serve.prefix_cache
        elif dd and not serve.prefix_cache:
            raise ValueError(
                "inflight_dedup=True requires prefix_cache: waiters "
                "attach through the shared block index, which does "
                "not exist with the cache off (use 'auto' to follow "
                "prefix_cache, or False for the A/B baseline)")
        self.dedup = bool(dd)
        self.dp = mesh.shape[DP_AXIS]
        if serve.max_rows % self.dp:
            raise ValueError(
                f"max_rows={serve.max_rows} must divide over "
                f"dp={self.dp}")
        from icikit.models.transformer.speculative import (
            tree_window_width,
        )
        k = serve.speculate_k
        # verify-window width: k scratch columns for the chain,
        # 1 + (k-1)*b linearized tree nodes for a branch-b caterpillar
        # (tree_branch == 1 IS the chain — same program)
        self.w_win = tree_window_width(k, serve.tree_branch)
        horizon = serve.max_prompt + serve.max_new + self.w_win - 1
        if horizon > cfg.max_seq:
            raise ValueError(
                f"max_prompt + max_new + window - 1 = {horizon} "
                f"exceeds max_seq = {cfg.max_seq} (tree windows are "
                "1 + (speculate_k-1)*tree_branch columns wide)")
        bs = serve.block_size
        self.nb_per_row = -(-horizon // bs)           # block-table width
        if self.nb_per_row > serve.n_blocks:
            raise ValueError(
                f"one max-size request needs {self.nb_per_row} blocks "
                f"but the pool holds {serve.n_blocks} per shard")
        kv = serve.kv_quant
        if kv == "auto":
            kv = "int8" if cfg.decode_quant == "int8" else "none"
        if kv not in ("none", "int8", "mixed"):
            raise ValueError(f"unknown kv_quant {kv!r} "
                             "(known: auto, none, int8, mixed)")
        if kv == "mixed" and cfg.decode_quant != "none":
            raise ValueError(
                "kv_quant='mixed' requires decode_quant='none': "
                "quantized weights touch every co-batched row, which "
                "breaks the fp-requests-bitwise-unchanged containment "
                "the mixed pool exists for")
        if kv == "none" and cfg.decode_quant == "int8":
            raise ValueError(
                "decode_quant='int8' stores int8 KV (kv_quant 'auto' "
                "or 'int8'): an fp KV arena on the int8 path would "
                "reintroduce the high-precision cache stream the "
                "route exists to remove")
        self.kv_mode = kv
        if cfg.decode_quant == "int8":
            from icikit.models.transformer.decode import (
                maybe_quantize_params,
            )
            # weights quantized ONCE at engine setup; scales ride the
            # pytree into every step/prefill program
            self.params = maybe_quantize_params(params, mesh, cfg)
        else:
            self.params = self._cast_weights(params, cfg)
        self.mesh = mesh
        self.cfg = cfg
        self.serve = serve
        self.queue = queue if queue is not None else RequestQueue()
        if serve.host_cache_blocks < 0:
            raise ValueError(
                f"host_cache_blocks must be >= 0, got "
                f"{serve.host_cache_blocks}")
        if ((serve.host_cache_blocks > 0 or serve.store_dir
                or store is not None) and not serve.prefix_cache):
            raise ValueError(
                "the spill tier and the persistent store hold INDEXED "
                "content; with prefix_cache off nothing is ever "
                "registered, so host_cache_blocks/store_dir would be "
                "silent no-ops — rejected loudly instead")
        if serve.store_dir:
            if store is not None:
                raise ValueError(
                    "store_dir and an injected store= object are "
                    "exclusive — the engine can write through to one "
                    "bottom tier, not two")
            from icikit.serve.store import PrefixStore
            store = PrefixStore(serve.store_dir)
        # else: `store` may be any store-SHAPED object (has/get/put/
        # quarantine with the PrefixStore payload contract) — the
        # fleet's KV bridge client rides in here, which is what makes
        # the host tier fleet-shared: tier_plan/restore/persist compose
        # against the duck type, digest re-verify at swap-in included
        self.pool = KVPool(cfg, mesh, serve.n_blocks, bs, quant=kv,
                           host_blocks=serve.host_cache_blocks,
                           store=store)
        if serve.host_cache_blocks > 0 or store is not None:
            # compile the tier programs at setup: the first eviction
            # batch and the first spilled-chain hit must pay a
            # memcpy, not an XLA compile, inside a request's TTFT
            self.pool.warm_restore(
                max(1, serve.prefill_chunk // bs),
                max_evict=self.nb_per_row)
        B = serve.max_rows
        self.rows: list[_Row | None] = [None] * B
        self._toks = np.zeros(B, np.int32)
        self._curs = np.zeros(B, np.int32)
        self._active = np.zeros(B, bool)
        self._isq = np.zeros(B, bool)     # row side (mixed routing)
        self._btab = np.zeros((B, self.nb_per_row), np.int32)
        self._seq_buf = np.zeros(
            (B, serve.max_prompt + serve.max_new), np.int32)
        # per-request sampling state (r12): each occupied slot's
        # stream-key data (the canonical fold_in(key(0), seed) —
        # derived in decode.request_stream_data, so serve/ builds no
        # keys of its own) and its (temperature, top_p, top_k) knobs.
        # Greedy rows carry temperature 0, which the selector maps to
        # raw-logit argmax bitwise.
        from icikit.models.transformer.decode import request_stream_data
        self._stream_data = request_stream_data
        proto = request_stream_data(0)
        self._kdat = np.zeros((B,) + proto.shape, proto.dtype)
        self._knobs = np.zeros((B, 3), np.float32)
        self._knobs[:, 1] = 1.0          # top_p neutral
        # step variants are compiled per (quantized-row-resident,
        # sampled-row-resident) and dispatched per step — an all-fp /
        # all-greedy batch pays zero quantization / sampling traffic,
        # and flipping programs mid-request cannot change a greedy or
        # fp row's tokens (see _build_step)
        self._step_fns: dict = {}
        # fp admissions: chunk programs keyed by (bucket width,
        # sampled-final-chunk) — the ladder is finite, so so is the
        # cache (the satellite bound)
        self._chunk_fns: dict = {}
        self._chunk_widths = self._bucket_ladder(serve.prefill_chunk)
        # q8 admissions: exact-length prefill programs, LRU-capped
        self._prefill_fns: collections.OrderedDict = \
            collections.OrderedDict()
        # per-slot suffix-automaton drafter state (drafter="suffix")
        self._automata: dict = {}
        self._prefix = self._zero_prefix()
        self.n_steps = 0
        self._occ_rows = 0       # sum of active rows over steps

    @staticmethod
    def _zero_prefix() -> dict:
        return {"hits": 0, "misses": 0, "hit_tokens": 0,
                "full_hits": 0, "cow": 0, "inflight_hits": 0,
                "inflight_hit_tokens": 0, "prefill_tokens": 0,
                # tiered KV (r16): admissions that planned a swap-in,
                # tokens they served from the tiers, restore traffic
                # split by source, and the host-side restore time
                "spill_hits": 0, "spill_hit_tokens": 0,
                "restores": 0, "restores_host": 0,
                "restores_store": 0, "restore_bytes": 0,
                "restore_ms_total": 0.0}

    @staticmethod
    def _bucket_ladder(chunk: int) -> tuple:
        """Power-of-two chunk widths up to ``chunk`` (always included):
        a prompt remainder takes the smallest covering bucket, so the
        compiled-chunk-program count is bounded by this ladder's
        length, not by the prompt-length distribution."""
        ws, w = [], 8
        while w < chunk:
            ws.append(w)
            w *= 2
        ws.append(chunk)
        return tuple(ws)

    @staticmethod
    def _cast_weights(params, cfg):
        """Pre-cast the matmul weights to the compute dtype ONCE.

        Every layer consumes these via ``.astype(compute_dtype)``;
        inside ``generate``'s single compiled loop XLA hoists that
        conversion out of the scan, but the engine's step is its own
        program per call and would re-convert the parameter stream
        every token. Token identity is unaffected: ``astype`` on an
        already-cast array yields the same round-to-nearest values
        ``generate`` computes in-loop; norm scales, embeddings and
        positional tables stay fp32 (their math is fp32 in both
        paths). Note the XLA:CPU caveat measured in round 9: CPU gemm
        re-packs bf16 operands to fp32 per *call*, so this pre-cast
        only pays on native-bf16 backends — the committed CPU bench
        rows run fp32 compute instead (icikit.bench.serve)."""
        import jax.numpy as jnp

        from icikit.models.transformer.model import _attn_param_keys
        cdt = jnp.dtype(cfg.compute_dtype)
        if cdt == jnp.float32:
            return params
        cast = set(_attn_param_keys(cfg)) | {"wo", "w1", "w2", "w_out"}
        return {k: (v.astype(cdt) if k in cast else v)
                for k, v in params.items()}

    # -- compiled programs -------------------------------------------

    def _pool_spec(self):
        from jax.sharding import PartitionSpec as P

        from icikit.models.transformer.model import DP_AXIS, TP_AXIS
        return P(DP_AXIS, None, None, TP_AXIS, None)

    def _scale_spec(self):
        from jax.sharding import PartitionSpec as P

        from icikit.models.transformer.model import DP_AXIS, TP_AXIS
        return P(DP_AXIS, None, None, TP_AXIS)

    def _build_step(self, quant_live: bool, sampled: bool,
                    filters: bool = True):
        """Compile one step program. ``quant_live`` matters only in
        "mixed" mode: the False variant skips the q8 quantize/write/
        dequant-gather entirely (arenas pass through untouched) so an
        all-fp resident batch pays zero quantization traffic — the
        host dispatches on ``self._isq.any()`` per step, and fp rows
        compute identically in both variants (their gather reads the
        fp arena either way), so flipping programs mid-request cannot
        change an fp row's tokens. ``sampled`` is the same move for
        sampling (r12): the False variant IS the pre-r12 greedy
        program (the key/knob inputs thread through dead); the True variant
        selects each window position's token with the row's counter
        key — and greedy rows there carry temperature 0, which the
        shared selector maps to raw-logit argmax, so flipping
        variants mid-request cannot change a greedy row's tokens
        either (the mixed-batch containment pin)."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from icikit.models.transformer.decode import (
            _DecodeCtx,
            _window_masked_attention,
            _window_masked_attention_q8,
            fold_positions,
            select_tokens,
        )
        from icikit.models.transformer.model import DP_AXIS
        from icikit.models.transformer.quant import decode_param_specs
        from icikit.models.transformer.speculative import (
            _accept_tree,
            _accept_window,
            _tree_mask,
            _tree_template,
        )
        from icikit.ops.quant import quantize_last
        from icikit.ops.rope import apply_rope, rope_sincos

        cfg = self.cfg
        ctx = _DecodeCtx(cfg, self.mesh)
        k = self.serve.speculate_k
        bs = self.serve.block_size
        NB = self.nb_per_row
        T = NB * bs
        n_layers = cfg.n_layers
        mode = self.kv_mode
        if mode == "mixed" and not quant_live:
            touch_q8 = False      # arenas thread through untouched
        else:
            touch_q8 = mode in ("int8", "mixed")
        # arenas the relocation (tree path) must move: exactly the
        # ones this variant writes
        written = set()
        if touch_q8:
            written |= {"qkc", "qvc", "ksc", "vsc"}
        if mode in ("none", "mixed"):
            written |= {"kc", "vc"}
        tb = self.serve.tree_branch
        tree = tb > 1
        if tree:
            w_win, dep_t, anc_t, prim_t = _tree_template(k, tb)
            dep_c = jnp.asarray(dep_t)
            anc_c = jnp.asarray(anc_t)
            prim_c = jnp.asarray(prim_t)
        else:
            w_win = k

        def per_shard(params, toks, curs, active, isq, btab, drafts,
                      kdat, knobs, bufs):
            b = toks.shape[0]
            lp = {kk: params[kk] for kk in ctx.layer_keys}
            w_toks = jnp.concatenate([toks[:, None], drafts], axis=1)
            if tree:
                # node j's LOGICAL position (rope, mask, key) is
                # cur + dep[j]; its K/V still lands at scratch column
                # cur + j — the accepted root-to-leaf path relocates
                # into position-aligned columns after accept
                pos = curs[:, None] + dep_c[None, :]     # (b, w)
                spos = (curs[:, None]
                        + jnp.arange(w_win)[None, :])    # (b, w)
                # tree-attention mask over the paged view — the ONE
                # construction, shared with _window_pass (the
                # engine-vs-generate identity hangs on it)
                mask = _tree_mask(anc_c, curs, T, w_win)
            else:
                pos = curs[:, None] + jnp.arange(k)[None, :]  # (b, k)
                spos = pos
                # per-row causal frontier over the row's paged view
                mask = (jnp.arange(T)[None, None, :]
                        <= pos[:, :, None])
            x = ctx.embed(params, w_toks, pos)
            sincos = (rope_sincos(pos, cfg.d_head, cfg.rope_theta)
                      if cfg.pos_encoding == "rope" else None)
            # physical write targets; inactive rows park on trash 0
            pages = jnp.take_along_axis(btab, spos // bs, axis=1)
            pages = jnp.where(active[:, None], pages, 0)
            slots = spos % bs
            out = {kk: [] for kk in bufs}
            for li in range(n_layers):
                lp1 = {kk: lp[kk][li] for kk in ctx.layer_keys}
                q, k_, v_ = ctx.qkv_proj(x, lp1)
                if sincos is not None:
                    q = apply_rope(q, pos, cfg.rope_theta, sincos)
                    k_ = apply_rope(k_, pos, cfg.rope_theta, sincos)
                if touch_q8:
                    # quantize-at-write, exactly the generate-path
                    # column quantization (token identity to int8
                    # generate hangs on the byte-for-byte match)
                    kq, ksn = quantize_last(k_)
                    vq, vsn = quantize_last(v_)
                    qkp, qvp = bufs["qkc"][li][0], bufs["qvc"][li][0]
                    kscp = bufs["ksc"][li][0]
                    vscp = bufs["vsc"][li][0]
                    qkp = qkp.at[pages, slots].set(kq)
                    qvp = qvp.at[pages, slots].set(vq)
                    kscp = kscp.at[pages, slots].set(ksn)
                    vscp = vscp.at[pages, slots].set(vsn)
                    out["qkc"].append(qkp[None])
                    out["qvc"].append(qvp[None])
                    out["ksc"].append(kscp[None])
                    out["vsc"].append(vscp[None])
                elif mode == "mixed":
                    for kk in ("qkc", "qvc", "ksc", "vsc"):
                        out[kk].append(bufs[kk][li])
                if mode in ("none", "mixed"):
                    kp, vp = bufs["kc"][li][0], bufs["vc"][li][0]
                    kp = kp.at[pages, slots].set(k_.astype(kp.dtype))
                    vp = vp.at[pages, slots].set(v_.astype(vp.dtype))
                    out["kc"].append(kp[None])
                    out["vc"].append(vp[None])
                # the paged gather: this row's blocks, contiguous again
                if mode == "int8":
                    ks = qkp[btab].reshape(b, T, *qkp.shape[2:])
                    vs = qvp[btab].reshape(b, T, *qvp.shape[2:])
                    ksc = kscp[btab].reshape(b, T, *kscp.shape[2:])
                    vsc = vscp[btab].reshape(b, T, *vscp.shape[2:])
                    attn = _window_masked_attention_q8(
                        q, ks, vs, ksc, vsc, mask, ctx.scale,
                        ctx.n_rep)
                else:
                    ks = kp[btab].reshape(b, T, *kp.shape[2:])
                    vs = vp[btab].reshape(b, T, *vp.shape[2:])
                    if touch_q8:
                        # per-row arena select on the gathered INPUTS:
                        # fp rows' lanes pass through exactly (their
                        # attention sees the identical values a pure-fp
                        # engine gathers — the containment pin), int8
                        # rows read their dequantized pages
                        kdq = (qkp[btab].reshape(b, T, *qkp.shape[2:])
                               .astype(jnp.float32)
                               * kscp[btab].reshape(
                                   b, T, *kscp.shape[2:])[..., None])
                        vdq = (qvp[btab].reshape(b, T, *qvp.shape[2:])
                               .astype(jnp.float32)
                               * vscp[btab].reshape(
                                   b, T, *vscp.shape[2:])[..., None])
                        sel = isq[:, None, None, None]
                        ks = jnp.where(sel, kdq.astype(ks.dtype), ks)
                        vs = jnp.where(sel, vdq.astype(vs.dtype), vs)
                    attn = _window_masked_attention(q, ks, vs, mask,
                                                    ctx.scale,
                                                    ctx.n_rep)
                x = ctx.close_attn(x, attn, lp1)
                x = ctx.ffn(x, lp1)
            g_lg = ctx.logits(params, x)                 # (b, w, V)
            if sampled:
                # per-(row, position) counter keys: the token decided
                # at window node j lands at position pos[:, j] + 1 —
                # the identical key (and identical filter math, via
                # the shared selector) sample_generate uses there,
                # which is the engine ≡ generate sampled identity
                # (several tree nodes at one depth share a key, but
                # exactly one sits on the realized path)
                import jax as _jax
                streams = _jax.random.wrap_key_data(kdat)
                g = select_tokens(g_lg,
                                  fold_positions(streams, pos + 1),
                                  knobs, filters)
            else:
                g = jnp.argmax(g_lg, axis=-1).astype(jnp.int32)
            if tree:
                # the ONE accept rule (primary chain runs
                # _accept_window verbatim inside _accept_tree) plus
                # the sideways hop — shared with speculative.py, the
                # engine-vs-generate identity contract hangs on it
                alts = drafts.reshape(b, k - 1, tb)
                m, m_p, side, a, new_tok, commit, src = _accept_tree(
                    w_toks[:, prim_c], alts, g[:, prim_c],
                    g[:, 1:].reshape(b, k - 1, tb), active)
                # accepted root-to-leaf path K/V (and scales) out of
                # tree scratch, into the position-aligned columns the
                # next step's committed-prefix reads expect; columns
                # past the accepted frontier hold relocation garbage
                # — beyond every future causal mask until the next
                # window overwrites them (chain-path discipline)
                src_pos = curs[:, None] + src              # (b, k)
                dst_pos = (curs[:, None]
                           + jnp.arange(k)[None, :])       # (b, k)
                sp_pg = jnp.take_along_axis(btab, src_pos // bs,
                                            axis=1)
                dst_pg = jnp.take_along_axis(btab, dst_pos // bs,
                                             axis=1)
                dst_pg = jnp.where(active[:, None], dst_pg, 0)

                def reloc(p):
                    taken = p[sp_pg, src_pos % bs]         # (b, k, …)
                    return p.at[dst_pg, dst_pos % bs].set(taken)

                out = {kk: ([reloc(v[0])[None] for v in vs]
                            if kk in written else vs)
                       for kk, vs in out.items()}
                tstats = jnp.stack(
                    [jnp.where(active, m_p, 0),
                     jnp.where(active, side, False)
                     .astype(jnp.int32)], axis=1)          # (b, 2)
                return (commit, a, jnp.where(active, new_tok, toks),
                        tstats,
                        {kk: tuple(v) for kk, v in out.items()})
            # the ONE accept rule, shared with speculative_generate —
            # the engine-vs-generate identity contract hangs on it
            _, a, new_tok = _accept_window(w_toks, g, active)
            return (g, a, jnp.where(active, new_tok, toks),
                    {kk: tuple(v) for kk, v in out.items()})

        bspecs = self.pool.buffer_specs(self._pool_spec(),
                                        self._scale_spec())
        import jax

        from icikit.parallel.shmap import shard_map as _shard_map
        # pools are DONATED: the step rewrites the whole arena
        # functionally, and without donation XLA must copy every
        # buffer per token step (pool.update drops the old refs, so
        # reuse is safe; KVPool allocates distinct per-layer buffers)
        outs = ((P(DP_AXIS, None), P(DP_AXIS), P(DP_AXIS),
                 P(DP_AXIS, None), bspecs) if tree else
                (P(DP_AXIS, None), P(DP_AXIS), P(DP_AXIS), bspecs))
        return jax.jit(_shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(decode_param_specs(cfg), P(DP_AXIS), P(DP_AXIS),
                      P(DP_AXIS), P(DP_AXIS), P(DP_AXIS, None),
                      P(DP_AXIS, None), P(DP_AXIS, None),
                      P(DP_AXIS, None), bspecs),
            out_specs=outs), donate_argnums=(9,))

    def _build_chunk(self, width: int, sampled: bool = False,
                     filters: bool = True):
        """One compiled prefill-chunk program for fp-side admissions —
        the replacement for the per-prompt-length program zoo.

        Computes ``width`` prompt positions starting at traced offset
        ``p0``: projects their q/k/v, writes the K/V into the row's
        pool blocks (padding positions route to trash block 0), then
        attends the row's whole paged view under the per-position
        causal mask — so chunk 2's queries read chunk 1's (or a cache
        hit's) K/V straight from the pool, and the per-position math
        is exactly the step program's. ``tok0`` (the selection at the
        last valid position — argmax, or under ``sampled`` the keyed
        draw at position ``s_prompt``) is only meaningful on the
        chunk that covers position ``s_prompt - 1``, and only on the
        owner shard (other shards gather trash), hence the per-shard
        out spec. ``sampled`` variants are compiled only for the
        FINAL chunk of a sampled request (mid-chunks discard tok0),
        so the greedy path never pays the draw.

        In "mixed" mode this program serves fp rows only (q8 rows take
        the exact ``_prefill`` path — see the module docstring): the
        q8 arenas pass through untouched."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from icikit.models.transformer.decode import (
            _DecodeCtx,
            _window_masked_attention,
            fold_positions,
            select_tokens,
        )
        from icikit.models.transformer.model import DP_AXIS
        from icikit.models.transformer.quant import decode_param_specs
        from icikit.ops.rope import apply_rope, rope_sincos

        cfg = self.cfg
        ctx = _DecodeCtx(cfg, self.mesh)
        bs = self.serve.block_size
        NB = self.nb_per_row
        T = NB * bs
        n_layers = cfg.n_layers
        mode = self.kv_mode
        if mode == "int8":
            raise RuntimeError(
                "chunk programs are fp-side only; int8 admissions use "
                "the exact _prefill path")

        def per_shard(params, toks, p0, n_valid, btab, kdat, knobs,
                      bufs):
            # toks (1, width) replicated across shards; btab (1, NB)
            # is the owner's table on its shard, all-zero elsewhere —
            # non-owner shards write (and gather) the trash block
            lp = {kk: params[kk] for kk in ctx.layer_keys}
            pos = p0[0] + jnp.arange(width)[None, :]         # (1, w)
            valid = (jnp.arange(width) < n_valid[0])[None, :]
            x = ctx.embed(params, toks, pos)
            sincos = (rope_sincos(pos, cfg.d_head, cfg.rope_theta)
                      if cfg.pos_encoding == "rope" else None)
            mask = (jnp.arange(T)[None, None, :] <= pos[:, :, None])
            pages = jnp.take_along_axis(btab, pos // bs, axis=1)
            pages = jnp.where(valid, pages, 0)   # padding → trash
            slots = pos % bs
            out = {kk: [] for kk in bufs}
            for li in range(n_layers):
                lp1 = {kk: lp[kk][li] for kk in ctx.layer_keys}
                q, k_, v_ = ctx.qkv_proj(x, lp1)
                if sincos is not None:
                    q = apply_rope(q, pos, cfg.rope_theta, sincos)
                    k_ = apply_rope(k_, pos, cfg.rope_theta, sincos)
                kp, vp = bufs["kc"][li][0], bufs["vc"][li][0]
                kp = kp.at[pages, slots].set(k_.astype(kp.dtype))
                vp = vp.at[pages, slots].set(v_.astype(vp.dtype))
                out["kc"].append(kp[None])
                out["vc"].append(vp[None])
                if mode == "mixed":
                    for kk in ("qkc", "qvc", "ksc", "vsc"):
                        out[kk].append(bufs[kk][li])
                ks = kp[btab].reshape(1, T, *kp.shape[2:])
                vs = vp[btab].reshape(1, T, *vp.shape[2:])
                attn = _window_masked_attention(q, ks, vs, mask,
                                                ctx.scale, ctx.n_rep)
                x = ctx.close_attn(x, attn, lp1)
                x = ctx.ffn(x, lp1)
            xl = jax.lax.dynamic_slice_in_dim(x, n_valid[0] - 1, 1,
                                              axis=1)
            lg0 = ctx.logits(params, xl[:, 0])
            if sampled:
                # first-token draw at position s_prompt = p0 + n_valid
                # — the identical counter key (and vmapped selector)
                # sample_generate's tok0 uses after its own prefill
                streams = jax.random.wrap_key_data(kdat)
                tok0 = select_tokens(
                    lg0, fold_positions(
                        streams, (p0 + n_valid).astype(jnp.int32)),
                    knobs[0], filters)
            else:
                tok0 = jnp.argmax(lg0, axis=-1).astype(jnp.int32)
            return tok0, {kk: tuple(v) for kk, v in out.items()}

        bspecs = self.pool.buffer_specs(self._pool_spec(),
                                        self._scale_spec())
        from icikit.parallel.shmap import shard_map as _shard_map
        return jax.jit(_shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(decode_param_specs(cfg), P(None, None), P(None),
                      P(None), P(DP_AXIS, None), P(None, None),
                      P(None, None), bspecs),
            out_specs=(P(DP_AXIS), bspecs)), donate_argnums=(7,))

    def _build_prefill(self, s_prompt: int, sampled: bool = False,
                       filters: bool = True):
        """Exact-length whole-prompt prefill for QUANTIZED admissions:
        the prompt's own attention runs on the raw projections and
        quantization happens at store time — the deployed int8-prefill
        semantics the r10 parity metric was corrected to honor, which
        a write-then-gather chunk over int8 pages cannot reproduce.
        On a "mixed" engine only the q8 arenas are touched (each
        request pays exactly its own side's bytes). ``sampled`` draws
        tok0 with the row's counter key at position ``s_prompt``
        (engine ≡ int8 ``sample_generate``, same contract as fp)."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from icikit.models.transformer.decode import (
            _DecodeCtx,
            _prefill,
            fold_positions,
            select_tokens,
        )
        from icikit.models.transformer.model import DP_AXIS
        from icikit.models.transformer.quant import decode_param_specs
        from icikit.ops.quant import quantize_last

        cfg = self.cfg
        ctx = _DecodeCtx(cfg, self.mesh)
        bs = self.serve.block_size
        npref = -(-s_prompt // bs)
        n_layers = cfg.n_layers

        def per_shard(params, prompt, pages, kdat, knobs, bufs):
            # prompt replicated: every shard computes the same prefill;
            # only the owner shard's pages are real (others trash 0)
            import jax as _jax
            x, caches = _prefill(ctx, params, prompt, s_prompt,
                                 npref * bs, fused=False)
            lg0 = ctx.logits(params, x[:, -1])
            if sampled:
                streams = _jax.random.wrap_key_data(kdat)
                tok0 = select_tokens(
                    lg0, fold_positions(
                        streams, jnp.full((1,), s_prompt, jnp.int32)),
                    knobs[0], filters)
            else:
                tok0 = jnp.argmax(lg0, axis=-1).astype(jnp.int32)
            if ctx.quant:            # mode == "int8": already int8
                kcache, vcache, kscache, vscache = caches
            else:
                kcache, vcache = caches
            out = {kk: [] for kk in bufs}
            for li in range(n_layers):
                if "kc" in bufs:     # mixed: fp arenas pass through
                    out["kc"].append(bufs["kc"][li])
                    out["vc"].append(bufs["vc"][li])
                qkp = bufs["qkc"][li][0]
                qvp = bufs["qvc"][li][0]
                kscp = bufs["ksc"][li][0]
                vscp = bufs["vsc"][li][0]
                if ctx.quant:
                    kq, ksn = kcache[li][0], kscache[li][0]
                    vq, vsn = vcache[li][0], vscache[li][0]
                else:
                    # mixed: the same per-column quantization the
                    # int8 generate path applies at store time
                    kq, ksn = quantize_last(kcache[li][0])
                    vq, vsn = quantize_last(vcache[li][0])
                out["qkc"].append(qkp.at[pages[0]].set(
                    kq.reshape(npref, bs, *qkp.shape[2:]))[None])
                out["qvc"].append(qvp.at[pages[0]].set(
                    vq.reshape(npref, bs, *qvp.shape[2:]))[None])
                out["ksc"].append(kscp.at[pages[0]].set(
                    ksn.reshape(npref, bs, *kscp.shape[2:]))[None])
                out["vsc"].append(vscp.at[pages[0]].set(
                    vsn.reshape(npref, bs, *vscp.shape[2:]))[None])
            return tok0, {kk: tuple(v) for kk, v in out.items()}

        bspecs = self.pool.buffer_specs(self._pool_spec(),
                                        self._scale_spec())
        import jax

        from icikit.parallel.shmap import shard_map as _shard_map
        return jax.jit(_shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(decode_param_specs(cfg), P(None, None),
                      P(DP_AXIS, None), P(None, None), P(None, None),
                      bspecs),
            out_specs=(P(None), bspecs)),
            donate_argnums=(5,)), npref

    # -- admission ---------------------------------------------------

    def _free_slot(self) -> int | None:
        for s, row in enumerate(self.rows):
            if row is None:
                return s
        return None

    def _refresh_btab(self, slot: int, row: _Row) -> None:
        # re-derive the slot's zero-padded block table from the
        # allocator's (the single source of truth after any
        # alloc/share/CoW)
        table = self.pool.allocators[row.shard].table(row.owner)
        self._btab[slot] = 0
        self._btab[slot, :len(table)] = table

    def _shard_of(self, slot: int) -> int:
        return slot // (self.serve.max_rows // self.dp)

    def _validate(self, req: Request, prompt: np.ndarray) -> None:
        sv = self.serve
        if not 1 <= prompt.size <= sv.max_prompt:
            raise PoisonedPromptError(
                f"{req.rid}: prompt length {prompt.size} outside "
                f"[1, {sv.max_prompt}]")
        if prompt.min(initial=0) < 0 or \
                prompt.max(initial=0) >= self.cfg.vocab:
            raise PoisonedPromptError(
                f"{req.rid}: token id outside [0, {self.cfg.vocab})")
        if prompt_checksum(prompt) != req.checksum:
            raise PoisonedPromptError(
                f"{req.rid}: prompt checksum mismatch (corrupted "
                "between submit and admission)")
        if req.n_new > sv.max_new:
            raise PoisonedPromptError(
                f"{req.rid}: n_new={req.n_new} exceeds "
                f"max_new={sv.max_new}")
        if req.quant and self.kv_mode == "none":
            raise PoisonedPromptError(
                f"{req.rid}: quant request on an engine with no int8 "
                "KV arena (kv_quant='none') — silently serving it at "
                "full precision would misreport the path it priced")
        if req.top_k > self.cfg.vocab:
            raise PoisonedPromptError(
                f"{req.rid}: top_k={req.top_k} exceeds "
                f"vocab={self.cfg.vocab}")

    def _admit(self) -> int:
        """Admit queued requests into free slots; returns how many.
        Admission allocates (or cache-shares) the prompt's blocks and
        stages the row for prefill — the compute itself streams
        through :meth:`_advance_prefill`, interleaved with decode
        steps."""
        admitted = 0
        while True:
            slot = self._free_slot()
            if slot is None:
                return admitted
            req = self.queue.claim()
            if req is None:
                return admitted
            chaos.maybe_delay("serve.admit")
            chaos.maybe_die("serve.admit")
            prompt = np.asarray(
                chaos.maybe_corrupt("serve.admit.prompt", req.prompt),
                np.int32)
            try:
                self._validate(req, prompt)
            except PoisonedPromptError as e:
                self.queue.fail(req.rid, e, retry=False,
                                seq=req.claim_seq)
                continue
            shard = self._shard_of(slot)
            s = int(prompt.size)
            quant_row = (self.kv_mode == "int8"
                         or (self.kv_mode == "mixed" and req.quant))
            side = "q8" if quant_row else "fp"
            # pool ownership is keyed by (rid, claim generation): a
            # reaped request re-admitted while a stale row still holds
            # its old blocks must NOT share a block table with it
            owner = f"{req.rid}.{req.claim_seq}"
            p0 = 0
            hit: list = []
            bs = self.serve.block_size
            chain_hexes: list = []
            dedup = self.dedup and side == "fp"
            waiting = False
            try:
                if self.serve.prefix_cache and side == "fp":
                    chain_hexes = block_hashes(prompt, bs, side)
                    hit = self.pool.lookup(shard, chain_hexes)
                    if hit:
                        self.pool.share(owner, shard, hit)
                        p0 = len(hit) * bs
                        if p0 >= s:
                            # full block-aligned hit: the last token's
                            # logits still need computing — recompute
                            # position s-1 (its write CoW-forks the
                            # shared tail block in _prefill_chunk)
                            p0 = s - 1
                # in-flight dedup: if the NEXT block this admission
                # would compute is already being computed by a
                # co-resident prefiller, park as a waiter — attach to
                # the blocks as the prefiller finalizes them instead
                # of duplicating the compute. Suffix blocks (and the
                # pool window) allocate only once waiting resolves.
                waiting = (dedup and len(hit) < len(chain_hexes)
                           and self.pool.announced(
                               shard, chain_hexes[len(hit)]))
                # tiered KV (r16): blocks past the device hit that the
                # host spill tier / persistent store can swap back in.
                # Restores stream through _advance_restore (bounded
                # per loop pass); table blocks for the remainder
                # allocate only once the restores land, so the table
                # stays position-ordered.
                restore_plan: list = []
                if (not waiting and self.serve.prefix_cache
                        and side == "fp"
                        and len(hit) < len(chain_hexes)):
                    restore_plan = self.pool.tier_plan(
                        shard, chain_hexes[len(hit):])
                if not waiting and not restore_plan:
                    self.pool.ensure(owner, shard, s)
            except PoolExhausted:
                # not the request's fault: back off without burning a
                # retry — admission re-attempts once rows evict
                self.pool.release(owner, shard)
                self.queue.release(req.rid, delay=0.005,
                                   seq=req.claim_seq)
                return admitted
            if not waiting and dedup and p0 < s:
                # this row is the prefiller for whatever full blocks
                # it will compute: announce them so a concurrent
                # duplicate waits instead of recomputing (announce
                # skips already-indexed hashes; register() settles
                # each announcement as the block finalizes)
                self.pool.announce(shard, owner,
                                   chain_hexes[len(hit):])
            with obs.span("serve.request", rid=req.rid, s_prompt=s,
                          n_new=req.n_new, slot=slot,
                          prefix_hit=p0):
                now = time.monotonic()
                if req.admit_t is None:
                    req.admit_t = now
                    # re-admissions keep the first admit_t (the SLO
                    # record is per-request) and must not re-emit its
                    # stale wait sample
                    obs.observe("serve.queue_wait_ms",
                                (now - req.arrival_t) * 1e3)
                req.prefix_hit_tokens = p0
                if side == "fp" and self.serve.prefix_cache:
                    # a waiter is served by the in-flight prefill, not
                    # the settled index: it counts under inflight_hits
                    # below, never as a miss (and a p0==0 waiter emits
                    # no hit_tokens sample — its blocks attach later).
                    # A tier-planned admission likewise defers: its
                    # restored tokens count only once the swap-in
                    # digest verifies (_advance_restore) — a corrupt
                    # spill must not have inflated the hit ledger.
                    if p0:
                        self._prefix["hits"] += 1
                        self._prefix["hit_tokens"] += p0
                        if len(hit) * bs >= s:
                            self._prefix["full_hits"] += 1
                        obs.count("serve.prefix.hits")
                        if not restore_plan:
                            # tier-planned admissions emit their ONE
                            # hit_tokens sample at restore settle,
                            # covering device + restored together
                            obs.observe("serve.prefix.hit_tokens",
                                        float(p0))
                    elif not waiting and not restore_plan:
                        self._prefix["misses"] += 1
                        obs.count("serve.prefix.misses")
                        obs.observe("serve.prefix.hit_tokens", 0.0)
                if waiting:
                    self._prefix["inflight_hits"] += 1
                    obs.count("serve.prefix.inflight_hits")
                if restore_plan:
                    self._prefix["spill_hits"] += 1
                    obs.count("serve.prefix.spill_hits")
                n_shared = len(hit)
                # the hexdigest IS the chain state's hex encoding, so
                # resuming the chain past the shared blocks is a
                # decode, not a re-hash
                chain = (bytes.fromhex(chain_hexes[n_shared - 1])
                         if n_shared else chain_seed(side))
                self.rows[slot] = _Row(
                    req=req, shard=shard, s_prompt=s, n_done=0,
                    sealed=n_shared, prefilled=p0, seq=req.claim_seq,
                    owner=owner, side=side, chain=chain,
                    hashes=chain_hexes, waiting=waiting,
                    restore=list(restore_plan),
                    tier_base=(p0 if restore_plan else -1))
                self._toks[slot] = 0
                self._curs[slot] = 0
                self._active[slot] = False
                self._isq[slot] = side == "q8"
                self._refresh_btab(slot, self.rows[slot])
                self._seq_buf[slot] = 0
                self._seq_buf[slot, :s] = prompt
                # sampling state: the canonical per-request stream
                # (decode.request_stream_data — serve/ builds no keys)
                # plus the traced knobs; greedy rows carry temp 0
                self._kdat[slot] = self._stream_data(req.seed)
                self._knobs[slot] = (req.temperature, req.top_p,
                                     req.top_k)
                obs.count("serve.admitted")
                req.trace.instant("serve.req.admitted",
                                  seq=req.claim_seq, slot=slot,
                                  prefix_hit=p0, waiting=waiting,
                                  restoring=len(restore_plan),
                                  side=side)
                if quant_row:
                    # the int8 path keeps whole-prompt admission (see
                    # _build_prefill) — run it to completion here
                    self._prefill_whole(slot, self.rows[slot], prompt)
            admitted += 1

    def _prefill_whole(self, slot: int, row: _Row, prompt) -> None:
        """Quantized admission: one exact-length prefill program,
        LRU-bounded compile cache (keyed by (length, sampled))."""
        s = row.s_prompt
        req = row.req
        key = (s, req.temperature > 0.0,
               req.temperature > 0.0 and (req.top_k > 0
                                          or req.top_p < 1.0))
        if key in self._prefill_fns:
            self._prefill_fns.move_to_end(key)
        else:
            self._prefill_fns[key] = self._build_prefill(
                s, key[1], key[2])
            while len(self._prefill_fns) > PREFILL_PROGRAM_CAP:
                self._prefill_fns.popitem(last=False)
        fn, npref = self._prefill_fns[key]
        table = self.pool.allocators[row.shard].table(row.owner)
        pages = np.zeros((self.dp, npref), np.int32)
        pages[row.shard] = table[:npref]
        with row.req.trace.span("serve.req.prefill.whole",
                                seq=row.seq, s_prompt=s):
            tok0, bufs = fn(self.params, prompt[None], pages,
                            self._kdat[slot:slot + 1],
                            self._knobs[slot:slot + 1],
                            self.pool.buffers())
            self.pool.update(bufs)
        row.prefilled = s
        self._prefix["prefill_tokens"] += s
        self._complete_prefill(slot, row, int(np.asarray(tok0)[0]))

    def _advance_prefill(self) -> None:
        """Run ONE chunk for every row still prefilling — the engine
        loop alternates this with the decode step, so a long prompt
        stalls co-batched decoders by at most one chunk per step (the
        chunked-prefill latency cap). WAITER rows (in-flight dedup)
        poll/attach here instead of computing; a waiter whose wait
        resolved this pass falls straight through to its first own
        chunk."""
        for slot, row in enumerate(self.rows):
            if row is None:
                continue
            if row.waiting:
                self._advance_waiter(slot, row)
                row = self.rows[slot]        # may have been evicted
                if row is None or row.waiting:
                    continue
            if row.restore:
                # tiered swap-in: at most one chunk-width of blocks
                # per pass, interleaved with decode exactly like a
                # compute chunk; a row whose restores finished this
                # pass falls straight through to its first own chunk
                self._advance_restore(slot, row)
                row = self.rows[slot]        # may have been evicted
                if row is None or row.restore:
                    continue
            if row.prefilled >= row.s_prompt:
                continue
            self._prefill_chunk(slot, row)

    def _advance_waiter(self, slot: int, row: _Row) -> None:
        """One poll of a waiter row: attach every newly finalized
        block of its prefix (the prefiller registers blocks
        progressively as its chunks land), then decide whether to
        keep waiting — the next needed hash must still be announced
        by a live prefiller. When the wait resolves (prefiller
        finished, or vanished via eviction/preemption and withdrew
        its announcements) the row allocates its remaining window
        and proceeds through the normal chunk stream for whatever is
        left. The waiter renews its lease every poll: waiting is
        progress, not death."""
        self.queue.renew(row.req.rid, seq=row.seq)
        bs = self.serve.block_size
        s = row.s_prompt
        hit = self.pool.lookup(row.shard, row.hashes)
        if len(hit) > row.sealed:
            new = hit[row.sealed:]
            self.pool.share(row.owner, row.shard, new)
            n_shared = len(hit)
            row.sealed = n_shared
            row.chain = bytes.fromhex(row.hashes[n_shared - 1])
            p0 = n_shared * bs
            if p0 >= s:
                p0 = s - 1          # full duplicate: recompute s-1 only
            # positions this row will now never compute because it
            # waited instead
            self._prefix["inflight_hit_tokens"] += max(
                0, p0 - row.prefilled)
            row.prefilled = p0
            row.req.prefix_hit_tokens = p0
            self._refresh_btab(slot, row)
            row.req.trace.instant("serve.req.dedup_attach",
                                  seq=row.seq, blocks=len(new),
                                  prefilled=p0)
        if (row.sealed < len(row.hashes)
                and self.pool.announced(row.shard,
                                        row.hashes[row.sealed])):
            return                  # still in flight — keep waiting
        # wait resolved: allocate the rest and become a normal
        # (possibly prefilling) row; announce any full blocks WE will
        # now compute (a third duplicate should wait on us)
        row.waiting = False
        if (self.serve.prefix_cache and row.side == "fp"
                and row.sealed < len(row.hashes)):
            # the vanished prefiller's finalized blocks may have been
            # evicted INTO the spill tier in the meantime — check the
            # tiers before recomputing (the restore phase does the
            # ensure/announce once it settles)
            plan = self.pool.tier_plan(row.shard,
                                       row.hashes[row.sealed:])
            if plan:
                row.restore = plan
                row.tier_base = row.prefilled
                self._prefix["spill_hits"] += 1
                obs.count("serve.prefix.spill_hits")
                return
        try:
            added = self.pool.ensure(row.owner, row.shard, s)
        except PoolExhausted:
            self._evict(slot)
            self.queue.release(row.req.rid, delay=0.005, seq=row.seq)
            return
        if added:
            self._refresh_btab(slot, row)
        if self.dedup and row.sealed < len(row.hashes):
            self.pool.announce(row.shard, row.owner,
                               row.hashes[row.sealed:])

    def _advance_restore(self, slot: int, row: _Row) -> None:
        """One pass of tiered swap-in for a row whose admission landed
        on a spilled/persisted chain: restore at most one chunk-width
        of blocks (``prefill_chunk // block_size``, min 1) from the
        host tier or the store, each re-verifying its content digest
        on arrival — so restore stalls on co-batched decoders are
        bounded exactly like compute stalls, and a corrupt swap-in is
        quarantined (the row falls back to recomputing the remainder
        through the normal chunk stream, burning no retry). Hit
        accounting for the restored tokens lands HERE, verified, not
        at admission. Restoring renews the lease: swap-in is
        progress, not death."""
        self.queue.renew(row.req.rid, seq=row.seq)
        bs = self.serve.block_size
        s = row.s_prompt
        n_pass = max(1, self.serve.prefill_chunk // bs)
        t0 = time.monotonic()
        try:
            results, fell_back = self.pool.restore_run(
                row.owner, row.shard, row.restore, n_pass,
                side=row.side)
        except PoolExhausted:
            self._evict(slot)
            self.queue.release(row.req.rid, delay=0.005,
                               seq=row.seq)
            return
        n_done = len(results)
        for out in results:
            if isinstance(out, dict):
                self._prefix["restores"] += 1
                self._prefix["restores_" + out["src"]] += 1
                self._prefix["restore_bytes"] += out["nbytes"]
            h = row.restore.pop(0)
            row.sealed += 1
            row.chain = bytes.fromhex(h)
        if fell_back:
            # a block vanished (tier churn) or failed its swap-in
            # verify (already quarantined by the pool): recompute the
            # rest fresh — never trust, never retry the bytes
            row.restore = []
        if n_done:
            dt_ms = (time.monotonic() - t0) * 1e3
            self._prefix["restore_ms_total"] += dt_ms
            obs.observe("serve.kv.restore_ms", dt_ms)
            p0 = row.sealed * bs
            if p0 >= s:
                p0 = s - 1    # full tier hit: recompute s-1 only
            row.prefilled = p0
            row.req.prefix_hit_tokens = p0
            self._refresh_btab(slot, row)
            row.req.trace.instant("serve.req.restore", seq=row.seq,
                                  blocks=n_done, prefilled=p0)
        if row.restore:
            self.queue.renew(row.req.rid, seq=row.seq)
            return                    # more next pass (bounded stall)
        # restore phase over (drained or fell back to compute):
        # settle the deferred hit accounting against what actually
        # verified, then allocate the remainder and rejoin the normal
        # admission stream
        p0 = row.prefilled
        gained = max(0, p0 - max(row.tier_base, 0))
        if gained:
            if row.tier_base <= 0:
                # no device-hit was counted at admission
                self._prefix["hits"] += 1
                obs.count("serve.prefix.hits")
            if row.sealed * bs >= s:
                self._prefix["full_hits"] += 1
            self._prefix["hit_tokens"] += gained
            self._prefix["spill_hit_tokens"] += gained
        if p0:
            # the admission's ONE hit_tokens sample (deferred from
            # _admit): device-hit + verified-restored tokens together
            obs.observe("serve.prefix.hit_tokens", float(p0))
        elif row.tier_base <= 0:
            # every planned restore fell through: a miss after all
            self._prefix["misses"] += 1
            obs.count("serve.prefix.misses")
            obs.observe("serve.prefix.hit_tokens", 0.0)
        row.tier_base = -1
        try:
            added = self.pool.ensure(row.owner, row.shard, s)
        except PoolExhausted:
            self._evict(slot)
            self.queue.release(row.req.rid, delay=0.005, seq=row.seq)
            return
        if added:
            self._refresh_btab(slot, row)
        if self.dedup and row.sealed < len(row.hashes):
            self.pool.announce(row.shard, row.owner,
                               row.hashes[row.sealed:])
        self.queue.renew(row.req.rid, seq=row.seq)

    def _chunk_width(self, rem: int) -> int:
        rem = min(rem, self.serve.prefill_chunk)
        for w in self._chunk_widths:
            if w >= rem:
                return w
        return self._chunk_widths[-1]

    def _prefill_chunk(self, slot: int, row: _Row) -> None:
        chaos.maybe_delay("serve.prefill.chunk")
        chaos.maybe_die("serve.prefill.chunk")
        # heartbeat per chunk: pre-r11 the whole prefill ran inside
        # the claim's fresh lease window; a chunked prefill spanning
        # many loop passes must renew like the step loop does, or a
        # prompt longer than lease_s gets reaped and reissued while
        # this row keeps computing
        self.queue.renew(row.req.rid, seq=row.seq)
        bs = self.serve.block_size
        s = row.s_prompt
        rem = s - row.prefilled
        width = self._chunk_width(rem)
        n_valid = min(rem, width)
        # CoW guard: never write into a page another owner maps —
        # fork every block the write window touches while it is
        # shared. By construction only the full-hit last-position
        # recompute targets a shared block, but the guard is the
        # invariant, not the construction.
        try:
            forked = False
            for j in range(row.prefilled // bs,
                           (row.prefilled + n_valid - 1) // bs + 1):
                if self.pool.cow(row.owner, row.shard, j,
                                 side=row.side):
                    forked = True
            if forked:
                self._prefix["cow"] += 1
                self._refresh_btab(slot, row)
                row.req.trace.instant("serve.req.cow", seq=row.seq,
                                      at="prefill.chunk")
        except PoolExhausted:
            self._evict(slot)
            self.queue.release(row.req.rid, delay=0.005, seq=row.seq)
            return
        # the sampled tok0 draw compiles only into the FINAL chunk of
        # a sampled request; mid-chunks (and all greedy chunks) run
        # the argmax variant, whose tok0 is identical for greedy rows
        # and discarded for sampled mid-chunks
        final = row.prefilled + n_valid >= s
        req = row.req
        sampled = bool(final and req.temperature > 0.0)
        key = (width, sampled,
               bool(sampled and (req.top_k > 0 or req.top_p < 1.0)))
        if key not in self._chunk_fns:
            self._chunk_fns[key] = self._build_chunk(*key)
        toks = np.zeros((1, width), np.int32)
        toks[0, :n_valid] = self._seq_buf[
            slot, row.prefilled:row.prefilled + n_valid]
        btab = np.zeros((self.dp, self.nb_per_row), np.int32)
        btab[row.shard] = self._btab[slot]
        with obs.span("serve.prefill.chunk", rid=row.req.rid,
                      p0=row.prefilled, width=width, n_valid=n_valid), \
                row.req.trace.span("serve.req.prefill.chunk",
                                   seq=row.seq, p0=row.prefilled,
                                   width=width, n_valid=n_valid):
            tok0, bufs = self._chunk_fns[key](
                self.params, toks,
                np.asarray([row.prefilled], np.int32),
                np.asarray([n_valid], np.int32),
                btab, self._kdat[slot:slot + 1],
                self._knobs[slot:slot + 1], self.pool.buffers())
            self.pool.update(bufs)
        # second heartbeat AFTER the program: a chunk's compile or
        # execute can itself outlast lease_s, and the reaper runs at
        # the loop top right after this returns — the entry renewal
        # alone would leave that window expired
        self.queue.renew(row.req.rid, seq=row.seq)
        row.prefilled += n_valid
        self._prefix["prefill_tokens"] += n_valid
        # progressive finalization (r12): seal + content-register every
        # block the prefilled frontier has fully passed NOW, not at
        # prefill completion — this is what in-flight waiters attach to
        # chunk by chunk, and what lets a later same-prefix admission
        # hit mid-prefill
        if row.n_done == 0:
            self._finalize_blocks(slot, row)
        if row.prefilled >= s:
            # tok0 is only real on the owner shard (P(DP_AXIS) out)
            self._complete_prefill(
                slot, row, int(np.asarray(tok0)[row.shard]))

    def _complete_prefill(self, slot: int, row: _Row,
                          tok0: int) -> None:
        req = row.req
        req.first_token_t = time.monotonic()
        row.last_t = req.first_token_t
        req.trace.instant("serve.req.first_token", seq=row.seq,
                          pos=row.s_prompt)
        row.tokens = [tok0]
        row.n_done = 1
        self._toks[slot] = tok0
        self._curs[slot] = row.s_prompt
        self._active[slot] = True
        self._seq_buf[slot, row.s_prompt] = tok0
        if self.serve.drafter == "suffix" and self.serve.speculate_k > 1:
            sam = SuffixAutomaton()
            for t in self._seq_buf[slot, :row.s_prompt + 1]:
                sam.feed(int(t))
            self._automata[slot] = sam
        self._finalize_blocks(slot, row)
        # a 1-token request (or an immediate EOS) finishes at prefill
        if req.n_new <= 1 or tok0 == req.eos_id:
            self._finish(slot)

    # -- stepping ----------------------------------------------------

    def _ensure_windows(self) -> None:
        """Grow block tables to cover this step's write window (the
        full scratch width — ``w_win`` tree nodes when tree
        speculation is on); a row the pool cannot extend is preempted
        (evicted + re-queued), never silently stalled. Tree windows
        additionally run the CoW guard over every scratch block (the
        guard is the invariant — a scratch write into a refcount>1
        block must fork first — even though decode-frontier blocks
        are never shared by construction): the ``serve.spec.tree
        .fork`` host boundary, drilled in tests/test_serve_chaos.py."""
        k = self.w_win
        tree = self.serve.tree_branch > 1
        bs = self.serve.block_size
        for slot, row in enumerate(self.rows):
            if row is None or row.prefilled < row.s_prompt:
                continue
            try:
                if tree and self._active[slot]:
                    chaos.maybe_delay("serve.spec.tree.fork")
                    chaos.maybe_die("serve.spec.tree.fork")
                added = self.pool.ensure(row.owner, row.shard,
                                         int(self._curs[slot]) + k)
                if tree and self._active[slot]:
                    cur = int(self._curs[slot])
                    forked = False
                    for j in range(cur // bs,
                                   (cur + k - 1) // bs + 1):
                        if self.pool.cow(row.owner, row.shard, j,
                                         side=row.side):
                            forked = True
                    if forked:
                        self._prefix["cow"] += 1
                        obs.count("serve.spec.tree.forks")
                        row.req.trace.instant("serve.req.cow",
                                              seq=row.seq,
                                              at="tree.fork")
                        added = True
            except PoolExhausted:
                # preemption, not failure: the pool filled up around
                # this row — evict and re-queue without burning a retry
                self._evict(slot)
                self.queue.release(row.req.rid, delay=0.005,
                                   seq=row.seq)
                continue
            if added:
                table = self.pool.allocators[row.shard].table(
                    row.owner)
                self._btab[slot, :len(table)] = table

    def _drafts(self) -> np.ndarray:
        k = self.serve.speculate_k
        tb = self.serve.tree_branch
        B = self.serve.max_rows
        if k == 1:
            return np.zeros((B, 0), np.int32)
        if tb > 1:
            # ranked b-way proposals, flattened to the linearized
            # caterpillar node order (depth-major, rank-minor —
            # exactly alts.reshape): column 0 of each depth is the
            # primary chain, bitwise the 1-way draft
            if self.serve.drafter == "suffix":
                out = np.zeros((B, k - 1, tb), np.int32)
                for slot, row in enumerate(self.rows):
                    if row is not None and self._active[slot]:
                        out[slot] = self._automata[slot].top_b(
                            k - 1, tb)
                return out.reshape(B, (k - 1) * tb)
            valid = np.ones(B, np.int32)
            for slot, row in enumerate(self.rows):
                if row is not None:
                    valid[slot] = row.s_prompt + row.n_done
            from icikit.serve.ngram_draft import ngram_propose_b_host
            return ngram_propose_b_host(
                self._seq_buf, valid, k, self.serve.ngram_n,
                tb).reshape(B, (k - 1) * tb)
        if self.serve.drafter == "suffix":
            out = np.zeros((B, k - 1), np.int32)
            for slot, row in enumerate(self.rows):
                if row is not None and self._active[slot]:
                    out[slot] = self._automata[slot].propose(k - 1)
            return out
        valid = np.ones(B, np.int32)
        for slot, row in enumerate(self.rows):
            if row is not None:
                valid[slot] = row.s_prompt + row.n_done
        return ngram_propose_host(self._seq_buf, valid, k,
                                  self.serve.ngram_n)

    def _step(self) -> None:
        chaos.maybe_delay("serve.step")
        chaos.maybe_die("serve.step")
        self._ensure_windows()
        self._chaos_pages()
        if not self._active.any():
            return
        k = self.serve.speculate_k
        live = (bool(self._isq.any()) if self.kv_mode == "mixed"
                else self.kv_mode == "int8")
        # sampled-variant dispatch mirrors the mixed-quant one: the
        # draw math compiles in only when a sampled row is resident,
        # and greedy rows select identically in both variants
        sk = self._knobs[self._active]
        samp = bool((sk[:, 0] > 0.0).any())
        # filters compile in only when some resident sampled row
        # actually arms top-k/top-p — pure-temperature traffic never
        # pays the per-draw vocab sort (the bypass in _sample_filter
        # keeps the variants bitwise-consistent per row)
        filt = bool((((sk[:, 0] > 0.0) & ((sk[:, 2] > 0)
                                          | (sk[:, 1] < 1.0)))).any())
        fkey = (live, samp, filt)
        if fkey not in self._step_fns:
            self._step_fns[fkey] = self._build_step(live, samp, filt)
        tree = self.serve.tree_branch > 1
        tstats = None
        step_no = self.n_steps
        step_attrs = {"step": step_no, "rows": int(self._active.sum())}
        traced = obs.tracing() is not None
        if traced:
            # co-batch roster: the step span names every participant's
            # trace id, so ONE engine step is joinable from EVERY
            # co-batched request's span tree (the causal fan-in a
            # per-request view needs to explain interference)
            step_attrs["roster"] = [
                r.req.trace.trace_id for s, r in enumerate(self.rows)
                if r is not None and self._active[s]]
        with obs.span("serve.engine.step", **step_attrs):
            outs = self._step_fns[fkey](
                self.params, self._toks, self._curs, self._active,
                self._isq, self._btab, self._drafts(),
                self._kdat, self._knobs, self.pool.buffers())
            if tree:
                g, a, newtok, tstats, bufs = outs
                tstats = np.asarray(tstats)
            else:
                g, a, newtok, bufs = outs
            self.pool.update(bufs)
            g = np.asarray(g)
            a = np.asarray(a)
            self._toks = np.asarray(newtok).copy()
        self.n_steps += 1
        now = time.monotonic()
        stepped = self._active.copy()   # rows that ran this step
        self._occ_rows += int(stepped.sum())
        committed = 0
        feed_sam = (self.serve.drafter == "suffix" and k > 1)
        for slot, row in enumerate(self.rows):
            if row is None or not self._active[slot]:
                continue
            req = row.req
            self.queue.renew(req.rid, seq=row.seq)
            a_r = int(a[slot])
            if traced:
                # per-step batch participation: one instant per
                # (request, step) with the verify-window outcome — for
                # k > 1 the step IS the speculation verify window, so
                # accepted-1 is the drafts this window kept (and the
                # tree split rides along)
                sattrs = {"step": step_no, "accepted": a_r}
                if tstats is not None:
                    sattrs["primary"] = int(tstats[slot, 0])
                    sattrs["sideways"] = bool(tstats[slot, 1])
                req.trace.instant("serve.req.step", seq=row.seq,
                                  **sattrs)
            if a_r > 0 and row.n_done < req.n_new:
                # inter-delivery stall: the span since this row last
                # committed — whatever co-batched admission work (a
                # whole-prompt prefill, a chunk) ran in between is IN
                # this gap, which is what the chunked cap bounds
                row.max_gap = max(row.max_gap, now - row.last_t)
                row.last_t = now
            self._curs[slot] += a_r
            take = g[slot, :a_r]
            done = False
            for t in take:
                if row.n_done >= req.n_new:
                    done = True
                    break
                row.tokens.append(int(t))
                self._seq_buf[slot, row.s_prompt + row.n_done] = int(t)
                if feed_sam:
                    self._automata[slot].feed(int(t))
                row.n_done += 1
                committed += 1
                if row.n_done >= req.n_new or \
                        (req.eos_id is not None and int(t) == req.eos_id):
                    done = True
                    break
            self._finalize_blocks(slot, row)
            if done:
                self._finish(slot)
        if k > 1:
            # proposed + accepted together make acceptance derivable
            # from the serve metrics alone — the measured-α row the
            # ROADMAP 3b "auto ladder flip" gates on. "proposed" is
            # per-DEPTH opportunities (k-1 per row-step), not raw
            # tree-node count: a branch-b tree offers (k-1)*b tokens
            # but can accept at most k-1, so this is the figure
            # comparable across branch counts
            obs.count("serve.spec.verify_steps")
            obs.count("serve.spec.row_steps", int(stepped.sum()))
            obs.count("serve.spec.draft_proposed",
                      int(stepped.sum()) * (k - 1))
            obs.count("serve.spec.draft_accepted",
                      int(np.maximum(a[stepped] - 1, 0).sum()))
            if tree:
                # the per-branch split the tree cost model's
                # expected-accepted-length estimator consumes
                obs.count("serve.spec.tree.draft_accepted",
                          int(np.maximum(a[stepped] - 1, 0).sum()))
                obs.count("serve.spec.tree.primary",
                          int(tstats[stepped, 0].sum()))
                obs.count("serve.spec.tree.sideways",
                          int(tstats[stepped, 1].sum()))
        obs.count("serve.tokens", committed)
        obs.gauge("serve.occupancy_rows",
                  float(self._active.sum()) / self.serve.max_rows)
        if obs.metrics() is not None and self.n_steps % 8 == 1:
            # a prefilling row's cursor is still 0 but its computed
            # prompt positions hold real K/V: count them, or the
            # gauge reads 1.0 at every admission and the watch's
            # fragmentation watermark alarms on healthy traffic.
            # Sampled every 8th step: the gauge is a level, the
            # allocator-table walk is real per-step host time
            # (tools/trace_overhead_study.py), and the watch polls at
            # a far coarser interval anyway
            used = {(r.owner, r.shard): max(int(self._curs[s]),
                                            r.prefilled)
                    for s, r in enumerate(self.rows) if r is not None}
            obs.gauge("serve.kv.fragmentation",
                      self.pool.fragmentation(used))

    def _finalize_blocks(self, slot: int, row: _Row) -> None:
        """Seal (integrity) and content-register (prefix cache) every
        block the committed frontier has fully passed. The frontier is
        the pending token's position (its K/V is not yet written) —
        everything before it is final; with a hit, the shared leading
        blocks arrive already finalized (``row.sealed`` starts past
        them). Registration is fp-side only — see the module
        docstring for why quantized pages never enter the index."""
        integ = self.serve.integrity == "pages"
        index = self.serve.prefix_cache and row.side == "fp"
        if not (integ or index):
            return
        bs = self.serve.block_size
        # clamp to the RECORDED-token frontier: a speculative window
        # can accept past n_new (cursor overshoot), leaving positions
        # whose tokens never entered _seq_buf — a chain hash over
        # that region would key real K/V under the wrong (zero) token
        # run and poison the index for future sharers
        frontier = (min(int(self._curs[slot]),
                        row.s_prompt + row.n_done)
                    if row.n_done else row.prefilled)
        table = self.pool.allocators[row.shard].table(row.owner)
        while (row.sealed + 1) * bs <= frontier:
            j = row.sealed
            page = table[j]
            if integ and not self.pool.sealed(row.shard, page):
                self.pool.seal(row.shard, page, side=row.side)
            if index:
                hx, row.chain = chain_extend(
                    row.chain, self._seq_buf[slot, j * bs:(j + 1) * bs])
                self.pool.register(row.shard, page, hx)
            row.sealed += 1

    def _chaos_pages(self) -> None:
        """The KV-page SDC drill hook: when a plan is armed, probe one
        sealed page per occupied row (deterministic order) and write
        any corruption back into the arena — exactly what a real
        in-memory flip would look like to the verify path. With block
        sharing the probed page may be mapped by several rows: every
        one of them must then fail its verify (the shared-prefix
        drill)."""
        if chaos.active() is None or self.serve.integrity != "pages":
            return
        for slot, row in enumerate(self.rows):
            if row is None or row.sealed == 0:
                continue
            table = self.pool.allocators[row.shard].table(row.owner)
            page = table[0]
            data = self.pool.read_page(row.shard, page, 0,
                                       side=row.side)
            out = chaos.maybe_corrupt("serve.kv.page", data)
            if out is not data:
                self.pool.poke_page(row.shard, page, 0, out,
                                    side=row.side)
                obs.emit("serve.kv.page_corrupted", rid=row.req.rid,
                         shard=row.shard, page=int(page))

    # -- eviction / completion ---------------------------------------

    def _evict(self, slot: int) -> None:
        row = self.rows[slot]
        # in-flight announcements die with the row: any waiter on them
        # stops waiting at its next poll and computes the blocks
        # itself (or re-announces them as the new prefiller)
        self.pool.withdraw(row.shard, row.owner)
        self.pool.release(row.owner, row.shard)
        self.rows[slot] = None
        self._active[slot] = False
        self._isq[slot] = False
        self._btab[slot] = 0
        self._knobs[slot] = (0.0, 1.0, 0.0)
        self._kdat[slot] = 0
        self._automata.pop(slot, None)

    def _finish(self, slot: int) -> None:
        row = self.rows[slot]
        req = row.req
        if self.serve.integrity == "pages":
            bad = self.pool.verify(row.owner, row.shard)
            if bad:
                # quarantine corrupted pages from the prefix index
                # BEFORE evicting: no retry (of this or any sharer)
                # may re-attach the bad content
                for bi in bad:
                    self.pool.quarantine(row.owner, row.shard, bi)
                req.trace.instant("serve.req.quarantine", seq=row.seq,
                                  pages=[int(b) for b in bad])
                self._evict(slot)
                self.queue.fail(req.rid, IntegrityError(
                    f"{req.rid}: sealed KV pages {bad} failed "
                    "checksum re-verify"), retry=True, seq=row.seq)
                obs.count("serve.integrity_failures")
                return
        self._evict(slot)
        if row.n_done > 1:
            req.max_gap_ms = row.max_gap * 1e3
        if self.queue.complete(req.rid, row.tokens, seq=row.seq):
            slo = req.slo()
            if "ttft_ms" in slo:
                obs.observe("serve.ttft_ms", slo["ttft_ms"])
            if "tpot_ms" in slo:
                obs.observe("serve.tpot_ms", slo["tpot_ms"])
            if "max_gap_ms" in slo:
                obs.observe("serve.max_gap_ms", slo["max_gap_ms"])

    # -- the loop ----------------------------------------------------

    def run(self, drain: bool = True, max_steps: int | None = None,
            watch=None):
        """Serve until the queue drains (or ``max_steps`` decode steps
        have run); returns the completed-request count for this call.
        Re-entrant: a fresh engine pointed at the same queue picks up
        reissued leases from a dead one. ``watch`` is an optional
        armed :class:`icikit.obs.watch.Watch`: the loop probes it once
        per pass (time-throttled inside ``maybe_poll``), which is what
        gives the anomaly detectors their mid-run windows — the caller
        renders ``watch.verdict()`` afterwards."""
        done0 = len(self.queue.done)
        tiered = (self.serve.host_cache_blocks > 0
                  or self.pool.store is not None)
        while True:
            self.queue.reap_expired()
            self._admit()
            self._advance_prefill()
            if tiered:
                # bounded off-path tier maintenance per pass: settle
                # one pending spill batch (device snapshot -> host
                # bytes, so spilled content stops pinning device
                # memory) and write a couple of queued host-tier
                # demotions through to the store (the allocation path
                # itself never materializes or touches disk)
                self.pool.settle_spills(1)
                self.pool.flush_demotions(2)
            if watch is not None:
                watch.maybe_poll()
            if not self._active.any():
                if any(r is not None and r.prefilled < r.s_prompt
                       for r in self.rows):
                    continue        # prefill still streaming
                if not drain or self.queue.drained():
                    break
                wait = self.queue.next_visible_in()
                if wait is None or wait > 0:
                    time.sleep(0.002 if wait is None
                               else min(wait, 0.05))
                continue
            self._step()
            if max_steps is not None and self.n_steps >= max_steps:
                break
        if self.pool.store is not None and self.queue.drained():
            # drain-time persistence flush (r16): the whole surviving
            # prefix corpus lands in the content-addressed store OFF
            # the serving hot path (a per-finalize write-through was
            # measured costing admission TTFT its tier win) — a
            # restarted engine re-warms from these sealed pages; a
            # crashed run still holds whatever the host-tier demotion
            # cascade flushed
            self.pool.persist_tiers()
        return len(self.queue.done) - done0

    @property
    def row_steps(self) -> int:
        """Total row-steps executed (sum of active rows over steps) —
        the denominator of tokens-per-row-step figures."""
        return self._occ_rows

    def occupancy_mean(self) -> float:
        """Mean decode-batch occupancy over every step so far — the
        quantity continuous batching exists to maximize."""
        if not self.n_steps:
            return 0.0
        return self._occ_rows / (self.n_steps * self.serve.max_rows)

    def resident_chains(self) -> list:
        """Union of chain hashes resident (indexed) on any dp shard —
        what the fleet heartbeat's bloom summary compresses."""
        seen: set = set()
        for a in self.pool.allocators:
            seen.update(a.indexed_hashes())
        return sorted(seen)

    def prefix_stats(self) -> dict:
        """Prefix-cache effectiveness counters for this engine's
        lifetime (bench records carry these)."""
        out = {
            **self._prefix,
            "evictions": sum(a.n_evictions
                             for a in self.pool.allocators),
            "cached_blocks": sum(a.n_cached
                                 for a in self.pool.allocators),
            "chunk_programs": len(self._chunk_fns),
        }
        if self.serve.host_cache_blocks > 0:
            out["spills"] = sum(a.n_spills
                                for a in self.pool.allocators)
            out["spilled_blocks"] = self.pool.spilled_blocks()
        if self.pool.store is not None:
            out["store_blocks"] = self.pool.store.n_blocks()
            out["store_writes"] = self.pool.store.n_writes
            out["store_quarantined"] = self.pool.store.n_quarantined
        return out

    def rewarm(self, prompts=None, max_blocks: int | None = None) -> int:
        """Eagerly re-warm the pool from the persistent store: restore
        every consecutive persisted block of each prompt's chain into
        the CACHED state (refcount-0, indexed — awaiting hits) on
        every dp shard, before traffic flows. ``prompts`` defaults to
        the queue's pending prompts (``RequestQueue.pending_prompts``,
        the restart hook: a fresh engine pointed at a recovered queue
        warms exactly the work it is about to serve). Demand paging at
        admission covers whatever this skips — rewarm only moves the
        disk reads off the first requests' critical path (the
        cold-start-vs-rewarm A/B in tools/tiered_kv_study.py).
        Returns the number of (shard, block) restores performed."""
        if self.pool.store is None or not self.serve.prefix_cache:
            return 0
        if prompts is None:
            prompts = self.queue.pending_prompts()
        bs = self.serve.block_size
        width = max(1, self.serve.prefill_chunk // bs)
        n = 0
        budget = 0       # DISTINCT blocks scheduled (the max_blocks
        seen: set = set()    # unit; n counts per-shard restores)
        for p in prompts:
            hs = [h for h in block_hashes(np.asarray(p, np.int32),
                                          bs, "fp")
                  if h not in seen]
            seen.update(hs)
            if max_blocks is not None:
                hs = hs[:max(0, max_blocks - budget)]
            if hs:
                budget += len(hs)
                n += self.pool.rewarm_chain(hs, width)
        if n:
            obs.count("serve.store.rewarm_blocks", n)
        return n

    def export_chain(self, tokens) -> int:
        """Persist the full-block chain of ``tokens`` (a served
        request's prompt ++ committed tokens) to the attached store —
        the fleet prefill engine's streaming half of a KV migration:
        after its 1-token prefill claim completes, the finalized sealed
        blocks (arena bytes + scale pages + seals, chain-hash-named
        exactly like ``serve/store.py`` files) ship to the block bridge
        BEFORE the handoff requeues the request, so the decode engine's
        admission finds them with ``tier_plan`` and adopts them through
        the ordinary digest-verified restore path. Only index-resident
        pages export (content-addressed: already-present hashes are
        no-ops); returns the number of blocks written. fp side only,
        BY DESIGN: quantized pages never enter the prefix index (the
        r11 parity rule — a cached q8 page cannot reproduce the raw
        prompt-column attention the deployed int8 prefill computes),
        so a quant request has no indexed chain to migrate and its
        decode phase recomputes."""
        if self.pool.store is None or not self.serve.prefix_cache:
            return 0
        bs = self.serve.block_size
        n = 0
        for h in block_hashes(np.asarray(tokens, np.int32), bs, "fp"):
            for shard in range(self.dp):
                page = self.pool.allocators[shard].indexed(h)
                if page is not None and self.pool.persist(
                        shard, page, h):
                    n += 1
        return n

    def reset_stats(self) -> None:
        """Zero the step/occupancy accumulators — the bench calls this
        after its warm-up run so committed occupancy/steps figures
        describe the measured traffic only."""
        self.n_steps = 0
        self._occ_rows = 0
        self._prefix = self._zero_prefix()

    # -- convenience -------------------------------------------------

    def submit(self, prompt, n_new: int, eos_id: int | None = None,
               not_before: float | None = None,
               max_retries: int = 2, quant: bool = False,
               seed: int = 0, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0) -> str:
        """Queue a request on this engine's queue (``RequestQueue
        .submit`` stamps the integrity checksum before the request
        becomes claimable — see ``serve.admit.prompt``). ``quant``
        routes the request's KV pages to the int8 arena on a
        ``kv_quant="mixed"`` engine. ``temperature > 0`` makes the
        request SAMPLED under its own ``seed`` stream: served tokens
        are bitwise ``sample_generate`` with base key ``key(0)`` and
        ``seeds=[seed]`` at the same knobs, for the request alone —
        schedule-invariant by the counter key discipline."""
        return self.queue.submit(prompt, n_new, eos_id=eos_id,
                                 not_before=not_before,
                                 max_retries=max_retries, quant=quant,
                                 seed=seed, temperature=temperature,
                                 top_k=top_k, top_p=top_p)
