"""Continuous-batching serving engine over the shared decode core.

``greedy_generate`` is a *batch* program: B prompts in, B continuations
out, every row marching in lockstep until the slowest finishes. A
serving system faces the opposite shape — requests arrive one at a
time, finish at different lengths, and throughput is set by how full
the decode batch *stays*, not by how big one batch once was. This
engine is the Orca-style composition step over everything below it:

- **prefill/decode disaggregation** — admission runs the request's
  prompt through the shared ``_prefill`` (one compiled program per
  prompt length), scatters its K/V into pool blocks, and produces the
  first token; the decode loop never pays prompt-shaped work.
- **continuous batching** — one fixed-width step program (``B`` rows,
  paged attention over per-row block tables) runs forever; finished
  rows are evicted and their slots re-admitted from the queue at
  *step boundaries* (and, with ``speculate_k >= 2``, at
  speculative-verify boundaries — the step IS the verify window).
- **paged KV cache** — rows gather their own blocks back into a
  contiguous view under a per-row causal mask
  (``_window_masked_attention``), so a corrupted or recycled page can
  only ever be read by the request whose table points at it.
- **token identity** — every committed token is the full model's
  argmax over the row's own committed prefix, computed by the same
  ``_DecodeCtx`` math as single-request decode; outputs are
  greedy-token-identical per request to ``greedy_generate`` (pinned
  across staggered admission, mixed prompt lengths, speculative
  on/off, dp/tp meshes in ``tests/test_serve_engine.py``).
- **speculative serving** — ``speculate_k >= 2`` turns the step into a
  k-token verify window fed by the zero-cost n-gram drafter
  (``serve/ngram_draft.py``); acceptance semantics are exactly
  ``speculative_generate``'s (longest prefix, m matches commit m+1
  tokens).

Scheduling rides :class:`icikit.serve.scheduler.RequestQueue` — leases
renewed per step, expiry reissue (dead-request abandonment), retry
with backoff on transient failures (pool preemption, KV-integrity
mismatch), idempotent completion commits.

SLO accounting flows through ``icikit.obs``: ``serve.ttft_ms`` /
``serve.tpot_ms`` / ``serve.queue_wait_ms`` histograms,
``serve.occupancy_rows`` / ``serve.kv.occupancy`` gauges,
``serve.tokens`` counters, a ``serve.request`` span per admission and
a ``serve.engine.step`` span per step (chrome-checker-valid).

Chaos sites (drilled in ``tests/test_serve_chaos.py``):

- ``serve.admit``        — delay/die at admission;
- ``serve.admit.prompt`` — SDC on the claimed prompt bytes; detection
  is the submit-time checksum → ``PoisonedPromptError`` → rejected
  without retry, engine keeps serving;
- ``serve.step``         — delay/die at the step boundary (a die is an
  engine crash: leases expire, requests reissue to the next engine);
- ``serve.kv.page``      — SDC on a sealed KV page; with
  ``integrity="pages"`` the owner request fails its completion
  verify and retries on fresh blocks while co-batched requests'
  outputs stay bitwise unchanged (containment is structural: nobody
  else's block table maps that page).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from icikit import chaos, obs
from icikit.serve.kvpool import KVPool, PoolExhausted
from icikit.serve.ngram_draft import DEFAULT_N, ngram_propose_host
from icikit.serve.scheduler import (
    PoisonedPromptError,
    Request,
    RequestQueue,
    prompt_checksum,
)


class IntegrityError(RuntimeError):
    """A request's sealed KV pages failed their checksum re-verify."""


@dataclass(frozen=True)
class ServeConfig:
    """Engine geometry — all static (they shape the compiled step)."""

    max_rows: int = 4        # decode batch width B (divisible by dp)
    block_size: int = 8      # KV block = this many token columns
    n_blocks: int = 64       # allocatable blocks per dp shard
    max_prompt: int = 64     # admission ceilings (validation, buffers)
    max_new: int = 64
    speculate_k: int = 1     # 1 = single-token; >= 2 = ngram verify
    ngram_n: int = DEFAULT_N
    integrity: str = "none"  # "none" | "pages" (seal + verify)
    # KV-arena precision: "auto" follows cfg.decode_quant (int8 decode
    # stores int8 KV — the pure bandwidth configuration, no fp arena
    # exists), "none"/"int8" force, "mixed" holds BOTH arenas over one
    # allocator and routes per request (Request.quant) — requires
    # decode_quant="none" so co-batched fp requests stay bitwise
    # identical to an unquantized engine (the containment pin in
    # tests/test_serve_quant.py)
    kv_quant: str = "auto"


@dataclass
class _Row:
    """Host-side state of one occupied engine slot."""

    req: Request
    shard: int
    s_prompt: int
    n_done: int              # committed tokens (includes the pending)
    sealed: int              # blocks checksummed so far
    seq: int = 0             # claim generation captured at admission
    owner: str = ""          # pool-ownership token: rid + claim seq
    side: str = "fp"         # which KV arena serves this row (fp | q8)
    # tokens accumulate HERE, not on the shared Request object: the
    # claim-seq fence covers queue mutations, but a stalled engine
    # resuming after its lease was reaped must also be unable to
    # interleave host-side appends into the live claimant's output —
    # only the fenced complete() publishes a row's tokens
    tokens: list = field(default_factory=list)


class Engine:
    """One engine = one compiled step program + host admission loop.

    ``params`` / ``mesh`` / ``cfg`` are the model triple every decode
    entry point takes; ``serve`` the engine geometry; ``queue`` the
    shared :class:`RequestQueue` (created if omitted — multi-engine
    setups share one queue, which is what makes lease-expiry reissue
    across engines work).
    """

    def __init__(self, params, mesh, cfg, serve: ServeConfig,
                 queue: RequestQueue | None = None):
        from icikit.models.transformer.model import DP_AXIS
        if cfg.n_experts:
            raise ValueError(
                "the serving engine does not support MoE "
                "(n_experts > 0): expert dispatch is a dp all-to-all "
                "whose routing this engine's paged step has not been "
                "built for")
        if serve.speculate_k < 1:
            raise ValueError(
                f"speculate_k must be >= 1, got {serve.speculate_k}")
        if serve.integrity not in ("none", "pages"):
            raise ValueError(
                f"unknown integrity {serve.integrity!r} "
                "(known: none, pages)")
        self.dp = mesh.shape[DP_AXIS]
        if serve.max_rows % self.dp:
            raise ValueError(
                f"max_rows={serve.max_rows} must divide over "
                f"dp={self.dp}")
        k = serve.speculate_k
        horizon = serve.max_prompt + serve.max_new + k - 1
        if horizon > cfg.max_seq:
            raise ValueError(
                f"max_prompt + max_new + k - 1 = {horizon} exceeds "
                f"max_seq = {cfg.max_seq}")
        bs = serve.block_size
        self.nb_per_row = -(-horizon // bs)           # block-table width
        if self.nb_per_row > serve.n_blocks:
            raise ValueError(
                f"one max-size request needs {self.nb_per_row} blocks "
                f"but the pool holds {serve.n_blocks} per shard")
        kv = serve.kv_quant
        if kv == "auto":
            kv = "int8" if cfg.decode_quant == "int8" else "none"
        if kv not in ("none", "int8", "mixed"):
            raise ValueError(f"unknown kv_quant {kv!r} "
                             "(known: auto, none, int8, mixed)")
        if kv == "mixed" and cfg.decode_quant != "none":
            raise ValueError(
                "kv_quant='mixed' requires decode_quant='none': "
                "quantized weights touch every co-batched row, which "
                "breaks the fp-requests-bitwise-unchanged containment "
                "the mixed pool exists for")
        if kv == "none" and cfg.decode_quant == "int8":
            raise ValueError(
                "decode_quant='int8' stores int8 KV (kv_quant 'auto' "
                "or 'int8'): an fp KV arena on the int8 path would "
                "reintroduce the high-precision cache stream the "
                "route exists to remove")
        self.kv_mode = kv
        if cfg.decode_quant == "int8":
            from icikit.models.transformer.decode import (
                maybe_quantize_params,
            )
            # weights quantized ONCE at engine setup; scales ride the
            # pytree into every step/prefill program
            self.params = maybe_quantize_params(params, mesh, cfg)
        else:
            self.params = self._cast_weights(params, cfg)
        self.mesh = mesh
        self.cfg = cfg
        self.serve = serve
        self.queue = queue if queue is not None else RequestQueue()
        self.pool = KVPool(cfg, mesh, serve.n_blocks, bs, quant=kv)
        B = serve.max_rows
        self.rows: list[_Row | None] = [None] * B
        self._toks = np.zeros(B, np.int32)
        self._curs = np.zeros(B, np.int32)
        self._active = np.zeros(B, bool)
        self._isq = np.zeros(B, bool)     # row side (mixed routing)
        self._btab = np.zeros((B, self.nb_per_row), np.int32)
        self._seq_buf = np.zeros(
            (B, serve.max_prompt + serve.max_new), np.int32)
        # mixed mode compiles two step variants and dispatches per
        # step on whether a quantized row is resident (see _build_step)
        self._step_fns: dict = {}
        self._prefill_fns: dict = {}
        self.n_steps = 0
        self._occ_rows = 0       # sum of active rows over steps

    @staticmethod
    def _cast_weights(params, cfg):
        """Pre-cast the matmul weights to the compute dtype ONCE.

        Every layer consumes these via ``.astype(compute_dtype)``;
        inside ``generate``'s single compiled loop XLA hoists that
        conversion out of the scan, but the engine's step is its own
        program per call and would re-convert the parameter stream
        every token. Token identity is unaffected: ``astype`` on an
        already-cast array yields the same round-to-nearest values
        ``generate`` computes in-loop; norm scales, embeddings and
        positional tables stay fp32 (their math is fp32 in both
        paths). Note the XLA:CPU caveat measured in round 9: CPU gemm
        re-packs bf16 operands to fp32 per *call*, so this pre-cast
        only pays on native-bf16 backends — the committed CPU bench
        rows run fp32 compute instead (icikit.bench.serve)."""
        import jax.numpy as jnp

        from icikit.models.transformer.model import _attn_param_keys
        cdt = jnp.dtype(cfg.compute_dtype)
        if cdt == jnp.float32:
            return params
        cast = set(_attn_param_keys(cfg)) | {"wo", "w1", "w2", "w_out"}
        return {k: (v.astype(cdt) if k in cast else v)
                for k, v in params.items()}

    # -- compiled programs -------------------------------------------

    def _pool_spec(self):
        from jax.sharding import PartitionSpec as P

        from icikit.models.transformer.model import DP_AXIS, TP_AXIS
        return P(DP_AXIS, None, None, TP_AXIS, None)

    def _scale_spec(self):
        from jax.sharding import PartitionSpec as P

        from icikit.models.transformer.model import DP_AXIS, TP_AXIS
        return P(DP_AXIS, None, None, TP_AXIS)

    def _build_step(self, quant_live: bool):
        """Compile one step program. ``quant_live`` matters only in
        "mixed" mode: the False variant skips the q8 quantize/write/
        dequant-gather entirely (arenas pass through untouched) so an
        all-fp resident batch pays zero quantization traffic — the
        host dispatches on ``self._isq.any()`` per step, and fp rows
        compute identically in both variants (their gather reads the
        fp arena either way), so flipping programs mid-request cannot
        change an fp row's tokens."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from icikit.models.transformer.decode import (
            _DecodeCtx,
            _window_masked_attention,
            _window_masked_attention_q8,
        )
        from icikit.models.transformer.model import DP_AXIS
        from icikit.models.transformer.quant import decode_param_specs
        from icikit.models.transformer.speculative import _accept_window
        from icikit.ops.quant import quantize_last
        from icikit.ops.rope import apply_rope, rope_sincos

        cfg = self.cfg
        ctx = _DecodeCtx(cfg, self.mesh)
        k = self.serve.speculate_k
        bs = self.serve.block_size
        NB = self.nb_per_row
        T = NB * bs
        n_layers = cfg.n_layers
        mode = self.kv_mode
        if mode == "mixed" and not quant_live:
            touch_q8 = False      # arenas thread through untouched
        else:
            touch_q8 = mode in ("int8", "mixed")

        def per_shard(params, toks, curs, active, isq, btab, drafts,
                      bufs):
            b = toks.shape[0]
            lp = {kk: params[kk] for kk in ctx.layer_keys}
            w_toks = jnp.concatenate([toks[:, None], drafts], axis=1)
            pos = curs[:, None] + jnp.arange(k)[None, :]     # (b, k)
            x = ctx.embed(params, w_toks, pos)
            sincos = (rope_sincos(pos, cfg.d_head, cfg.rope_theta)
                      if cfg.pos_encoding == "rope" else None)
            # per-row causal frontier over the row's own paged view
            mask = (jnp.arange(T)[None, None, :] <= pos[:, :, None])
            # physical write targets; inactive rows park on trash 0
            pages = jnp.take_along_axis(btab, pos // bs, axis=1)
            pages = jnp.where(active[:, None], pages, 0)
            slots = pos % bs
            out = {kk: [] for kk in bufs}
            for li in range(n_layers):
                lp1 = {kk: lp[kk][li] for kk in ctx.layer_keys}
                q, k_, v_ = ctx.qkv_proj(x, lp1)
                if sincos is not None:
                    q = apply_rope(q, pos, cfg.rope_theta, sincos)
                    k_ = apply_rope(k_, pos, cfg.rope_theta, sincos)
                if touch_q8:
                    # quantize-at-write, exactly the generate-path
                    # column quantization (token identity to int8
                    # generate hangs on the byte-for-byte match)
                    kq, ksn = quantize_last(k_)
                    vq, vsn = quantize_last(v_)
                    qkp, qvp = bufs["qkc"][li][0], bufs["qvc"][li][0]
                    kscp = bufs["ksc"][li][0]
                    vscp = bufs["vsc"][li][0]
                    qkp = qkp.at[pages, slots].set(kq)
                    qvp = qvp.at[pages, slots].set(vq)
                    kscp = kscp.at[pages, slots].set(ksn)
                    vscp = vscp.at[pages, slots].set(vsn)
                    out["qkc"].append(qkp[None])
                    out["qvc"].append(qvp[None])
                    out["ksc"].append(kscp[None])
                    out["vsc"].append(vscp[None])
                elif mode == "mixed":
                    for kk in ("qkc", "qvc", "ksc", "vsc"):
                        out[kk].append(bufs[kk][li])
                if mode in ("none", "mixed"):
                    kp, vp = bufs["kc"][li][0], bufs["vc"][li][0]
                    kp = kp.at[pages, slots].set(k_.astype(kp.dtype))
                    vp = vp.at[pages, slots].set(v_.astype(vp.dtype))
                    out["kc"].append(kp[None])
                    out["vc"].append(vp[None])
                # the paged gather: this row's blocks, contiguous again
                if mode == "int8":
                    ks = qkp[btab].reshape(b, T, *qkp.shape[2:])
                    vs = qvp[btab].reshape(b, T, *qvp.shape[2:])
                    ksc = kscp[btab].reshape(b, T, *kscp.shape[2:])
                    vsc = vscp[btab].reshape(b, T, *vscp.shape[2:])
                    attn = _window_masked_attention_q8(
                        q, ks, vs, ksc, vsc, mask, ctx.scale,
                        ctx.n_rep)
                else:
                    ks = kp[btab].reshape(b, T, *kp.shape[2:])
                    vs = vp[btab].reshape(b, T, *vp.shape[2:])
                    if touch_q8:
                        # per-row arena select on the gathered INPUTS:
                        # fp rows' lanes pass through exactly (their
                        # attention sees the identical values a pure-fp
                        # engine gathers — the containment pin), int8
                        # rows read their dequantized pages
                        kdq = (qkp[btab].reshape(b, T, *qkp.shape[2:])
                               .astype(jnp.float32)
                               * kscp[btab].reshape(
                                   b, T, *kscp.shape[2:])[..., None])
                        vdq = (qvp[btab].reshape(b, T, *qvp.shape[2:])
                               .astype(jnp.float32)
                               * vscp[btab].reshape(
                                   b, T, *vscp.shape[2:])[..., None])
                        sel = isq[:, None, None, None]
                        ks = jnp.where(sel, kdq.astype(ks.dtype), ks)
                        vs = jnp.where(sel, vdq.astype(vs.dtype), vs)
                    attn = _window_masked_attention(q, ks, vs, mask,
                                                    ctx.scale,
                                                    ctx.n_rep)
                x = ctx.close_attn(x, attn, lp1)
                x = ctx.ffn(x, lp1)
            g = jnp.argmax(ctx.logits(params, x),
                           axis=-1).astype(jnp.int32)        # (b, k)
            # the ONE accept rule, shared with speculative_generate —
            # the engine-vs-generate identity contract hangs on it
            _, a, new_tok = _accept_window(w_toks, g, active)
            return (g, a, jnp.where(active, new_tok, toks),
                    {kk: tuple(v) for kk, v in out.items()})

        bspecs = self.pool.buffer_specs(self._pool_spec(),
                                        self._scale_spec())
        import jax

        from icikit.parallel.shmap import shard_map as _shard_map
        # pools are DONATED: the step rewrites the whole arena
        # functionally, and without donation XLA must copy every
        # buffer per token step (pool.update drops the old refs, so
        # reuse is safe; KVPool allocates distinct per-layer buffers)
        return jax.jit(_shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(decode_param_specs(cfg), P(DP_AXIS), P(DP_AXIS),
                      P(DP_AXIS), P(DP_AXIS), P(DP_AXIS, None),
                      P(DP_AXIS, None), bspecs),
            out_specs=(P(DP_AXIS, None), P(DP_AXIS), P(DP_AXIS),
                       bspecs)), donate_argnums=(7,))

    def _build_prefill(self, s_prompt: int, quant_row: bool):
        """``quant_row`` matters only in "mixed" mode: an fp
        admission's prefill skips the q8-arena quantize/scatter (its
        pages live in the fp arena; the q arenas pass through), a
        quant admission's skips the fp scatter — each request pays
        exactly its own side's bytes."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from icikit.models.transformer.decode import _DecodeCtx, _prefill
        from icikit.models.transformer.model import DP_AXIS
        from icikit.models.transformer.quant import decode_param_specs
        from icikit.ops.quant import quantize_last

        cfg = self.cfg
        ctx = _DecodeCtx(cfg, self.mesh)
        bs = self.serve.block_size
        npref = -(-s_prompt // bs)
        n_layers = cfg.n_layers
        mode = self.kv_mode
        touch_fp = mode == "none" or (mode == "mixed" and not quant_row)
        touch_q8 = mode == "int8" or (mode == "mixed" and quant_row)

        def per_shard(params, prompt, pages, bufs):
            # prompt replicated: every shard computes the same prefill;
            # only the owner shard's pages are real (others trash 0)
            x, caches = _prefill(ctx, params, prompt, s_prompt,
                                 npref * bs, fused=False)
            tok0 = jnp.argmax(ctx.logits(params, x[:, -1]),
                              axis=-1).astype(jnp.int32)
            if ctx.quant:            # mode == "int8": already int8
                kcache, vcache, kscache, vscache = caches
            else:
                kcache, vcache = caches
            out = {kk: [] for kk in bufs}
            for li in range(n_layers):
                if "kc" in bufs and not touch_fp:
                    out["kc"].append(bufs["kc"][li])
                    out["vc"].append(bufs["vc"][li])
                elif "kc" in bufs:
                    kp, vp = bufs["kc"][li][0], bufs["vc"][li][0]
                    kb = kcache[li][0].reshape(npref, bs,
                                               *kp.shape[2:])
                    vb = vcache[li][0].reshape(npref, bs,
                                               *vp.shape[2:])
                    out["kc"].append(
                        kp.at[pages[0]].set(kb.astype(kp.dtype))[None])
                    out["vc"].append(
                        vp.at[pages[0]].set(vb.astype(vp.dtype))[None])
                if "qkc" in bufs and not touch_q8:
                    for kk in ("qkc", "qvc", "ksc", "vsc"):
                        out[kk].append(bufs[kk][li])
                elif "qkc" in bufs:
                    qkp = bufs["qkc"][li][0]
                    qvp = bufs["qvc"][li][0]
                    kscp = bufs["ksc"][li][0]
                    vscp = bufs["vsc"][li][0]
                    if ctx.quant:
                        kq, ksn = kcache[li][0], kscache[li][0]
                        vq, vsn = vcache[li][0], vscache[li][0]
                    else:
                        # mixed: the same per-column quantization the
                        # int8 generate path applies at store time
                        kq, ksn = quantize_last(kcache[li][0])
                        vq, vsn = quantize_last(vcache[li][0])
                    out["qkc"].append(qkp.at[pages[0]].set(
                        kq.reshape(npref, bs, *qkp.shape[2:]))[None])
                    out["qvc"].append(qvp.at[pages[0]].set(
                        vq.reshape(npref, bs, *qvp.shape[2:]))[None])
                    out["ksc"].append(kscp.at[pages[0]].set(
                        ksn.reshape(npref, bs, *kscp.shape[2:]))[None])
                    out["vsc"].append(vscp.at[pages[0]].set(
                        vsn.reshape(npref, bs, *vscp.shape[2:]))[None])
            return tok0, {kk: tuple(v) for kk, v in out.items()}

        bspecs = self.pool.buffer_specs(self._pool_spec(),
                                        self._scale_spec())
        import jax

        from icikit.parallel.shmap import shard_map as _shard_map
        return jax.jit(_shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(decode_param_specs(cfg), P(None, None),
                      P(DP_AXIS, None), bspecs),
            out_specs=(P(None), bspecs)),
            donate_argnums=(3,)), npref

    # -- admission ---------------------------------------------------

    def _free_slot(self) -> int | None:
        for s, row in enumerate(self.rows):
            if row is None:
                return s
        return None

    def _shard_of(self, slot: int) -> int:
        return slot // (self.serve.max_rows // self.dp)

    def _validate(self, req: Request, prompt: np.ndarray) -> None:
        sv = self.serve
        if not 1 <= prompt.size <= sv.max_prompt:
            raise PoisonedPromptError(
                f"{req.rid}: prompt length {prompt.size} outside "
                f"[1, {sv.max_prompt}]")
        if prompt.min(initial=0) < 0 or \
                prompt.max(initial=0) >= self.cfg.vocab:
            raise PoisonedPromptError(
                f"{req.rid}: token id outside [0, {self.cfg.vocab})")
        if prompt_checksum(prompt) != req.checksum:
            raise PoisonedPromptError(
                f"{req.rid}: prompt checksum mismatch (corrupted "
                "between submit and admission)")
        if req.n_new > sv.max_new:
            raise PoisonedPromptError(
                f"{req.rid}: n_new={req.n_new} exceeds "
                f"max_new={sv.max_new}")
        if req.quant and self.kv_mode == "none":
            raise PoisonedPromptError(
                f"{req.rid}: quant request on an engine with no int8 "
                "KV arena (kv_quant='none') — silently serving it at "
                "full precision would misreport the path it priced")

    def _admit(self) -> int:
        """Admit queued requests into free slots; returns how many."""
        admitted = 0
        while True:
            slot = self._free_slot()
            if slot is None:
                return admitted
            req = self.queue.claim()
            if req is None:
                return admitted
            chaos.maybe_delay("serve.admit")
            chaos.maybe_die("serve.admit")
            prompt = np.asarray(
                chaos.maybe_corrupt("serve.admit.prompt", req.prompt),
                np.int32)
            try:
                self._validate(req, prompt)
            except PoisonedPromptError as e:
                self.queue.fail(req.rid, e, retry=False,
                                seq=req.claim_seq)
                continue
            shard = self._shard_of(slot)
            s = int(prompt.size)
            # pool ownership is keyed by (rid, claim generation): a
            # reaped request re-admitted while a stale row still holds
            # its old blocks must NOT share a block table with it
            owner = f"{req.rid}.{req.claim_seq}"
            try:
                self.pool.ensure(owner, shard, s)
            except PoolExhausted:
                # not the request's fault: back off without burning a
                # retry — admission re-attempts once rows evict
                self.queue.release(req.rid, delay=0.005,
                                   seq=req.claim_seq)
                return admitted
            with obs.span("serve.request", rid=req.rid, s_prompt=s,
                          n_new=req.n_new, slot=slot):
                self._prefill_into(req, prompt, slot, shard, owner)
            admitted += 1

    def _prefill_into(self, req: Request, prompt, slot: int,
                      shard: int, owner: str) -> None:
        quant_row = (self.kv_mode == "int8"
                     or (self.kv_mode == "mixed" and req.quant))
        key = (prompt.size, quant_row)
        if key not in self._prefill_fns:
            self._prefill_fns[key] = self._build_prefill(prompt.size,
                                                         quant_row)
        fn, npref = self._prefill_fns[key]
        table = self.pool.allocators[shard].table(owner)
        pages = np.zeros((self.dp, npref), np.int32)
        pages[shard] = table[:npref]
        tok0, bufs = fn(self.params, prompt[None], pages,
                        self.pool.buffers())
        self.pool.update(bufs)
        tok0 = int(np.asarray(tok0)[0])
        now = time.monotonic()
        first_admission = req.admit_t is None
        if first_admission:
            req.admit_t = now
        req.first_token_t = now
        side = "q8" if quant_row else "fp"
        self.rows[slot] = _Row(req=req, shard=shard,
                               s_prompt=int(prompt.size), n_done=1,
                               sealed=0, seq=req.claim_seq,
                               owner=owner, side=side, tokens=[tok0])
        self._toks[slot] = tok0
        self._curs[slot] = prompt.size
        self._active[slot] = True
        self._isq[slot] = side == "q8"
        self._btab[slot] = 0
        self._btab[slot, :len(table)] = table
        self._seq_buf[slot] = 0
        self._seq_buf[slot, :prompt.size] = prompt
        self._seq_buf[slot, prompt.size] = tok0
        obs.count("serve.admitted")
        if first_admission:
            # re-admissions keep the first admit_t (the SLO record is
            # per-request) and must not re-emit its stale wait sample
            obs.observe("serve.queue_wait_ms",
                        (req.admit_t - req.arrival_t) * 1e3)
        # a 1-token request (or an immediate EOS) finishes at prefill
        if req.n_new <= 1 or tok0 == req.eos_id:
            self._finish(slot)

    # -- stepping ----------------------------------------------------

    def _ensure_windows(self) -> None:
        """Grow block tables to cover this step's write window; a row
        the pool cannot extend is preempted (evicted + re-queued),
        never silently stalled."""
        k = self.serve.speculate_k
        for slot, row in enumerate(self.rows):
            if row is None:
                continue
            try:
                added = self.pool.ensure(row.owner, row.shard,
                                         int(self._curs[slot]) + k)
            except PoolExhausted:
                # preemption, not failure: the pool filled up around
                # this row — evict and re-queue without burning a retry
                self._evict(slot)
                self.queue.release(row.req.rid, delay=0.005,
                                   seq=row.seq)
                continue
            if added:
                table = self.pool.allocators[row.shard].table(
                    row.owner)
                self._btab[slot, :len(table)] = table

    def _drafts(self) -> np.ndarray:
        k = self.serve.speculate_k
        B = self.serve.max_rows
        if k == 1:
            return np.zeros((B, 0), np.int32)
        valid = np.ones(B, np.int32)
        for slot, row in enumerate(self.rows):
            if row is not None:
                valid[slot] = row.s_prompt + row.n_done
        return ngram_propose_host(self._seq_buf, valid, k,
                                  self.serve.ngram_n)

    def _step(self) -> None:
        chaos.maybe_delay("serve.step")
        chaos.maybe_die("serve.step")
        self._ensure_windows()
        self._chaos_pages()
        if not self._active.any():
            return
        k = self.serve.speculate_k
        live = (bool(self._isq.any()) if self.kv_mode == "mixed"
                else self.kv_mode == "int8")
        if live not in self._step_fns:
            self._step_fns[live] = self._build_step(live)
        with obs.span("serve.engine.step", step=self.n_steps,
                      rows=int(self._active.sum())):
            g, a, newtok, bufs = self._step_fns[live](
                self.params, self._toks, self._curs, self._active,
                self._isq, self._btab, self._drafts(),
                self.pool.buffers())
            self.pool.update(bufs)
            g = np.asarray(g)
            a = np.asarray(a)
            self._toks = np.asarray(newtok).copy()
        self.n_steps += 1
        stepped = self._active.copy()   # rows that ran this step
        self._occ_rows += int(stepped.sum())
        committed = 0
        for slot, row in enumerate(self.rows):
            if row is None or not self._active[slot]:
                continue
            req = row.req
            self.queue.renew(req.rid, seq=row.seq)
            a_r = int(a[slot])
            self._curs[slot] += a_r
            take = g[slot, :a_r]
            done = False
            for t in take:
                if row.n_done >= req.n_new:
                    done = True
                    break
                row.tokens.append(int(t))
                self._seq_buf[slot, row.s_prompt + row.n_done] = int(t)
                row.n_done += 1
                committed += 1
                if row.n_done >= req.n_new or \
                        (req.eos_id is not None and int(t) == req.eos_id):
                    done = True
                    break
            if self.serve.integrity == "pages":
                self._seal(slot, row)
            if done:
                self._finish(slot)
        if k > 1:
            # proposed + accepted together make acceptance derivable
            # from the serve metrics alone — the measured-α row the
            # ROADMAP 3b "auto ladder flip" gates on
            obs.count("serve.spec.verify_steps")
            obs.count("serve.spec.row_steps", int(stepped.sum()))
            obs.count("serve.spec.draft_proposed",
                      int(stepped.sum()) * (k - 1))
            obs.count("serve.spec.draft_accepted",
                      int(np.maximum(a[stepped] - 1, 0).sum()))
        obs.count("serve.tokens", committed)
        obs.gauge("serve.occupancy_rows",
                  float(self._active.sum()) / self.serve.max_rows)
        if obs.metrics() is not None:
            used = {(r.owner, r.shard): int(self._curs[s])
                    for s, r in enumerate(self.rows) if r is not None}
            obs.gauge("serve.kv.fragmentation",
                      self.pool.fragmentation(used))

    def _seal(self, slot: int, row: _Row) -> None:
        """Checksum blocks the committed frontier has fully passed.
        The frontier is the pending token's position (its K/V is not
        yet written) — everything before it is final."""
        frontier = int(self._curs[slot])
        bs = self.serve.block_size
        table = self.pool.allocators[row.shard].table(row.owner)
        while (row.sealed + 1) * bs <= frontier:
            self.pool.seal(row.owner, row.shard, row.sealed,
                           table[row.sealed], side=row.side)
            row.sealed += 1

    def _chaos_pages(self) -> None:
        """The KV-page SDC drill hook: when a plan is armed, probe one
        sealed page per occupied row (deterministic order) and write
        any corruption back into the arena — exactly what a real
        in-memory flip would look like to the verify path."""
        if chaos.active() is None or self.serve.integrity != "pages":
            return
        for slot, row in enumerate(self.rows):
            if row is None or row.sealed == 0:
                continue
            table = self.pool.allocators[row.shard].table(row.owner)
            page = table[0]
            data = self.pool.read_page(row.shard, page, 0,
                                       side=row.side)
            out = chaos.maybe_corrupt("serve.kv.page", data)
            if out is not data:
                self.pool.poke_page(row.shard, page, 0, out,
                                    side=row.side)
                obs.emit("serve.kv.page_corrupted", rid=row.req.rid,
                         shard=row.shard, page=int(page))

    # -- eviction / completion ---------------------------------------

    def _evict(self, slot: int) -> None:
        row = self.rows[slot]
        self.pool.free(row.owner, row.shard)
        self.rows[slot] = None
        self._active[slot] = False
        self._isq[slot] = False
        self._btab[slot] = 0

    def _finish(self, slot: int) -> None:
        row = self.rows[slot]
        req = row.req
        if self.serve.integrity == "pages":
            bad = self.pool.verify(row.owner, row.shard)
            if bad:
                self._evict(slot)
                self.queue.fail(req.rid, IntegrityError(
                    f"{req.rid}: sealed KV pages {bad} failed "
                    "checksum re-verify"), retry=True, seq=row.seq)
                obs.count("serve.integrity_failures")
                return
        self._evict(slot)
        if self.queue.complete(req.rid, row.tokens, seq=row.seq):
            slo = req.slo()
            if "ttft_ms" in slo:
                obs.observe("serve.ttft_ms", slo["ttft_ms"])
            if "tpot_ms" in slo:
                obs.observe("serve.tpot_ms", slo["tpot_ms"])

    # -- the loop ----------------------------------------------------

    def run(self, drain: bool = True, max_steps: int | None = None):
        """Serve until the queue drains (or ``max_steps`` decode steps
        have run); returns the completed-request count for this call.
        Re-entrant: a fresh engine pointed at the same queue picks up
        reissued leases from a dead one."""
        done0 = len(self.queue.done)
        while True:
            self.queue.reap_expired()
            self._admit()
            if not self._active.any():
                if not drain or self.queue.drained():
                    break
                wait = self.queue.next_visible_in()
                if wait is None or wait > 0:
                    time.sleep(0.002 if wait is None
                               else min(wait, 0.05))
                continue
            self._step()
            if max_steps is not None and self.n_steps >= max_steps:
                break
        return len(self.queue.done) - done0

    @property
    def row_steps(self) -> int:
        """Total row-steps executed (sum of active rows over steps) —
        the denominator of tokens-per-row-step figures."""
        return self._occ_rows

    def occupancy_mean(self) -> float:
        """Mean decode-batch occupancy over every step so far — the
        quantity continuous batching exists to maximize."""
        if not self.n_steps:
            return 0.0
        return self._occ_rows / (self.n_steps * self.serve.max_rows)

    def reset_stats(self) -> None:
        """Zero the step/occupancy accumulators — the bench calls this
        after its warm-up run so committed occupancy/steps figures
        describe the measured traffic only."""
        self.n_steps = 0
        self._occ_rows = 0

    # -- convenience -------------------------------------------------

    def submit(self, prompt, n_new: int, eos_id: int | None = None,
               not_before: float | None = None,
               max_retries: int = 2, quant: bool = False) -> str:
        """Queue a request on this engine's queue (``RequestQueue
        .submit`` stamps the integrity checksum before the request
        becomes claimable — see ``serve.admit.prompt``). ``quant``
        routes the request's KV pages to the int8 arena on a
        ``kv_quant="mixed"`` engine."""
        return self.queue.submit(prompt, n_new, eos_id=eos_id,
                                 not_before=not_before,
                                 max_retries=max_retries, quant=quant)
