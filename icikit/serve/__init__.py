"""icikit.serve — continuous-batching serving engine.

The composition layer ROADMAP item 1 asked for: the token-identical
decode core (``models/transformer/decode.py``), the lease-queue
self-healing pattern (``models/solitaire/scheduler.py``), and the obs
bus, assembled into a multi-request engine with a paged KV cache,
SLO accounting, and request-level chaos drills. See docs/SERVING.md
for the architecture and ``icikit.bench.serve`` for the Poisson
benchmark.
"""

from icikit.serve.engine import (  # noqa: F401
    Engine,
    IntegrityError,
    ServeConfig,
    prompt_checksum,
)
from icikit.serve.kvpool import (  # noqa: F401
    BlockAllocator,
    KVPool,
    PoolExhausted,
    block_hashes,
)
from icikit.serve.ngram_draft import (  # noqa: F401
    SuffixAutomaton,
    ngram_propose,
    ngram_propose_host,
)
from icikit.serve.store import (  # noqa: F401
    PrefixStore,
)
from icikit.serve.scheduler import (  # noqa: F401
    PoisonedPromptError,
    Request,
    RequestQueue,
)
