"""End-to-end observability demo + self-check (``make trace-demo``).

Runs the three instrumented paths small — a transformer train loop, a
``solve_dynamic`` solitaire run (with a chaos drill so recovery events
fire), and one collective sweep — under an armed obs session, then:

- exports the span timeline as a Chrome trace and **validates** it
  (:func:`icikit.obs.chrome.validate`: well-nested B/E per thread,
  monotonic timestamps);
- writes the metrics snapshot and checks the acceptance keys are
  present (``train.step_ms``, ``scheduler.reissues``,
  ``collective.bytes``);
- measures the disabled-path overhead (``bench_overhead``) so the
  zero-cost claim is re-verified on the machine at hand.

Exit code 0 iff everything above holds. CLI::

    JAX_PLATFORMS=cpu python -m icikit.obs.demo \\
        --trace /tmp/trace.json --metrics /tmp/metrics.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trace", default="trace.json")
    ap.add_argument("--metrics", default="obs_metrics.json")
    ap.add_argument("--devices", type=int, default=4)
    args = ap.parse_args(argv)

    # Simulated multi-device CPU mesh. `import jax` has already
    # happened (the icikit package pulls it in), but the XLA *backend*
    # initializes lazily on first device query — until then both
    # XLA_FLAGS and the config API still take effect. Same dance as
    # tests/conftest.py and bench.run --simulate.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{args.devices}").strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.devices)
    except (RuntimeError, AttributeError):
        pass  # pre-0.5 jax: the XLA_FLAGS path above did the job
    if jax.device_count() < 2:
        print(f"note: running on {jax.device_count()} device(s); "
              "scheduler healing needs >= 2 workers", file=sys.stderr)

    from icikit import chaos, obs
    from icikit.bench.harness import sweep_collective
    from icikit.models.solitaire.dataset import generate_dataset
    from icikit.models.solitaire.scheduler import solve_dynamic
    from icikit.models.transformer.train import train
    from icikit.utils.mesh import make_mesh

    # overhead first, while obs is still fully disabled (an env-armed
    # session would make the measurement meaningless — skip it then)
    overhead = None
    if obs.tracing() is None and not obs.enabled():
        overhead = obs.bench_overhead(n=100_000)

    with obs.session(trace=True, metrics=True) as s:
        with obs.span("demo.train"):
            rc = train(["--steps", "6", "--batch", "4", "--vocab", "32",
                        "--d-model", "32", "--n-heads", "2",
                        "--d-head", "8", "--d-ff", "64",
                        "--n-layers", "1", "--seq", "16",
                        "--compute-dtype", "float32",
                        "--log-every", "3", "--sample-tokens", "0"])
        # one worker dies on its first pull -> lease reissue events
        plan = chaos.FaultPlan(schedule={"die:solitaire.worker.1": (0,)})
        with obs.span("demo.solve"), chaos.inject(plan):
            rep = solve_dynamic(generate_dataset(24, "easy", seed=7),
                                chunk_size=4)
        with obs.span("demo.collectives"):
            recs = sweep_collective(make_mesh(), "allgather", "ring",
                                    sizes=[256], runs=2, warmup=1)
        events = s.trace.snapshot()
        snap = s.registry.snapshot()

    obs.chrome.export(args.trace, events)
    problems = obs.chrome.validate(args.trace)
    with open(args.metrics, "w") as f:
        json.dump(obs.json_safe(snap), f, indent=1)

    need = {"train.step_ms": snap["histograms"],
            "scheduler.reissues": snap["counters"],
            "collective.bytes": snap["counters"]}
    missing = [k for k, table in need.items() if k not in table]
    ok = (rc == 0 and not problems and not missing
          and rep.n_deaths == 1 and rep.n_reissues > 0
          and all(r.verified for r in recs))
    print(json.dumps({
        "event": "trace_demo",
        "trace": args.trace, "trace_events": len(events),
        "trace_valid": not problems,
        "metrics": args.metrics,
        "metrics_keys_missing": missing,
        "scheduler_reissues": snap["counters"].get("scheduler.reissues"),
        "collective_bytes": snap["counters"].get("collective.bytes"),
        "train_step_ms_p50": snap["histograms"]
            .get("train.step_ms", {}).get("p50"),
        "disabled_overhead": overhead,
        "ok": ok,
    }))
    for p in problems:
        print(f"INVALID TRACE: {p}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
