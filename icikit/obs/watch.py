"""Metrics anomaly watch: windowed detectors over the live registry.

The metrics registry records *what happened*; nothing in the stack
says *whether that is healthy*. This layer closes the loop: a
:class:`Watch` polls the registry mid-run (lock-scoped snapshots —
safe against concurrent engine-thread emits), differences consecutive
snapshots into **windows**, runs pluggable :class:`Watcher`\\ s over
each window, emits every finding as a structured ``obs.alert`` event
on the bus, and renders a per-run health **verdict** that
``bench.serve`` stamps into its records and the chaos soaks can gate
on. The "Cores that don't count" posture applies to telemetry too:
the serve counters are *validated* against expectations here, not
assumed healthy because they exist.

Detectors (the serve catalog — docs/OBSERVABILITY.md):

- :class:`SloBurnRate` — windowed SLO violation fraction on a latency
  histogram (``serve.ttft_ms`` / ``serve.tpot_ms`` /
  ``serve.max_gap_ms``). The numerator is an exact above-threshold
  count the histogram maintains from arming (``Histogram.track_over``)
  — decimated percentiles cannot give a violation *fraction*.
- :class:`AcceptanceDrop` — windowed draft-acceptance ratio for the
  speculation route (``serve.spec.draft_accepted`` over
  ``serve.spec.draft_proposed``) under a floor: a drafter gone cold
  silently turns every verify window into pure overhead.
- :class:`GaugeWatermark` — high/low watermarks on gauges
  (``serve.kv.fragmentation`` high, ``serve.kv.occupancy`` high,
  ``serve.occupancy_rows`` low at saturation).
- :class:`SpillThrash` — spill-tier thrash watermark (r16): windowed
  restore rate ~ eviction rate with real volume on both means the
  tiered KV cache is churning (restored blocks evicted again inside
  one window) instead of serving — the device pool is under-sized
  for the working set.
- :class:`RateAlarm` — windowed counter-rate alarms where the healthy
  rate is (near) zero: duplicate commits, integrity failures,
  quarantined pages, reissues.

Zero-overhead contract: the watch only costs when polled, and polling
a disabled registry is a no-op; the one hot-path addition is the
armed over-threshold compare inside ``Histogram.observe`` (nothing
when no threshold is armed, i.e. always nothing unless a Watch is).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from icikit.obs import bus as _bus
from icikit.obs import metrics as _metrics


@dataclass
class Alert:
    """One detector finding over one window."""

    watch: str              # detector name ("slo_burn[serve.ttft_ms]")
    metric: str             # the metric that tripped
    value: float            # observed value (burn rate, ratio, level)
    threshold: float        # the configured bound it crossed
    severity: str = "warn"
    detail: str = ""

    def to_event(self) -> dict:
        return {"watch": self.watch, "metric": self.metric,
                "value": self.value, "threshold": self.threshold,
                "severity": self.severity, "detail": self.detail}


class Watcher:
    """Detector interface: ``check(window, snap)`` returns alerts for
    ONE polling window. ``window`` carries deltas (counters, histogram
    count/sum/over) plus current gauge levels and the window's
    wall-span; ``snap`` is the full cumulative snapshot for detectors
    that want run-so-far context. ``arm(registry)`` runs once at
    attach — the hook over-threshold detectors use to register their
    crossings before traffic flows."""

    name = "watcher"

    def arm(self, registry) -> None:
        pass

    def check(self, window: dict, snap: dict) -> list:
        raise NotImplementedError  # pragma: no cover - interface


class SloBurnRate(Watcher):
    """Windowed SLO burn: fraction of window observations above
    ``threshold`` exceeding ``budget`` (with at least ``min_count``
    observations in the window, so an idle window cannot alarm on one
    straggler)."""

    def __init__(self, metric: str, threshold: float,
                 budget: float = 0.25, min_count: int = 8):
        self.metric = metric
        self.threshold = float(threshold)
        self.budget = budget
        self.min_count = min_count
        self.name = f"slo_burn[{metric}]"

    def arm(self, registry) -> None:
        registry.histogram(self.metric).track_over(self.threshold)

    def check(self, window: dict, snap: dict) -> list:
        h = window["histograms"].get(self.metric)
        if h is None or h["count"] < self.min_count:
            return []
        burn = h["over"].get(str(self.threshold), 0) / h["count"]
        if burn <= self.budget:
            return []
        return [Alert(self.name, self.metric, round(burn, 4),
                      self.budget,
                      detail=f"{h['count']} obs in window, SLO "
                             f"{self.threshold}")]


class AcceptanceDrop(Watcher):
    """Windowed draft-acceptance ratio under ``floor`` (speculation
    gone cold). Windows offering fewer than ``min_proposed`` draft
    positions are skipped — the ratio is meaningless at low volume,
    and a non-speculative run never proposes at all."""

    def __init__(self, floor: float = 0.005, min_proposed: int = 64,
                 accepted: str = "serve.spec.draft_accepted",
                 proposed: str = "serve.spec.draft_proposed"):
        self.floor = floor
        self.min_proposed = min_proposed
        self.accepted = accepted
        self.proposed = proposed
        self.name = f"acceptance[{proposed}]"

    def check(self, window: dict, snap: dict) -> list:
        c = window["counters"]
        prop = c.get(self.proposed, 0)
        if prop < self.min_proposed:
            return []
        ratio = c.get(self.accepted, 0) / prop
        if ratio >= self.floor:
            return []
        return [Alert(self.name, self.accepted, round(ratio, 4),
                      self.floor,
                      detail=f"{prop} proposed in window")]


class GaugeWatermark(Watcher):
    """Current gauge level outside ``[low, high]`` (either bound
    optional; a gauge the run never wrote is skipped, never treated
    as zero)."""

    def __init__(self, gauge: str, high: float | None = None,
                 low: float | None = None):
        self.gauge = gauge
        self.high = high
        self.low = low
        self.name = f"watermark[{gauge}]"

    def check(self, window: dict, snap: dict) -> list:
        v = window["gauges"].get(self.gauge)
        if v is None:
            return []
        out = []
        if self.high is not None and v > self.high:
            out.append(Alert(self.name, self.gauge, v, self.high,
                             detail="above high watermark"))
        if self.low is not None and v < self.low:
            out.append(Alert(self.name, self.gauge, v, self.low,
                             detail="below low watermark"))
        return out


class SpillThrash(Watcher):
    """Spill-tier thrash (r16): windowed restore rate ~ eviction rate
    with real volume on both — blocks the tier swaps back in are
    being evicted again within the window, so the tier is churning
    memory bandwidth instead of serving the prefix population (the
    device pool is simply too small for the working set). Both
    counters must clear ``min_blocks`` and their ratio must sit
    inside ``band`` of 1.0 — a healthy warm-up window restores
    without evicting, and a healthy pressure window evicts cold
    content without re-restoring it."""

    def __init__(self, min_blocks: int = 16, band: float = 0.5,
                 restores: str = "serve.prefix.restores",
                 evictions: str = "serve.kv.evictions"):
        self.min_blocks = min_blocks
        self.band = band
        self.restores = restores
        self.evictions = evictions
        self.name = f"spill_thrash[{restores}]"

    def check(self, window: dict, snap: dict) -> list:
        c = window["counters"]
        r = c.get(self.restores, 0)
        e = c.get(self.evictions, 0)
        if r < self.min_blocks or e < self.min_blocks:
            return []
        ratio = r / e
        if not (1.0 - self.band) <= ratio <= (1.0 + self.band):
            return []
        return [Alert(self.name, self.restores, round(ratio, 4),
                      self.band,
                      detail=f"{r} restores ~ {e} evictions in one "
                             "window — spill tier churning, device "
                             "pool under-sized for the working set")]


class RateAlarm(Watcher):
    """Counter moved more than ``max_in_window`` inside one window —
    for counters whose healthy rate is zero (duplicate commits,
    integrity failures, quarantines)."""

    def __init__(self, counter: str, max_in_window: int = 0,
                 severity: str = "error"):
        self.counter = counter
        self.max_in_window = max_in_window
        self.severity = severity
        self.name = f"rate[{counter}]"

    def check(self, window: dict, snap: dict) -> list:
        d = window["counters"].get(self.counter, 0)
        if d <= self.max_in_window:
            return []
        return [Alert(self.name, self.counter, d, self.max_in_window,
                      severity=self.severity,
                      detail="window count over alarm bound")]


@dataclass
class _WatchState:
    prev: dict | None = None
    prev_t: float = 0.0
    polls: int = 0
    alerts: list = field(default_factory=list)


class Watch:
    """Detector harness over one registry.

    ``attach()`` arms the detectors (over-threshold registration) and
    baselines the first window; ``maybe_poll()`` is the engine-loop
    probe (time-throttled to ``min_interval_s``); ``poll()`` forces a
    window; ``verdict()`` closes the final window and renders the
    per-run health record. Registry resolution is late (armed registry
    at call time) unless one is pinned at construction, so a Watch
    built before ``obs.enable_metrics()`` still works.
    """

    def __init__(self, *watchers: Watcher, registry=None,
                 min_interval_s: float = 0.05):
        self.watchers = list(watchers)
        self._registry = registry
        self.min_interval_s = min_interval_s
        self._st = _WatchState()
        self._armed = False

    def registry(self):
        return self._registry if self._registry is not None \
            else _metrics.metrics()

    def attach(self) -> "Watch":
        reg = self.registry()
        if reg is None:
            return self
        if not self._armed:
            for w in self.watchers:
                w.arm(reg)
            self._armed = True
        self._st.prev = reg.snapshot()
        self._st.prev_t = time.monotonic()
        return self

    def maybe_poll(self) -> None:
        st = self._st
        if st.prev is None:
            return
        now = time.monotonic()
        if now - st.prev_t < self.min_interval_s:
            return
        self.poll()

    def poll(self) -> list:
        """One window: snapshot, difference, run detectors, emit
        ``obs.alert`` events; returns this window's alerts."""
        reg = self.registry()
        st = self._st
        if reg is None or st.prev is None:
            return []
        snap = reg.snapshot()
        now = time.monotonic()
        window = _window(st.prev, snap, now - st.prev_t)
        st.prev, st.prev_t = snap, now
        st.polls += 1
        alerts = []
        for w in self.watchers:
            alerts.extend(w.check(window, snap))
        for a in alerts:
            _bus.emit("obs.alert", **a.to_event())
        st.alerts.extend(alerts)
        return alerts

    def verdict(self) -> dict:
        """Close the final window and render the per-run health record
        (the shape ``bench.serve`` stamps into its rows)."""
        self.poll()
        st = self._st
        return {
            "healthy": not st.alerts,
            "n_alerts": len(st.alerts),
            "polls": st.polls,
            "watchers": [w.name for w in self.watchers],
            "alerts": [a.to_event() for a in st.alerts],
        }


def _window(prev: dict, snap: dict, seconds: float) -> dict:
    """Difference two registry snapshots into one window record."""
    counters = {k: v - prev["counters"].get(k, 0)
                for k, v in snap["counters"].items()}
    hists = {}
    for k, h in snap["histograms"].items():
        p = prev["histograms"].get(k, {})
        pover = p.get("over", {})
        hists[k] = {
            "count": h["count"] - p.get("count", 0),
            "sum": h["sum"] - p.get("sum", 0.0),
            "over": {t: n - pover.get(t, 0)
                     for t, n in h.get("over", {}).items()},
        }
    return {"seconds": seconds, "counters": counters,
            "histograms": hists, "gauges": dict(snap["gauges"])}


def serve_watch(ttft_slo_ms: float = 5_000.0,
                tpot_slo_ms: float = 1_000.0,
                gap_slo_ms: float = 5_000.0,
                burn_budget: float = 0.25,
                acceptance_floor: float = 0.005,
                frag_high: float = 0.9,
                occupancy_high: float = 0.98,
                registry=None,
                min_interval_s: float = 0.05) -> Watch:
    """The standard serving watch: SLO burn on the three latency
    histograms, speculation acceptance floor, KV
    fragmentation/occupancy watermarks, the spill-tier thrash
    detector, and zero-tolerance alarms on duplicate commits,
    integrity failures, and quarantined pages.
    Defaults are deliberately loose for CPU-scale smoke traffic — a
    clean run must verdict healthy; tune per deployment."""
    return Watch(
        SloBurnRate("serve.ttft_ms", ttft_slo_ms, burn_budget),
        SloBurnRate("serve.tpot_ms", tpot_slo_ms, burn_budget),
        SloBurnRate("serve.max_gap_ms", gap_slo_ms, burn_budget),
        AcceptanceDrop(acceptance_floor),
        GaugeWatermark("serve.kv.fragmentation", high=frag_high),
        GaugeWatermark("serve.kv.occupancy", high=occupancy_high),
        SpillThrash(),
        RateAlarm("serve.duplicate_commits"),
        RateAlarm("serve.integrity_failures"),
        RateAlarm("serve.prefix.quarantined"),
        registry=registry, min_interval_s=min_interval_s,
    )


def fleet_watch(pending_high: float = 8.0,
                ttft_slo_ms: float = 30_000.0,
                burn_budget: float = 0.5,
                min_count: int = 4,
                registry=None,
                min_interval_s: float = 0.1) -> Watch:
    """The coordinator-side watch driving the elastic roster (r18):
    queue-depth watermark on ``fleet.pending`` (sustained backlog →
    spawn) and SLO burn on coordinator-observed TTFT (commit-time
    minus submit-time — survives engine death, unlike engine-local
    marks). The harness polls ``verdict()`` via the ``fleet_stats``
    RPC and turns alerts into join/retire decisions; the coordinator
    itself only measures. Duplicate commits stay zero-tolerance — a
    failover that double-commits is a fencing bug, not load."""
    return Watch(
        GaugeWatermark("fleet.pending", high=pending_high),
        SloBurnRate("serve.ttft_ms", ttft_slo_ms, burn_budget,
                    min_count=min_count),
        RateAlarm("serve.duplicate_commits"),
        registry=registry, min_interval_s=min_interval_s,
    )
