"""Metrics anomaly watch: windowed detectors over the live registry.

The metrics registry records *what happened*; nothing in the stack
says *whether that is healthy*. This layer closes the loop: a
:class:`Watch` polls the registry mid-run (lock-scoped snapshots —
safe against concurrent engine-thread emits), differences consecutive
snapshots into **windows**, runs pluggable :class:`Watcher`\\ s over
each window, emits every finding as a structured ``obs.alert`` event
on the bus, and renders a per-run health **verdict** that
``bench.serve`` stamps into its records and the chaos soaks can gate
on. The "Cores that don't count" posture applies to telemetry too:
the serve counters are *validated* against expectations here, not
assumed healthy because they exist.

Detectors (the serve catalog — docs/OBSERVABILITY.md):

- :class:`SloBurnRate` — windowed SLO violation fraction on a latency
  histogram (``serve.ttft_ms`` / ``serve.tpot_ms`` /
  ``serve.max_gap_ms``). The numerator is an exact above-threshold
  count the histogram maintains from arming (``Histogram.track_over``)
  — decimated percentiles cannot give a violation *fraction*.
- :class:`AcceptanceDrop` — windowed draft-acceptance ratio for the
  speculation route (``serve.spec.draft_accepted`` over
  ``serve.spec.draft_proposed``) under a floor: a drafter gone cold
  silently turns every verify window into pure overhead.
- :class:`GaugeWatermark` — high/low watermarks on gauges
  (``serve.kv.fragmentation`` high, ``serve.kv.occupancy`` high,
  ``serve.occupancy_rows`` low at saturation).
- :class:`SpillThrash` — spill-tier thrash watermark (r16): windowed
  restore rate ~ eviction rate with real volume on both means the
  tiered KV cache is churning (restored blocks evicted again inside
  one window) instead of serving — the device pool is under-sized
  for the working set.
- :class:`RateAlarm` — windowed counter-rate alarms where the healthy
  rate is (near) zero: duplicate commits, integrity failures,
  quarantined pages, reissues.
- :class:`StragglerOutlier` — cross-source outlier detection for the
  fleet: one engine's windowed mean latency k× the fleet median. Runs
  under a :class:`MultiWatch`, which keeps a **per-source** window per
  engine-labeled stream so one engine's burst cannot mask another's
  SLO burn (the fleet collector's harness —
  :mod:`icikit.obs.aggregate`).

Zero-overhead contract: the watch only costs when polled, and polling
a disabled registry is a no-op; the one hot-path addition is the
armed over-threshold compare inside ``Histogram.observe`` (nothing
when no threshold is armed, i.e. always nothing unless a Watch is).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from icikit.obs import bus as _bus
from icikit.obs import metrics as _metrics


@dataclass
class Alert:
    """One detector finding over one window."""

    watch: str              # detector name ("slo_burn[serve.ttft_ms]")
    metric: str             # the metric that tripped
    value: float            # observed value (burn rate, ratio, level)
    threshold: float        # the configured bound it crossed
    severity: str = "warn"
    detail: str = ""
    source: str = ""        # emitting stream ("eng0") in multi-source
                            # watches; empty for process-local watches

    def to_event(self) -> dict:
        ev = {"watch": self.watch, "metric": self.metric,
              "value": self.value, "threshold": self.threshold,
              "severity": self.severity, "detail": self.detail}
        if self.source:
            ev["source"] = self.source
        return ev


class Watcher:
    """Detector interface: ``check(window, snap)`` returns alerts for
    ONE polling window. ``window`` carries deltas (counters, histogram
    count/sum/over) plus current gauge levels and the window's
    wall-span; ``snap`` is the full cumulative snapshot for detectors
    that want run-so-far context. ``arm(registry)`` runs once at
    attach — the hook over-threshold detectors use to register their
    crossings before traffic flows."""

    name = "watcher"

    def arm(self, registry) -> None:
        pass

    def check(self, window: dict, snap: dict) -> list:
        raise NotImplementedError  # pragma: no cover - interface


class SloBurnRate(Watcher):
    """Windowed SLO burn: fraction of window observations above
    ``threshold`` exceeding ``budget`` (with at least ``min_count``
    observations in the window, so an idle window cannot alarm on one
    straggler)."""

    def __init__(self, metric: str, threshold: float,
                 budget: float = 0.25, min_count: int = 8):
        self.metric = metric
        self.threshold = float(threshold)
        self.budget = budget
        self.min_count = min_count
        self.name = f"slo_burn[{metric}]"

    def arm(self, registry) -> None:
        registry.histogram(self.metric).track_over(self.threshold)

    def check(self, window: dict, snap: dict) -> list:
        h = window["histograms"].get(self.metric)
        if h is None or h["count"] < self.min_count:
            return []
        burn = h["over"].get(str(self.threshold), 0) / h["count"]
        if burn <= self.budget:
            return []
        return [Alert(self.name, self.metric, round(burn, 4),
                      self.budget,
                      detail=f"{h['count']} obs in window, SLO "
                             f"{self.threshold}")]


class AcceptanceDrop(Watcher):
    """Windowed draft-acceptance ratio under ``floor`` (speculation
    gone cold). Windows offering fewer than ``min_proposed`` draft
    positions are skipped — the ratio is meaningless at low volume,
    and a non-speculative run never proposes at all."""

    def __init__(self, floor: float = 0.005, min_proposed: int = 64,
                 accepted: str = "serve.spec.draft_accepted",
                 proposed: str = "serve.spec.draft_proposed"):
        self.floor = floor
        self.min_proposed = min_proposed
        self.accepted = accepted
        self.proposed = proposed
        self.name = f"acceptance[{proposed}]"

    def check(self, window: dict, snap: dict) -> list:
        c = window["counters"]
        prop = c.get(self.proposed, 0)
        if prop < self.min_proposed:
            return []
        ratio = c.get(self.accepted, 0) / prop
        if ratio >= self.floor:
            return []
        return [Alert(self.name, self.accepted, round(ratio, 4),
                      self.floor,
                      detail=f"{prop} proposed in window")]


class GaugeWatermark(Watcher):
    """Current gauge level outside ``[low, high]`` (either bound
    optional; a gauge the run never wrote is skipped, never treated
    as zero)."""

    def __init__(self, gauge: str, high: float | None = None,
                 low: float | None = None):
        self.gauge = gauge
        self.high = high
        self.low = low
        self.name = f"watermark[{gauge}]"

    def check(self, window: dict, snap: dict) -> list:
        v = window["gauges"].get(self.gauge)
        if v is None:
            return []
        out = []
        if self.high is not None and v > self.high:
            out.append(Alert(self.name, self.gauge, v, self.high,
                             detail="above high watermark"))
        if self.low is not None and v < self.low:
            out.append(Alert(self.name, self.gauge, v, self.low,
                             detail="below low watermark"))
        return out


class SpillThrash(Watcher):
    """Spill-tier thrash (r16): windowed restore rate ~ eviction rate
    with real volume on both — blocks the tier swaps back in are
    being evicted again within the window, so the tier is churning
    memory bandwidth instead of serving the prefix population (the
    device pool is simply too small for the working set). Both
    counters must clear ``min_blocks`` and their ratio must sit
    inside ``band`` of 1.0 — a healthy warm-up window restores
    without evicting, and a healthy pressure window evicts cold
    content without re-restoring it."""

    def __init__(self, min_blocks: int = 16, band: float = 0.5,
                 restores: str = "serve.prefix.restores",
                 evictions: str = "serve.kv.evictions"):
        self.min_blocks = min_blocks
        self.band = band
        self.restores = restores
        self.evictions = evictions
        self.name = f"spill_thrash[{restores}]"

    def check(self, window: dict, snap: dict) -> list:
        c = window["counters"]
        r = c.get(self.restores, 0)
        e = c.get(self.evictions, 0)
        if r < self.min_blocks or e < self.min_blocks:
            return []
        ratio = r / e
        if not (1.0 - self.band) <= ratio <= (1.0 + self.band):
            return []
        return [Alert(self.name, self.restores, round(ratio, 4),
                      self.band,
                      detail=f"{r} restores ~ {e} evictions in one "
                             "window — spill tier churning, device "
                             "pool under-sized for the working set")]


class RateAlarm(Watcher):
    """Counter moved more than ``max_in_window`` inside one window —
    for counters whose healthy rate is zero (duplicate commits,
    integrity failures, quarantines)."""

    def __init__(self, counter: str, max_in_window: int = 0,
                 severity: str = "error"):
        self.counter = counter
        self.max_in_window = max_in_window
        self.severity = severity
        self.name = f"rate[{counter}]"

    def check(self, window: dict, snap: dict) -> list:
        d = window["counters"].get(self.counter, 0)
        if d <= self.max_in_window:
            return []
        return [Alert(self.name, self.counter, d, self.max_in_window,
                      severity=self.severity,
                      detail="window count over alarm bound")]


class StragglerOutlier:
    """Cross-source detector: one source's windowed mean latency at
    ``factor``× the fleet median ("Cores that don't count": a
    garbage-computing or merely-sick host shows up as the outlier
    against its peers, not against an absolute bound). Consumes the
    per-source windows a :class:`MultiWatch` assembles —
    ``check_sources(windows)`` instead of the single-stream
    ``check(window, snap)`` — because an outlier is only definable
    against the other sources' same-window behavior. Sources offering
    fewer than ``min_count`` observations in the window are excluded
    from both the median and the verdict, and fewer than
    ``min_sources`` participating sources means no verdict at all (a
    1-engine fleet has no peers to be an outlier against)."""

    def __init__(self, metric: str = "serve.tpot_ms",
                 factor: float = 3.0, min_count: int = 4,
                 min_sources: int = 2, severity: str = "warn"):
        self.metric = metric
        self.factor = factor
        self.min_count = min_count
        self.min_sources = min_sources
        self.severity = severity
        self.name = f"straggler[{metric}]"

    def check_sources(self, windows: dict) -> list:
        means = {}
        for src, w in windows.items():
            h = (w or {}).get("histograms", {}).get(self.metric)
            if h and h["count"] >= self.min_count:
                means[src] = h["sum"] / h["count"]
        if len(means) < self.min_sources:
            return []
        ranked = sorted(means.values())
        mid = len(ranked) // 2
        median = (ranked[mid] if len(ranked) % 2
                  else (ranked[mid - 1] + ranked[mid]) / 2.0)
        if median <= 0:
            return []
        bound = self.factor * median
        return [Alert(self.name, self.metric, round(m, 3),
                      round(bound, 3), severity=self.severity,
                      source=src,
                      detail=f"windowed mean {self.factor}x over "
                             f"fleet median {median:.3f} ms "
                             f"({len(means)} sources)")
                for src, m in sorted(means.items()) if m > bound]


@dataclass
class _WatchState:
    prev: dict | None = None
    prev_t: float = 0.0
    polls: int = 0
    alerts: list = field(default_factory=list)


class Watch:
    """Detector harness over one registry.

    ``attach()`` arms the detectors (over-threshold registration) and
    baselines the first window; ``maybe_poll()`` is the engine-loop
    probe (time-throttled to ``min_interval_s``); ``poll()`` forces a
    window; ``verdict()`` closes the final window and renders the
    per-run health record. Registry resolution is late (armed registry
    at call time) unless one is pinned at construction, so a Watch
    built before ``obs.enable_metrics()`` still works.
    """

    def __init__(self, *watchers: Watcher, registry=None,
                 min_interval_s: float = 0.05, source: str = ""):
        self.watchers = list(watchers)
        self._registry = registry
        self.min_interval_s = min_interval_s
        self.source = source
        self.last_window: dict | None = None
        self._st = _WatchState()
        self._armed = False

    def registry(self):
        return self._registry if self._registry is not None \
            else _metrics.metrics()

    def attach(self) -> "Watch":
        reg = self.registry()
        if reg is None:
            return self
        if not self._armed:
            for w in self.watchers:
                w.arm(reg)
            self._armed = True
        self._st.prev = reg.snapshot()
        self._st.prev_t = time.monotonic()
        return self

    def maybe_poll(self) -> None:
        st = self._st
        if st.prev is None:
            return
        now = time.monotonic()
        if now - st.prev_t < self.min_interval_s:
            return
        self.poll()

    def poll(self) -> list:
        """One window: snapshot, difference, run detectors, emit
        ``obs.alert`` events; returns this window's alerts."""
        reg = self.registry()
        st = self._st
        if reg is None or st.prev is None:
            return []
        snap = reg.snapshot()
        now = time.monotonic()
        window = _window(st.prev, snap, now - st.prev_t)
        st.prev, st.prev_t = snap, now
        self.last_window = window
        st.polls += 1
        alerts = []
        for w in self.watchers:
            alerts.extend(w.check(window, snap))
        for a in alerts:
            if self.source and not a.source:
                a.source = self.source
            _bus.emit("obs.alert", **a.to_event())
        st.alerts.extend(alerts)
        return alerts

    def verdict(self) -> dict:
        """Close the final window and render the per-run health record
        (the shape ``bench.serve`` stamps into its rows)."""
        self.poll()
        st = self._st
        return {
            "healthy": not st.alerts,
            "n_alerts": len(st.alerts),
            "polls": st.polls,
            "watchers": [w.name for w in self.watchers],
            "alerts": [a.to_event() for a in st.alerts],
        }


class MultiWatch:
    """Detector harness over MANY labeled streams (the fleet
    collector's shape).

    The r15 :class:`Watch` differences ONE registry — aggregating N
    engines' observations into it would let one engine's burst mask
    another's SLO burn (the burn *fraction* averages out). Here every
    source gets its OWN registry, detector set, and window:
    ``observe(source, metric, v)`` feeds the per-source stream,
    ``poll()`` windows each source independently (alerts stamped with
    their source), then hands the side-by-side window dict to the
    cross-source detectors (:class:`StragglerOutlier`) that only make
    sense over peers. Per-source detectors come from ``make_watchers``
    — a factory, not instances, because detector state (armed
    thresholds) must not be shared across sources."""

    def __init__(self, make_watchers=None, cross=(),
                 min_interval_s: float = 0.25):
        self.make_watchers = make_watchers or (lambda: [])
        self.cross = list(cross)
        self.min_interval_s = min_interval_s
        self._lock = threading.Lock()
        self._sources: dict = {}    # source -> (Registry, Watch)
        self.alerts: list = []
        self.polls = 0
        self._prev_t = time.monotonic()

    def registry(self, source: str):
        """The per-source registry (created on first touch)."""
        with self._lock:
            entry = self._sources.get(source)
            if entry is None:
                reg = _metrics.Registry()
                w = Watch(*self.make_watchers(), registry=reg,
                          source=source, min_interval_s=0.0)
                w.attach()
                entry = self._sources[source] = (reg, w)
            return entry[0]

    def observe(self, source: str, metric: str, value) -> None:
        self.registry(source).histogram(metric).observe(value)

    def count(self, source: str, metric: str, n=1) -> None:
        self.registry(source).counter(metric).add(n)

    def sources(self) -> list:
        with self._lock:
            return sorted(self._sources)

    def maybe_poll(self) -> list:
        if time.monotonic() - self._prev_t < self.min_interval_s:
            return []
        return self.poll()

    def poll(self) -> list:
        with self._lock:
            entries = list(self._sources.items())
        self._prev_t = time.monotonic()
        self.polls += 1
        alerts: list = []
        windows: dict = {}
        for source, (_, w) in entries:
            alerts.extend(w.poll())
            windows[source] = w.last_window
        for det in self.cross:
            for a in det.check_sources(windows):
                _bus.emit("obs.alert", **a.to_event())
                alerts.append(a)
        self.alerts.extend(alerts)
        return alerts

    def verdict(self) -> dict:
        self.poll()
        return {
            "healthy": not self.alerts,
            "n_alerts": len(self.alerts),
            "polls": self.polls,
            "sources": self.sources(),
            "alerts": [a.to_event() for a in self.alerts],
        }


def _window(prev: dict, snap: dict, seconds: float) -> dict:
    """Difference two registry snapshots into one window record."""
    counters = {k: v - prev["counters"].get(k, 0)
                for k, v in snap["counters"].items()}
    hists = {}
    for k, h in snap["histograms"].items():
        p = prev["histograms"].get(k, {})
        pover = p.get("over", {})
        hists[k] = {
            "count": h["count"] - p.get("count", 0),
            "sum": h["sum"] - p.get("sum", 0.0),
            "over": {t: n - pover.get(t, 0)
                     for t, n in h.get("over", {}).items()},
        }
    return {"seconds": seconds, "counters": counters,
            "histograms": hists, "gauges": dict(snap["gauges"])}


def serve_watch(ttft_slo_ms: float = 5_000.0,
                tpot_slo_ms: float = 1_000.0,
                gap_slo_ms: float = 5_000.0,
                burn_budget: float = 0.25,
                acceptance_floor: float = 0.005,
                frag_high: float = 0.9,
                occupancy_high: float = 0.98,
                registry=None,
                min_interval_s: float = 0.05) -> Watch:
    """The standard serving watch: SLO burn on the three latency
    histograms, speculation acceptance floor, KV
    fragmentation/occupancy watermarks, the spill-tier thrash
    detector, and zero-tolerance alarms on duplicate commits,
    integrity failures, and quarantined pages.
    Defaults are deliberately loose for CPU-scale smoke traffic — a
    clean run must verdict healthy; tune per deployment."""
    return Watch(
        SloBurnRate("serve.ttft_ms", ttft_slo_ms, burn_budget),
        SloBurnRate("serve.tpot_ms", tpot_slo_ms, burn_budget),
        SloBurnRate("serve.max_gap_ms", gap_slo_ms, burn_budget),
        AcceptanceDrop(acceptance_floor),
        GaugeWatermark("serve.kv.fragmentation", high=frag_high),
        GaugeWatermark("serve.kv.occupancy", high=occupancy_high),
        SpillThrash(),
        RateAlarm("serve.duplicate_commits"),
        RateAlarm("serve.integrity_failures"),
        RateAlarm("serve.prefix.quarantined"),
        registry=registry, min_interval_s=min_interval_s,
    )


def fleet_watch(pending_high: float = 8.0,
                ttft_slo_ms: float = 30_000.0,
                burn_budget: float = 0.5,
                min_count: int = 4,
                registry=None,
                min_interval_s: float = 0.1) -> Watch:
    """The coordinator-side watch driving the elastic roster (r18):
    queue-depth watermark on ``fleet.pending`` (sustained backlog →
    spawn) and SLO burn on coordinator-observed TTFT (commit-time
    minus submit-time — survives engine death, unlike engine-local
    marks). The harness polls ``verdict()`` via the ``fleet_stats``
    RPC and turns alerts into join/retire decisions; the coordinator
    itself only measures. Duplicate commits stay zero-tolerance — a
    failover that double-commits is a fencing bug, not load."""
    return Watch(
        GaugeWatermark("fleet.pending", high=pending_high),
        SloBurnRate("serve.ttft_ms", ttft_slo_ms, burn_budget,
                    min_count=min_count),
        RateAlarm("serve.duplicate_commits"),
        registry=registry, min_interval_s=min_interval_s,
    )
