"""CLI entry for the Chrome-trace structural validator.

``python -m icikit.obs.chrome`` works but trips runpy's
found-in-sys.modules RuntimeWarning (the package ``__init__`` imports
:mod:`icikit.obs.chrome` before runpy re-executes it as ``__main__``);
this module is NOT imported by the package, so the blessed CLI stays
warning-free::

    python -m icikit.obs.check trace.json    # exit 0 iff valid
"""

from __future__ import annotations

import sys

from icikit.obs.chrome import main

if __name__ == "__main__":
    sys.exit(main())
