"""Chrome-trace (Perfetto) export and validation.

The export format is the Trace Event Format's "JSON Object Format":
``{"traceEvents": [...], "displayTimeUnit": "ms"}`` with ``B``/``E``
duration events, ``i`` instants, and ``M`` metadata — loadable directly
in ``chrome://tracing`` or https://ui.perfetto.dev ("Open trace file").

:func:`validate` is the structural checker the tests and ``make
trace-demo`` run against every export: valid JSON, every ``B`` matched
by an ``E`` on the same thread (well-nested, LIFO), timestamps
monotonic per thread. It exists because a trace that silently violates
nesting loads as garbage in Perfetto — the failure mode is "confusing
picture", not an error message, so the checker has to be mechanical.

CLI::

    python -m icikit.obs.check trace.json    # exit 0 iff valid
"""

from __future__ import annotations

import json
import sys


def to_chrome(events: list) -> dict:
    """Wrap raw trace events in the Chrome JSON-object envelope."""
    return {"traceEvents": list(events), "displayTimeUnit": "ms"}


def close_dangling(events: list) -> list:
    """Synthetic ``E`` events for every ``B`` no thread ever closed —
    in LIFO order per thread, stamped ``closed_by: "export"`` — plus
    synthetic ``e`` closes for every dangling ASYNC ``b`` span (spans
    keyed by ``(cat, id)``, the request-scoped trees).

    A worker the scheduler abandoned mid-span (a hung straggler whose
    join timed out — a scenario the farm is *designed* to survive) is
    still inside its region at export time, and so is a request still
    queued or leased when an engine run is cut off at ``max_steps``;
    without these closes the export of a healthy healed run fails the
    structural validator. Sync closes reuse the thread's last seen
    ``ts``; async closes are stamped at the trace's global last ``ts``
    (an async pair may straddle threads, so only the global frontier
    is guaranteed not to violate any thread's monotonicity).
    """
    stacks: dict[tuple, list] = {}
    last_ts: dict[tuple, float] = {}
    astacks: dict[tuple, list] = {}   # (cat, id) -> [(name, pid, tid)]
    max_ts = 0
    for ev in events:
        if not isinstance(ev, dict):
            continue
        key = (ev.get("pid"), ev.get("tid"))
        ts = ev.get("ts")
        if isinstance(ts, (int, float)) and not isinstance(ts, bool):
            last_ts[key] = ts
            if ts > max_ts:
                max_ts = ts
        ph = ev.get("ph")
        if ph == "B":
            stacks.setdefault(key, []).append(ev.get("name"))
        elif ph == "E":
            if stacks.get(key):
                stacks[key].pop()
        elif ph == "b":
            akey = (ev.get("cat"), ev.get("id"))
            astacks.setdefault(akey, []).append(
                (ev.get("name"), ev.get("pid"), ev.get("tid")))
        elif ph == "e":
            akey = (ev.get("cat"), ev.get("id"))
            if astacks.get(akey):
                astacks[akey].pop()
    closes = []
    for key, stack in sorted(stacks.items(), key=repr):
        for name in reversed(stack):
            closes.append({
                "ph": "E", "name": name, "pid": key[0], "tid": key[1],
                "ts": last_ts.get(key, 0),
                "args": {"closed_by": "export"}})
    for akey, stack in sorted(astacks.items(), key=repr):
        for name, pid, tid in reversed(stack):
            closes.append({
                "ph": "e", "name": name, "cat": akey[0], "id": akey[1],
                "pid": pid, "tid": tid, "ts": max_ts,
                "args": {"closed_by": "export"}})
    return closes


def export(path, events: list) -> dict:
    """Write ``events`` to ``path`` as a Chrome-trace JSON file
    (dangling spans closed — see :func:`close_dangling`); returns the
    written object."""
    events = list(events)
    obj = to_chrome(events + close_dangling(events))
    with open(path, "w") as f:
        json.dump(obj, f)
    return obj


def validate(trace) -> list[str]:
    """Structural problems in a Chrome trace; empty list == valid.

    ``trace`` is the loaded object (dict envelope or bare event list),
    a JSON string, or a path. Checks:

    - parses as JSON into the envelope/array format;
    - every event is a dict with a ``ph``;
    - ``B``/``E`` pairs balance per (pid, tid) and match LIFO (an ``E``
      naming a different span than the innermost open ``B`` is a
      nesting violation);
    - ASYNC ``b``/``e`` pairs carry a ``cat`` and an ``id`` and
      balance per (cat, id) LIFO — threads do NOT scope them, which is
      exactly why the request-scoped trees use them: a span may open
      on one engine's track and close on another's (``n`` async
      instants need the same keys but no pairing);
    - ``ts`` is numeric and monotonic (non-decreasing) per (pid, tid)
      across timestamped events;
    - ``X`` complete events carry a non-negative ``dur``.
    """
    problems: list[str] = []
    if isinstance(trace, str):
        if trace.lstrip()[:1] in ("{", "["):
            try:
                trace = json.loads(trace)
            except json.JSONDecodeError as e:
                return [f"not valid JSON: {e}"]
        else:
            try:
                with open(trace) as f:
                    trace = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                return [f"cannot load trace: {e}"]
    if isinstance(trace, dict):
        events = trace.get("traceEvents")
        if not isinstance(events, list):
            return ["envelope has no 'traceEvents' list"]
    elif isinstance(trace, list):
        events = trace
    else:
        return [f"trace must be a dict or list, got {type(trace).__name__}"]

    stacks: dict[tuple, list] = {}    # (pid, tid) -> open B names
    astacks: dict[tuple, list] = {}   # (cat, id) -> open b names
    last_ts: dict[tuple, float] = {}  # (pid, tid) -> last seen ts
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ph, str):
            problems.append(f"event {i}: missing 'ph'")
            continue
        if ph == "M":
            continue  # metadata carries no timestamp
        key = (ev.get("pid"), ev.get("tid"))
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or isinstance(ts, bool):
            problems.append(f"event {i} ({ph}): non-numeric ts {ts!r}")
            continue
        if key in last_ts and ts < last_ts[key]:
            problems.append(
                f"event {i} ({ph} {ev.get('name')!r}): ts {ts} goes "
                f"backwards on tid {key[1]} (prev {last_ts[key]})")
        last_ts[key] = ts
        if ph == "B":
            stacks.setdefault(key, []).append(ev.get("name"))
        elif ph == "E":
            stack = stacks.get(key)
            if not stack:
                problems.append(
                    f"event {i}: E {ev.get('name')!r} on tid {key[1]} "
                    "with no open B")
                continue
            opened = stack.pop()
            name = ev.get("name")
            if name is not None and name != opened:
                problems.append(
                    f"event {i}: E {name!r} closes B {opened!r} on tid "
                    f"{key[1]} (nesting violation)")
        elif ph in ("b", "e", "n"):
            cat, aid = ev.get("cat"), ev.get("id")
            if not isinstance(cat, str) or aid is None:
                problems.append(
                    f"event {i}: async {ph} {ev.get('name')!r} "
                    f"missing cat/id (cat={cat!r}, id={aid!r})")
                continue
            akey = (cat, aid)
            if ph == "b":
                astacks.setdefault(akey, []).append(ev.get("name"))
            elif ph == "e":
                astack = astacks.get(akey)
                if not astack:
                    problems.append(
                        f"event {i}: e {ev.get('name')!r} on async "
                        f"{akey} with no open b")
                    continue
                opened = astack.pop()
                name = ev.get("name")
                if name is not None and name != opened:
                    problems.append(
                        f"event {i}: e {name!r} closes b {opened!r} "
                        f"on async {akey} (nesting violation)")
        elif ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: X with bad dur {dur!r}")
    for (pid, tid), stack in stacks.items():
        if stack:
            problems.append(
                f"tid {tid}: {len(stack)} unclosed B event(s): "
                + ", ".join(repr(n) for n in stack))
    for akey, astack in astacks.items():
        if astack:
            problems.append(
                f"async {akey}: {len(astack)} unclosed b event(s): "
                + ", ".join(repr(n) for n in astack))
    return problems


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m icikit.obs.check TRACE_JSON",
              file=sys.stderr)
        return 2
    problems = validate(argv[0])
    if problems:
        for p in problems:
            print(f"INVALID: {p}", file=sys.stderr)
        return 1
    with open(argv[0]) as f:
        n = len(json.load(f).get("traceEvents", []))
    print(f"OK: {argv[0]} is a valid Chrome trace ({n} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
