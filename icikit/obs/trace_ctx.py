"""Request-scoped trace context: one causal span tree per request.

The serving stack's thread spans (``serve.engine.step``,
``serve.prefill.chunk``) answer "what was this engine doing at t" —
they cannot answer "what happened to request r42": a request's life
crosses threads (submitted by a client thread, served by an engine
loop, possibly reissued to a *different* engine after a lease reap),
and one engine step belongs to every co-batched request at once, so a
thread-scoped tree has no row for "the request". This module is the
Dapper-style answer built on Chrome ASYNC events:

- a **trace id** is minted at ``RequestQueue.submit`` and rides the
  :class:`Request` for its whole life (``req.trace``);
- every lifecycle edge lands as an async span or instant keyed by
  ``(cat="serve.req", id=trace_id)`` — async pairs match by id, NOT by
  thread, so a span opened under one engine's track legally closes
  under another's (the reissue/handoff case the structural validator
  covers via its b/e discipline);
- the tree shape is ``serve.req`` (root, submit → terminal) holding
  alternating ``serve.req.queued`` (arrival/requeue → claim) and
  ``serve.req.attempt`` (claim → complete/fail/preempt/reap) segments;
  inside an attempt: ``serve.req.prefill.chunk`` spans, and instants
  for admission, first token, per-step batch participation (with the
  accepted-token and tree primary/sideways stats), CoW forks, dedup
  attaches, quarantine, retry, preemption, reissue;
- a **lease reap closes what the dead engine left open**
  (:meth:`TraceCtx.abandon` stamps ``closed_by: lease_reaped``) and
  records the abandoned claim generation, so the NEXT attempt opens
  with an explicit ``reissued_from`` arg — one request, one tree, a
  visible edge where the engines handed off, no orphan spans.

Discipline (shared with every obs probe): with no tracer armed every
method is one module-global read plus a ``None`` check — no
allocation, no clock read — and trace emission never influences
tokens (the tracing-on ≡ tracing-off bitwise pin in
``tests/test_trace_ctx.py``). Mutation is fenced by claim generation
exactly like the queue's lease stamps: a stalled engine whose request
was reaped and reissued carries a stale ``seq`` and its late span
calls become no-ops instead of corrupting the live claimant's tree.
"""

from __future__ import annotations

import itertools
import threading

from icikit.obs import tracer as _tracer

CAT = "serve.req"

_IDS = itertools.count()


def mint(rid: str) -> "TraceCtx":
    """A fresh context for one request (called by ``submit``; the id is
    process-unique — rids restart per queue, trace ids never)."""
    return TraceCtx(rid)


def adopt(rid: str, trace_id: str, seq: int) -> "TraceCtx":
    """A context bound to an EXISTING request tree — the fleet case:
    the trace id rode the claim RPC from the coordinator, whose queue
    owns the root/queued/attempt spans. Engine-side spans and instants
    land under the same ``(cat, id)`` async track (in another process
    they land in that process's buffer; merged or co-resident, the
    validator pairs them by id, not by thread/process), so one request
    reads as ONE continuous tree across the engine handoff — the
    ``reissued_from`` edge the coordinator emits at a lease reap spans
    processes because this id does. ``seq`` is the claim generation
    the engine holds: its fenced calls stay live exactly while the
    coordinator's lease does."""
    ctx = TraceCtx(rid)
    ctx.trace_id = trace_id
    ctx._seq = seq
    ctx._adopted = True
    return ctx


class TraceCtx:
    """Per-request async-span tree state, carried on the Request.

    ``seq``-stamped methods follow the queue's claim-generation fence:
    ``begin_attempt(seq)`` records the live generation; a later call
    stamped with any other generation is a no-op (``seq=None`` trusts
    the caller — the queue's own lifecycle edges, which are already
    behind its ``_lease_live`` check).
    """

    __slots__ = ("trace_id", "rid", "_open", "_seq", "_reissued_from",
                 "_lock", "_adopted")

    def __init__(self, rid: str):
        self.trace_id = f"req-{next(_IDS)}"
        self.rid = rid
        # True for fleet engine-side contexts (see adopt()): paired
        # spans then emit as THREAD spans instead of async pairs
        self._adopted = False
        self._open: list = []       # open async span names, LIFO
        self._seq = None            # live claim generation
        self._reissued_from = None  # claim seq abandoned by a reap
        # fences the check-then-act window: without it a stale engine
        # that passed _live() could stall (GIL release inside an XLA
        # compile), lose its lease, and land its event in the
        # REISSUED attempt's tree after abandon() already ran — the
        # disabled path never touches the lock
        self._lock = threading.Lock()

    # -- fenced primitives -------------------------------------------

    def _live(self, seq) -> bool:
        return seq is None or seq == self._seq

    def open(self, name: str, seq=None, **attrs) -> None:
        tb = _tracer._TRACE
        if tb is None:
            return
        with self._lock:
            if not self._live(seq):
                return
            attrs["rid"] = self.rid
            tb.async_event("b", name, CAT, self.trace_id, attrs)
            self._open.append(name)

    def close(self, name: str, seq=None, **attrs) -> None:
        """Close ``name``, closing through any spans still nested in it
        (their ``e`` events are stamped ``closed_by: name`` — LIFO, so
        the structural validator stays satisfied even when a terminal
        edge arrives while an inner span is open)."""
        tb = _tracer._TRACE
        if tb is None:
            return
        with self._lock:
            if not self._live(seq) or name not in self._open:
                return
            while self._open:
                top = self._open.pop()
                if top == name:
                    if attrs:
                        attrs["rid"] = self.rid
                    tb.async_event("e", top, CAT, self.trace_id,
                                   attrs or None)
                    return
                tb.async_event("e", top, CAT, self.trace_id,
                               {"closed_by": name})

    def instant(self, name: str, seq=None, **attrs) -> None:
        tb = _tracer._TRACE
        if tb is None:
            return
        with self._lock:
            if not self._live(seq):
                return
            attrs["rid"] = self.rid
            tb.async_event("n", name, CAT, self.trace_id, attrs)

    def span(self, name: str, seq=None, **attrs):
        """Context-manager form for strictly scoped regions (prefill
        chunks); the shared no-op singleton when tracing is off or the
        caller's claim is stale. Adopted (fleet engine-side) contexts
        emit these as ordinary THREAD spans carrying the trace id as
        an attr instead of async pairs: the coordinator's reaper owns
        the async stack and cannot know what a dead remote engine
        left open — as thread spans, a killed engine's danglers are
        exactly the abandoned-straggler case ``chrome.close_dangling``
        already heals at export, while the request's async tree stays
        structurally valid."""
        if _tracer._TRACE is None or not self._live(seq):
            return _tracer.NOOP_SPAN
        if self._adopted:
            return _tracer.span(name, rid=self.rid,
                                req=self.trace_id, **attrs)
        return _CtxSpan(self, name, seq, attrs)

    # -- lifecycle edges (called by scheduler + engine) --------------

    def begin_attempt(self, seq: int, **attrs) -> None:
        """Open an attempt segment under claim generation ``seq``; when
        the previous segment ended in a lease reap, the new segment
        carries the explicit ``reissued_from`` edge."""
        with self._lock:
            self._seq = seq
            if self._reissued_from is not None:
                reissued = self._reissued_from
                self._reissued_from = None
            else:
                reissued = None
        if _tracer._TRACE is None:
            return
        # "claim_seq", not "seq": the bare name is the fence parameter
        # on every ctx method and must stay out of **attrs
        attrs["claim_seq"] = seq
        if reissued is not None:
            attrs["reissued_from"] = reissued
        self.open("serve.req.attempt", **attrs)

    def end_attempt(self, seq=None, **attrs) -> None:
        self.close("serve.req.attempt", seq=seq, **attrs)

    def abandon(self, reason: str, seq: int | None = None) -> None:
        """Close every open span ABOVE the ``serve.req`` root (LIFO,
        stamped ``closed_by: reason``) — the reaper's move when a
        lease expires: the dead engine can no longer close what it
        opened, and the next attempt must start from a clean segment
        stack, but the request itself is still alive (that is the
        point of reissue), so the root span survives the reap.
        Records the abandoned claim generation for the
        ``reissued_from`` edge, and invalidates the generation so the
        dead engine's late span calls fence out."""
        with self._lock:
            if seq is not None:
                self._reissued_from = seq
            self._seq = None
            tb = _tracer._TRACE
            if tb is None:
                del self._open[1 if self._open[:1] == ["serve.req"]
                               else 0:]
                return
            while self._open and self._open[-1] != "serve.req":
                top = self._open.pop()
                tb.async_event("e", top, CAT, self.trace_id,
                               {"closed_by": reason})


class _CtxSpan:
    __slots__ = ("_ctx", "_name", "_seq", "_attrs")

    def __init__(self, ctx: TraceCtx, name: str, seq, attrs: dict):
        self._ctx = ctx
        self._name = name
        self._seq = seq
        self._attrs = attrs

    def __enter__(self):
        self._ctx.open(self._name, seq=self._seq, **self._attrs)
        return self

    def __exit__(self, *exc):
        self._ctx.close(self._name, seq=self._seq)
        return False


def request_trees(events: list) -> dict:
    """Group a trace's ``serve.req`` async events by trace id —
    ``{trace_id: [events...]}`` in stream order. The assertion helper
    the continuity tests (and ``tools/obs_smoke_check.py``) use to ask
    "how many request trees, and is each one whole?"."""
    trees: dict = {}
    for ev in events:
        if isinstance(ev, dict) and ev.get("cat") == CAT \
                and ev.get("ph") in ("b", "e", "n"):
            trees.setdefault(ev.get("id"), []).append(ev)
    return trees
