"""Coordinator-side fleet telemetry collector.

The other half of the fleet observability plane
(:mod:`icikit.fleet.telemetry` is the engine/standby half): the
coordinator owns a :class:`FleetCollector` and routes every
``telemetry.*`` RPC into it. The collector

- **ingests batches** — re-verifies each batch's content digest (the
  telemetry layer's own rot detector: a frame the
  ``fleet.telemetry.send`` probe flipped passes the transport
  checksum by design and is caught HERE), tracks per-source sequence
  gaps, and keeps honest per-source loss counters
  (``dropped``/``corrupt_frames``/``lost_batches``) that the health
  verdict reports — telemetry loss is never silently absorbed;
- **merges traces** — every source's Chrome events are shifted by its
  handshake clock offset into the collector's monotonic domain (a
  constant per-process shift preserves per-(pid, tid) monotonicity),
  pid-collision-remapped onto distinct process tracks with
  ``process_name`` metadata, and stably sorted into ONE checker-valid
  event list in which the r15 async request trees span processes:
  the coordinator's ``serve.req`` root/attempt pairs plus each
  engine's adopted instants and thread spans — prefill engine →
  handoff → decode engine, one tree (``cross_process_trees`` counts
  them). A killed engine's dangling thread spans are exactly the
  abandoned-straggler case ``chrome.close_dangling`` heals at export;
- **maintains the fleet metrics registry** — per-engine labeled
  gauges (``fleet.engine.<id>.<name>`` mirrors of each source's
  gauges plus heartbeat occupancy), control-plane op latencies
  (``fleet.claim_ms``/``fleet.renew_ms``), and the
  ``fleet.tokens_per_s`` rollup windowed from heartbeat token counts;
- **runs the watch detectors on the aggregated stream** — a
  :class:`~icikit.obs.watch.MultiWatch` with per-engine windows
  (one engine's burst cannot mask another's SLO burn) and the
  :class:`~icikit.obs.watch.StragglerOutlier` cross-source detector
  (TPOT k× fleet median → ``obs.alert`` with the engine as
  ``source`` — the coordinator feeds these into its defect ledger);
- **tracks roster residency** — per-engine resident-chain bloom
  summaries from the heartbeat (``update_resident``), queryable via
  the coordinator's ``resident_chains`` op: the substrate ROADMAP
  1a's cache-aware ``claim(accept=)`` routing consumes.

Control-plane rule compliant (enforced by ``fleet-control-plane``):
no jax import, no device dispatch — the collector runs inside the
coordinator process, whose claim path must keep flowing while engine
device schedules are under suspicion.
"""

from __future__ import annotations

import json
import threading
import time

from icikit import chaos, obs
from icikit.fleet.telemetry import payload_digest
from icikit.fleet.transport import _maybe_corrupt_bytes
from icikit.obs import trace_ctx
from icikit.obs import watch as _watch
from icikit.obs.metrics import Registry


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class _Source:
    """Per-source collector state (one engine/standby/process)."""

    __slots__ = ("name", "pid", "role", "offset_us", "last_seq",
                 "dropped", "batches", "corrupt", "lost", "events",
                 "trace", "metrics", "report", "resident")

    def __init__(self, name: str):
        self.name = name
        self.pid = None
        self.role = "engine"
        self.offset_us = 0
        self.last_seq = 0
        self.dropped = 0        # sender-reported (queue/send losses)
        self.batches = 0
        self.corrupt = 0        # digest-failed batches dropped here
        self.lost = 0           # sequence gaps (batches never seen)
        self.events = 0
        self.trace: list = []
        self.metrics: dict | None = None
        self.report: dict | None = None
        self.resident: dict | None = None


class FleetCollector:
    """Aggregates the fleet's telemetry inside the coordinator."""

    def __init__(self, registry=None, watch=None,
                 ttft_slo_ms: float = 30_000.0,
                 tpot_slo_ms: float = 5_000.0,
                 burn_budget: float = 0.5,
                 min_count: int = 4,
                 straggler_factor: float = 3.0,
                 poll_interval_s: float = 0.5,
                 rate_window_s: float = 0.5,
                 on_alert=None):
        self.registry = registry if registry is not None else Registry()
        if watch is None:
            def make():
                return [
                    _watch.SloBurnRate("serve.ttft_ms", ttft_slo_ms,
                                       burn_budget,
                                       min_count=min_count),
                    _watch.SloBurnRate("serve.tpot_ms", tpot_slo_ms,
                                       burn_budget,
                                       min_count=min_count),
                ]
            watch = _watch.MultiWatch(
                make,
                cross=(_watch.StragglerOutlier(
                    factor=straggler_factor, min_count=min_count),),
                min_interval_s=poll_interval_s)
        self.watch = watch
        self.on_alert = on_alert
        self._lock = threading.Lock()
        self._sources: dict = {}
        self._rate_window_s = rate_window_s
        self._tokens_at = (0, time.monotonic())

    # -- RPC surface (routed by the coordinator's _handle) ------------

    def handle(self, op: str, msg: dict, blobs) -> tuple:
        if op == "telemetry.hello":
            return self._hello(msg)
        if op == "telemetry.batch":
            return self._batch(msg, blobs)
        raise ValueError(f"unknown telemetry op {op!r}")

    def _source(self, name: str) -> _Source:
        with self._lock:
            s = self._sources.get(name)
            if s is None:
                s = self._sources[name] = _Source(name)
            return s

    def _hello(self, msg: dict) -> tuple:
        s = self._source(str(msg.get("source") or "unknown"))
        with self._lock:
            if msg.get("pid") is not None:
                s.pid = int(msg["pid"])
            s.role = str(msg.get("role") or s.role)
        obs.count("fleet.telemetry.handshakes")
        # the handshake echo: the caller brackets this read with its
        # own clock marks and derives its offset into OUR domain
        return {"clock_us": _now_us()}, ()

    def _batch(self, msg: dict, blobs) -> tuple:
        chaos.maybe_delay("fleet.telemetry.recv")
        chaos.maybe_die("fleet.telemetry.recv")
        s = self._source(str(msg.get("source") or "unknown"))
        payload = bytes(blobs[0]) if blobs else b""
        # recv-side rot probe BEFORE the digest re-verify — the drill
        # must be caught by this layer, batch dropped and counted
        payload = _maybe_corrupt_bytes("fleet.telemetry.recv", payload)
        obs.count("fleet.telemetry.batches")
        seq = int(msg.get("seq") or 0)
        with self._lock:
            if s.last_seq and seq > s.last_seq + 1:
                gap = seq - s.last_seq - 1
                s.lost += gap
            else:
                gap = 0
            s.last_seq = max(s.last_seq, seq)
            if msg.get("offset_us") is not None:
                s.offset_us = int(msg["offset_us"])
            s.dropped = max(s.dropped, int(msg.get("dropped") or 0))
            s.batches += 1
        if gap:
            obs.count("fleet.telemetry.lost_batches", gap)
        if payload_digest(payload) != msg.get("digest"):
            with self._lock:
                s.corrupt += 1
            obs.count("fleet.telemetry.corrupt_frames")
            # rotten content is dropped, never parsed — the honest
            # counter above is the whole story
            return {"accepted": False}, ()
        batch = json.loads(payload.decode())
        events = batch.get("events") or []
        trace = batch.get("trace") or []
        snap = batch.get("metrics")
        with self._lock:
            s.events += len(events)
            s.trace.extend(trace)
            if snap is not None:
                s.metrics = snap
        self._rollup(s.name, snap)
        return {"accepted": True}, ()

    # -- roster feeds (called by the coordinator directly) ------------

    def update_report(self, source: str, stats: dict | None) -> None:
        """Heartbeat stats from the coordinator's ``report`` op."""
        s = self._source(source)
        with self._lock:
            s.report = dict(stats or {})
        occ = (stats or {}).get("occupancy")
        if occ is not None:
            self.registry.gauge(
                f"fleet.engine.{source}.occupancy").set(occ)

    def update_resident(self, source: str, summary) -> None:
        """Per-engine resident-chain bloom summary (heartbeat)."""
        s = self._source(source)
        with self._lock:
            s.resident = dict(summary) if summary else None

    def resident_summaries(self) -> dict:
        with self._lock:
            return {name: dict(s.resident)
                    for name, s in self._sources.items()
                    if s.resident}

    def observe_slo(self, source: str, slo: dict | None) -> None:
        """Feed one request's terminal SLO marks into the per-engine
        watch stream (the coordinator calls this at commit)."""
        source = source or "unknown"
        for metric, key in (("serve.ttft_ms", "ttft_ms"),
                            ("serve.tpot_ms", "tpot_ms"),
                            ("serve.queue_wait_ms", "queue_wait_ms")):
            v = (slo or {}).get(key)
            if v is not None:
                self.watch.observe(source, metric, v)

    def observe_latency(self, name: str, ms: float) -> None:
        """Control-plane op latency (``fleet.claim_ms``,
        ``fleet.renew_ms``) into the fleet registry."""
        self.registry.histogram(name).observe(ms)

    def _rollup(self, source: str, snap: dict | None) -> None:
        if not snap:
            return
        for name, v in (snap.get("gauges") or {}).items():
            self.registry.gauge(f"fleet.engine.{source}.{name}").set(v)

    # -- polling (driven from the coordinator's reap loop) ------------

    def maybe_poll(self) -> list:
        now = time.monotonic()
        with self._lock:
            total = sum(int((s.report or {}).get("tokens") or 0)
                        for s in self._sources.values())
        prev_total, prev_t = self._tokens_at
        if now - prev_t >= self._rate_window_s:
            rate = (total - prev_total) / max(now - prev_t, 1e-9)
            self._tokens_at = (total, now)
            self.registry.gauge("fleet.tokens_per_s").set(rate)
            obs.gauge("fleet.tokens_per_s", rate)
        alerts = self.watch.maybe_poll()
        if alerts and self.on_alert is not None:
            for a in alerts:
                try:
                    self.on_alert(a)
                except Exception:  # noqa: BLE001 - a listener bug must
                    pass           # not stall the reap loop
        return alerts

    # -- trace merge ---------------------------------------------------

    def merge_traces(self, local_events=()) -> list:
        """ONE checker-valid event list across every process.

        Per-source events are clock-shifted by the handshake offset
        (constant per process → per-(pid, tid) monotonicity survives),
        colliding pids are remapped onto fresh tracks (two in-process
        test "engines" share an OS pid; real worker processes never
        collide), ``process_name`` metadata labels each track, and the
        final list is STABLY sorted by ts — stable keeps each track's
        internal (already monotonic) order, so B/E and async b/e
        discipline survive the interleave.
        """
        merged = [dict(ev) for ev in local_events]
        used = {ev.get("pid") for ev in merged
                if ev.get("pid") is not None}
        next_pid = (max(used) + 1) if used else 1
        with self._lock:
            sources = [(name, s.role, int(s.offset_us or 0),
                        [dict(ev) for ev in s.trace])
                       for name, s in sorted(self._sources.items())]
        for name, role, off, trace in sources:
            if not trace:
                continue
            src_pids = sorted({ev.get("pid") for ev in trace
                               if ev.get("pid") is not None})
            remap = {}
            for p in src_pids:
                q = p
                while q in used:
                    q = next_pid
                    next_pid += 1
                used.add(q)
                remap[p] = q
                merged.append({"ph": "M", "name": "process_name",
                               "pid": q,
                               "args": {"name": f"{role}:{name}"}})
            for ev in trace:
                p = ev.get("pid")
                if p in remap:
                    ev["pid"] = remap[p]
                ts = ev.get("ts")
                if isinstance(ts, (int, float)) \
                        and not isinstance(ts, bool):
                    ev["ts"] = ts + off
                merged.append(ev)
        merged.sort(key=_sort_ts)
        return merged

    @staticmethod
    def cross_process_trees(events, exclude_pid=None) -> int:
        """How many ``serve.req`` trees span ≥2 distinct processes
        besides ``exclude_pid`` (pass the coordinator's own pid to
        count prefill→handoff→decode trees specifically)."""
        n = 0
        for evs in trace_ctx.request_trees(events).values():
            pids = {e.get("pid") for e in evs
                    if e.get("pid") is not None}
            pids.discard(exclude_pid)
            if len(pids) >= 2:
                n += 1
        return n

    # -- reporting -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            src = {name: {"pid": s.pid, "role": s.role,
                          "batches": s.batches, "events": s.events,
                          "trace_events": len(s.trace),
                          "dropped": s.dropped,
                          "corrupt_frames": s.corrupt,
                          "lost_batches": s.lost,
                          "offset_us": s.offset_us,
                          "resident_n": (s.resident or {}).get("n")}
                   for name, s in sorted(self._sources.items())}
        return {
            "sources": src,
            "batches": sum(v["batches"] for v in src.values()),
            "dropped": sum(v["dropped"] for v in src.values()),
            "corrupt_frames": sum(v["corrupt_frames"]
                                  for v in src.values()),
            "lost_batches": sum(v["lost_batches"]
                                for v in src.values()),
        }

    def verdict(self) -> dict:
        """Health verdict over the aggregated stream: watch alerts
        PLUS telemetry loss — a channel that dropped or rotted frames
        is reported here even when every detector stayed quiet."""
        st = self.stats()
        wv = self.watch.verdict()
        losses = []
        for name, s in sorted(st["sources"].items()):
            for kind in ("dropped", "corrupt_frames", "lost_batches"):
                if s[kind]:
                    losses.append({"source": name, "kind": kind,
                                   "n": s[kind]})
        return {
            "healthy": wv["healthy"] and not losses,
            "n_alerts": wv["n_alerts"],
            "polls": wv["polls"],
            "sources": wv["sources"],
            "alerts": wv["alerts"],
            "telemetry_loss": losses,
            "batches": st["batches"],
        }


def _sort_ts(ev: dict):
    # M metadata carries no ts; pin it ahead of the timeline
    ts = ev.get("ts")
    if ev.get("ph") == "M" or not isinstance(ts, (int, float)) \
            or isinstance(ts, bool):
        return float("-inf")
    return ts
