"""icikit.obs — unified tracing & metrics (spans, event bus, Chrome
trace, metrics registry).

The reference's whole observability story was ``MPI_Barrier`` +
reset-on-read ``get_timer()`` + rank-0 printf; a production stack needs
to explain *where time and bytes went* and make recovery drills
auditable. This package is that layer, dependency-free and
disabled-by-default:

- **event bus** (:mod:`icikit.obs.bus`) — ``emit("anomaly", step=3)``
  fans out to pluggable sinks (stderr/stdout JSONL, in-memory ring,
  file). Replaces every bare ``print(json.dumps(...))``.
- **spans** (:mod:`icikit.obs.tracer`) — ``with span("solve.chunk",
  chunk=i):`` nested, thread-aware regions exported as a
  Perfetto-loadable ``trace.json`` (:mod:`icikit.obs.chrome`), and
  optionally mirrored onto the device timeline via
  ``jax.profiler.TraceAnnotation``.
- **metrics** (:mod:`icikit.obs.metrics`) — counters / gauges /
  histograms (``collective.bytes``, ``scheduler.reissues``,
  ``train.step_ms`` p50/p99), snapshotted into bench reports.
- **request traces** (:mod:`icikit.obs.trace_ctx`) — one async-span
  tree per serving request (trace id minted at submit, carried across
  lease reissue with an explicit ``reissued_from`` edge), exported in
  the same Chrome trace on ``(cat, id)`` tracks.
- **anomaly watch** (:mod:`icikit.obs.watch`) — windowed detectors
  over the metrics stream (SLO burn rate, acceptance drop, KV
  watermarks, zero-rate alarms) emitting ``obs.alert`` events and a
  per-run health verdict. See docs/OBSERVABILITY.md.

Zero-overhead contract: with nothing armed, every probe
(``emit``/``span``/``count``/``observe``) is one module-global read
plus a ``None``/truthiness check — no allocation, no formatting
(``span()`` returns a shared singleton). ``bench_overhead()`` measures
it; docs/DESIGN.md quotes the numbers.

Arming::

    ICIKIT_OBS=1 python -m icikit.models.transformer.train ...
        # -> JSONL events on stderr; trace.json + obs_metrics.json
        #    written at exit

    ICIKIT_OBS="trace=/tmp/t.json;metrics=/tmp/m.json;jsonl=off"
        # ;-separated spec: trace=PATH|off, metrics=PATH|off,
        #    jsonl=stderr|stdout|PATH|off, mirror=1 (device-timeline
        #    mirroring via jax.profiler.TraceAnnotation)

or programmatically: ``obs.start_tracing()``, ``obs.enable_metrics()``,
``obs.add_sink(obs.RingSink())`` — see ``session()`` for the one-call
scoped form tests use.
"""

from __future__ import annotations

import atexit
import os
import time

from icikit.obs import chrome
from icikit.obs import metrics as _metrics_mod
from icikit.obs import trace_ctx  # noqa: F401
from icikit.obs import tracer as _tracer_mod
from icikit.obs import watch  # noqa: F401
from icikit.obs.bus import (  # noqa: F401
    FileSink,
    JsonlSink,
    RingSink,
    Sink,
    add_sink,
    dumps_strict,
    emit,
    enabled,
    installed,
    json_safe,
    remove_sink,
)
from icikit.obs.chrome import export as export_trace  # noqa: F401
from icikit.obs.chrome import validate as validate_trace  # noqa: F401
from icikit.obs.metrics import (  # noqa: F401
    Registry,
    count,
    disable_metrics,
    enable_metrics,
    gauge,
    metrics,
    observe,
)
from icikit.obs.metrics import snapshot as metrics_snapshot  # noqa: F401
from icikit.obs.tracer import (  # noqa: F401
    NOOP_SPAN,
    TraceBuffer,
    instant,
    span,
    start_tracing,
    stop_tracing,
    traced,
    tracing,
)


def emit_records(records) -> None:
    """Route a CLI's result records through the bus under a scoped
    stdout sink: one strict-JSON line per record on stdout (the
    historical ``print(json.dumps(rec))`` bytes, for finite payloads),
    with the same records delivered to whatever sinks ``ICIKIT_OBS``
    armed. The one record-output path every bench CLI shares."""
    with installed(JsonlSink("stdout")):
        for rec in records:
            emit(None, **rec)


class session:
    """Scoped all-in-one arming (the test/demo form)::

        with obs.session(ring := obs.RingSink()) as s:
            ...
        s.trace.snapshot(); ring.events; s.registry.snapshot()

    Installs the given sinks, arms tracing and metrics, and restores
    the previous state (including a previously armed env session) on
    exit. ``s.trace`` is the :class:`TraceBuffer`, ``s.registry`` the
    metrics :class:`Registry`.
    """

    def __init__(self, *sinks, trace: bool = True, metrics: bool = True,
                 mirror_device: bool = False):
        self._sinks = sinks
        self._want_trace = trace
        self._want_metrics = metrics
        self._mirror = mirror_device
        self.trace = None
        self.registry = None

    def __enter__(self):
        for s in self._sinks:
            add_sink(s)
        self._prev_trace = _tracer_mod._swap(
            TraceBuffer(mirror_device=self._mirror)
            if self._want_trace else None)
        self.trace = tracing()
        self._prev_metrics = _metrics_mod._swap(
            Registry() if self._want_metrics else None)
        self.registry = metrics()
        return self

    def __exit__(self, *exc):
        for s in self._sinks:
            remove_sink(s)
        _tracer_mod._swap(self._prev_trace)
        _metrics_mod._swap(self._prev_metrics)
        return False


def bench_overhead(n: int = 200_000) -> dict:
    """Measure the disabled fast path against an empty loop: ns/call
    for ``span()`` entry+exit and ``emit()`` with no sink. The numbers
    back the zero-overhead claim (docs/DESIGN.md quotes a run)."""
    from icikit.obs import tracer as _t
    if _t._TRACE is not None or enabled():
        raise RuntimeError("bench_overhead needs obs fully disabled")
    r = range(n)
    t0 = time.perf_counter()
    for _ in r:
        pass
    empty_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in r:
        with span("x"):
            pass
    span_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in r:
        emit("x", a=1)
    emit_s = time.perf_counter() - t0
    return {
        "n": n,
        "empty_loop_ns": empty_s / n * 1e9,
        "span_disabled_ns": span_s / n * 1e9,
        "emit_no_sink_ns": emit_s / n * 1e9,
    }


# -- env arming (ICIKIT_OBS) ----------------------------------------

def parse_spec(spec: str) -> dict:
    """Parse an ``ICIKIT_OBS`` spec into option dict (see module
    docstring). ``"1"``/``"true"``/``"on"`` selects every default."""
    opts = {"jsonl": "stderr", "trace": "trace.json",
            "metrics": "obs_metrics.json", "mirror": False}
    if spec.strip().lower() in ("1", "true", "on", "yes"):
        return opts
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        key, sep, value = entry.partition("=")
        key, value = key.strip(), value.strip()
        if not sep or key not in opts:
            raise ValueError(f"bad ICIKIT_OBS entry {entry!r} (known: "
                             f"{', '.join(sorted(opts))})")
        if key == "mirror":
            opts["mirror"] = value.lower() in ("1", "true", "on", "yes")
        else:
            opts[key] = value
    return opts


def _arm_from_env(spec: str) -> None:
    opts = parse_spec(spec)
    flush_paths = {}
    if opts["jsonl"] != "off":
        if opts["jsonl"] in ("stderr", "stdout"):
            add_sink(JsonlSink(opts["jsonl"]))
        else:
            add_sink(FileSink(opts["jsonl"]))
    if opts["trace"] != "off":
        start_tracing(mirror_device=opts["mirror"])
        flush_paths["trace"] = opts["trace"]
    if opts["metrics"] != "off":
        enable_metrics()
        flush_paths["metrics"] = opts["metrics"]
    if flush_paths:
        atexit.register(_flush_env_session, flush_paths)


def _flush_env_session(paths: dict) -> None:
    """atexit hook for env-armed sessions: write the trace and the
    metrics snapshot where the spec asked."""
    import json as _json
    tb = stop_tracing()
    if "trace" in paths and tb is not None:
        chrome.export(paths["trace"], tb.snapshot())
    reg = disable_metrics()
    if "metrics" in paths and reg is not None:
        with open(paths["metrics"], "w") as f:
            _json.dump(json_safe(reg.snapshot()), f, indent=1)


_env_spec = os.environ.get("ICIKIT_OBS")
if _env_spec and _env_spec.strip().lower() not in ("", "0", "off",
                                                   "false"):
    _arm_from_env(_env_spec)
