"""Metrics registry: counters, gauges, histograms.

Where spans answer "where did the time go *this run*", metrics answer
"how much, in total": bytes moved per collective, chunks reissued by
the scheduler, per-step latency percentiles. The registry is
dependency-free and snapshot-oriented — :func:`snapshot` returns one
JSON-safe dict that bench reports stamp into their record files.

Fast path (the chaos/bus discipline): module-level helpers
(:func:`count`, :func:`gauge`, :func:`observe`) are one global read +
``None`` check when no registry is enabled — hot loops instrument
unconditionally and pay nothing in production.
"""

from __future__ import annotations

import threading

_METRICS = None             # Registry | None; lock-free hot-path read
_LOCK = threading.Lock()


class Counter:
    """Monotonic accumulator (``scheduler.reissues``,
    ``collective.bytes``)."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def add(self, n=1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-written value (``scheduler.workers_alive``)."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Streaming distribution with exact count/sum/min/max and
    percentile estimates from a bounded, deterministically-decimated
    sample.

    When the sample buffer fills, every other retained sample is
    dropped and the keep-stride doubles — no RNG (reservoir sampling
    would make snapshots run-order dependent), bounded memory, and for
    the benchmark-scale streams this serves (10^2..10^5 observations)
    the stride-decimated sample still covers the whole stream evenly.
    """

    __slots__ = ("count", "total", "min", "max", "_sample", "_stride",
                 "_seen", "_lock", "_cap", "_over")

    def __init__(self, cap: int = 4096):
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._sample: list = []
        self._stride = 1
        self._seen = 0
        self._cap = cap
        self._over: dict = {}   # threshold -> observations above it
        self._lock = threading.Lock()

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            for t in self._over:
                if v > t:
                    self._over[t] += 1
            self._seen += 1
            if self._seen >= self._stride:
                self._seen = 0
                self._sample.append(v)
                if len(self._sample) >= self._cap:
                    self._sample = self._sample[::2]
                    self._stride *= 2

    def track_over(self, threshold: float) -> None:
        """Arm an exact above-``threshold`` observation count (the SLO
        burn-rate numerator — a windowed violation *fraction* cannot be
        recovered from decimated percentiles, so the watch layer
        registers its thresholds up front and the histogram counts
        crossings at observe time: one compare per armed threshold).
        Idempotent; counts observations from arming onward."""
        with self._lock:
            self._over.setdefault(float(threshold), 0)

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile over the retained sample (q in
        [0, 100])."""
        with self._lock:
            s = sorted(self._sample)
        return self._pct(s, q)

    @staticmethod
    def _pct(s: list, q: float):
        if not s:
            return None
        idx = min(len(s) - 1, max(0, round(q / 100.0 * (len(s) - 1))))
        return s[idx]

    def summary(self) -> dict:
        # one lock-scoped copy of EVERY field: the watch layer
        # snapshots mid-run against concurrent engine-thread observes,
        # and a count read in one instant with a sum read in the next
        # is a torn record (mean drifts, burn rates go negative)
        with self._lock:
            count, total = self.count, self.total
            mn, mx = self.min, self.max
            sample = sorted(self._sample)
            over = dict(self._over)
        out = {
            "count": count, "sum": total,
            "min": mn, "max": mx,
            "mean": (total / count) if count else None,
            "p50": self._pct(sample, 50), "p99": self._pct(sample, 99),
        }
        if over:
            out["over"] = {str(t): n for t, n in sorted(over.items())}
        return out


class Registry:
    """Named metric store; names are dotted strings
    (``train.step_ms``). First access creates the metric, so a clean
    run still snapshots its zero counters — "0 reissues" is a
    statement, "no such key" is a blind spot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def _get(self, table: dict, name: str, cls):
        with self._lock:
            m = table.get(name)
            if m is None:
                m = table[name] = cls()
            return m

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(self._histograms, name, Histogram)

    def snapshot(self) -> dict:
        """JSON-safe view of every metric, for record files. Safe
        against concurrent emits: the table copy is lock-scoped here
        and every histogram summary is lock-scoped in
        :meth:`Histogram.summary` (counter/gauge values are single
        atomic reads)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {k: g.value for k, g in sorted(gauges.items())},
            "histograms": {k: h.summary()
                           for k, h in sorted(histograms.items())},
        }

    def clear_gauges(self, prefix: str = "") -> None:
        """Drop every gauge whose name starts with ``prefix``. Gauges
        are last-written values: a bench arm that never writes (say)
        ``serve.occupancy_rows`` would otherwise snapshot the PREVIOUS
        arm's parting value into its own record — arms call this at
        their timed-window start so a stale gauge reads as absent, not
        as a plausible number."""
        with self._lock:
            for k in [k for k in self._gauges if k.startswith(prefix)]:
                del self._gauges[k]


# -- module-level fast-path helpers ---------------------------------

def metrics() -> Registry | None:
    """The enabled registry, or None when metrics are disabled."""
    return _METRICS


def enable_metrics() -> Registry:
    """Arm a fresh process-wide registry and return it."""
    global _METRICS
    with _LOCK:
        _METRICS = Registry()
        return _METRICS


def disable_metrics() -> Registry | None:
    """Disarm; returns the registry that was live (for a final
    snapshot)."""
    global _METRICS
    with _LOCK:
        reg, _METRICS = _METRICS, None
        return reg


def _swap(reg: Registry | None) -> Registry | None:
    """Install ``reg`` (may be None), returning the previous registry —
    the restore primitive scoped sessions need."""
    global _METRICS
    with _LOCK:
        prev, _METRICS = _METRICS, reg
        return prev


def count(name: str, n=1) -> None:
    """Bump a counter (creates it at 0 first — so passing ``n=0``
    *registers* the metric without moving it)."""
    reg = _METRICS
    if reg is None:
        return
    c = reg.counter(name)
    if n:
        c.add(n)


def gauge(name: str, v: float) -> None:
    reg = _METRICS
    if reg is None:
        return
    reg.gauge(name).set(v)


def observe(name: str, v: float) -> None:
    reg = _METRICS
    if reg is None:
        return
    reg.histogram(name).observe(v)


def snapshot() -> dict | None:
    """Snapshot of the enabled registry, or None when disabled."""
    reg = _METRICS
    return None if reg is None else reg.snapshot()
