"""Host-side spans with Chrome-trace/Perfetto export.

``span("solve.chunk", chunk=3)`` opens a named region on the calling
thread's timeline; regions nest (a thread-local stack tracks the
parent), carry attributes, and land in a :class:`TraceBuffer` as
Chrome-trace ``B``/``E`` duration events with monotonic microsecond
timestamps — ``export`` writes a ``trace.json`` that loads directly in
``chrome://tracing`` or https://ui.perfetto.dev.

Fast path: with no tracer armed, :func:`span` returns one shared no-op
context manager — no allocation, no clock read, no formatting (the
``icikit.chaos`` probe discipline; see the measured numbers in
docs/DESIGN.md "Observability"). Arm with :func:`start_tracing` /
``ICIKIT_OBS``.

``mirror_device=True`` additionally wraps each span in
``jax.profiler.TraceAnnotation``, so when a ``jax.profiler`` session is
active the host spans appear on the device-side timeline too and the
two traces correlate by name.
"""

from __future__ import annotations

import os
import threading
import time

_TRACE = None               # TraceBuffer | None; lock-free hot-path read
_LOCK = threading.Lock()


def _now_us() -> int:
    # monotonic microseconds — Chrome-trace's native unit; perf_counter
    # is one clock for all threads, so per-thread ordering is free
    return time.perf_counter_ns() // 1000


class TraceBuffer:
    """Accumulates Chrome-trace events; thread-safe, append-only."""

    def __init__(self, mirror_device: bool = False):
        self.events: list = []
        self.pid = os.getpid()
        self.mirror_device = mirror_device
        self._lock = threading.Lock()
        self._next_id = 0
        self._next_tid = 1
        self._tls = threading.local()
        self._annotation_cls = None
        if mirror_device:
            try:  # resolved once; obs stays importable without jax
                from jax.profiler import TraceAnnotation
                self._annotation_cls = TraceAnnotation
            except Exception:
                self.mirror_device = False

    def _tid(self) -> int:
        """This thread's timeline id: a synthetic per-buffer counter,
        NOT ``threading.get_ident()`` — the OS reuses idents after a
        thread exits, which would merge a new worker's spans onto a
        dead thread's Perfetto track under the dead thread's name."""
        tid = getattr(self._tls, "tid", None)
        if tid is None:
            with self._lock:
                tid = self._tls.tid = self._next_tid
                self._next_tid += 1
                self.events.append({
                    "ph": "M", "name": "thread_name", "pid": self.pid,
                    "tid": tid,
                    "args": {"name": threading.current_thread().name}})
        return tid

    # -- span bookkeeping (called from _Span only) -------------------

    def _open(self, name: str, attrs: dict) -> tuple:
        tid = self._tid()
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            args = {"trace_id": sid}
            if stack:
                args["parent"] = stack[-1]
            if attrs:
                args.update(attrs)
            self.events.append({
                "ph": "B", "name": name, "pid": self.pid, "tid": tid,
                "ts": _now_us(), "args": args})
        stack.append(sid)
        return sid, tid

    def _close(self, name: str, tid: int) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack:
            stack.pop()
        with self._lock:
            self.events.append({
                "ph": "E", "name": name, "pid": self.pid, "tid": tid,
                "ts": _now_us()})

    def instant(self, name: str, **attrs) -> None:
        """One tick mark on the calling thread's timeline (Chrome ``i``
        event) — for point-in-time facts like ``chaos.fired``."""
        tid = self._tid()
        with self._lock:
            self.events.append({
                "ph": "i", "name": name, "pid": self.pid,
                "tid": tid, "ts": _now_us(),
                "s": "t", "args": dict(attrs)})

    def async_event(self, ph: str, name: str, cat: str, aid: str,
                    attrs: dict | None = None) -> None:
        """One Chrome ASYNC event (``ph`` in ``b``/``e``/``n``): a span
        keyed by ``(cat, id)`` instead of by thread, so it may open on
        one thread (or synthetic engine track) and close on another —
        the request-scoped tracing primitive (``icikit.obs.trace_ctx``).
        Perfetto groups all events of one ``(cat, id)`` into one track;
        the structural validator pairs ``b``/``e`` per ``(cat, id)``
        LIFO (``icikit.obs.chrome``)."""
        tid = self._tid()
        ev = {"ph": ph, "name": name, "cat": cat, "id": aid,
              "pid": self.pid, "tid": tid, "ts": _now_us()}
        if attrs:
            ev["args"] = dict(attrs)
        # lock-free append: list.append is atomic under the GIL and
        # async events carry no cross-event nesting state (pairing is
        # by (cat, id) at validate time) — this is the serving engine's
        # per-step hot path, measured in tools/trace_overhead_study.py
        self.events.append(ev)

    def snapshot(self) -> list:
        with self._lock:
            return list(self.events)


class _Span:
    """A live span (context manager). ``trace_id`` is the span's id in
    the trace — stamp it into records (e.g. ``BenchRecord.trace_id``)
    so report rows correlate with trace regions."""

    __slots__ = ("name", "attrs", "trace_id", "_tb", "_tid", "_ann")

    def __init__(self, tb: TraceBuffer, name: str, attrs: dict):
        self._tb = tb
        self.name = name
        self.attrs = attrs
        self.trace_id = None
        self._tid = None
        self._ann = None

    def __enter__(self):
        if self._tb._annotation_cls is not None:
            self._ann = self._tb._annotation_cls(self.name)
            self._ann.__enter__()
        self.trace_id, self._tid = self._tb._open(self.name, self.attrs)
        return self

    def __exit__(self, *exc):
        # _tid is None when __enter__ died partway (e.g. the device
        # annotation raised): closing an unopened span would corrupt
        # the nesting, and an AttributeError here would mask the
        # original failure in a caller's finally
        if self._tid is not None:
            self._tb._close(self.name, self._tid)
        if self._ann is not None:
            self._ann.__exit__(*exc)
        return False


class _NoopSpan:
    """The shared disabled span: entering/exiting does nothing and
    allocates nothing (``span()`` returns this very singleton)."""

    __slots__ = ()
    name = None
    trace_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


def span(name: str, **attrs):
    """Open a named region on this thread's timeline (use as a context
    manager). Disabled → returns the shared no-op singleton."""
    tb = _TRACE
    if tb is None:
        return NOOP_SPAN
    return _Span(tb, name, attrs)


def traced(name: str | None = None, **attrs):
    """Decorator form: ``@traced("solve.chunk")`` wraps each call of
    the function in a span (function's qualname when ``name`` is
    omitted). The disabled-path cost is one global read per call."""
    def deco(fn):
        label = name or fn.__qualname__

        def wrapper(*a, **kw):
            tb = _TRACE
            if tb is None:
                return fn(*a, **kw)
            with _Span(tb, label, attrs):
                return fn(*a, **kw)
        wrapper.__name__ = fn.__name__
        wrapper.__qualname__ = fn.__qualname__
        wrapper.__doc__ = fn.__doc__
        wrapper.__wrapped__ = fn
        return wrapper
    return deco


def instant(name: str, **attrs) -> None:
    """Record an instant event on the active trace (no-op when
    disabled)."""
    tb = _TRACE
    if tb is None:
        return
    tb.instant(name, **attrs)


def tracing() -> TraceBuffer | None:
    """The armed trace buffer, or None when tracing is disabled."""
    return _TRACE


def start_tracing(mirror_device: bool = False) -> TraceBuffer:
    """Arm a fresh process-wide trace buffer and return it (replaces
    any previous one)."""
    global _TRACE
    with _LOCK:
        _TRACE = TraceBuffer(mirror_device=mirror_device)
        return _TRACE


def stop_tracing() -> TraceBuffer | None:
    """Disarm tracing; returns the buffer that was recording (so the
    caller can export it)."""
    global _TRACE
    with _LOCK:
        tb, _TRACE = _TRACE, None
        return tb


def _swap(tb: TraceBuffer | None) -> TraceBuffer | None:
    """Install ``tb`` (may be None), returning the previous buffer —
    the restore primitive scoped sessions need."""
    global _TRACE
    with _LOCK:
        prev, _TRACE = _TRACE, tb
        return prev
