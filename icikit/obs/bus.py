"""Process-wide event bus with pluggable sinks.

The reference's telemetry was rank-0 ``printf`` (SURVEY.md §5.1); the
seed faithfully reproduced it as bare ``print(json.dumps(...))`` lines
scattered through the long-running paths. This bus gives those events
one spine: producers call :func:`emit`, consumers install a
:class:`Sink`, and the two never know about each other.

Contract (shared with ``icikit.chaos``'s probe discipline):

- **zero overhead when disabled** — :func:`emit` with no sink installed
  is one module-global read and a truthiness check; no formatting, no
  locking, no I/O. Call sites that must build expensive payloads guard
  with :func:`enabled` first.
- **strict JSON on the wire** — :class:`JsonlSink` emits one JSON
  object per line and never bare ``NaN``/``Infinity`` (non-finite
  floats become their ``repr`` string, the trainer's established
  NaN-as-string rule), so downstream consumers may use strict parsers.
- events are plain dicts; ``emit("anomaly", step=3)`` produces
  ``{"event": "anomaly", "step": 3}``, and ``emit(None, step=3)``
  produces ``{"step": 3}`` (the trainer's historical bare step record).

Sinks are installed process-wide (``add_sink``/``remove_sink``) or
scoped (``with installed(sink): ...``); the installed set is an
immutable tuple so the hot path reads it without a lock.
"""

from __future__ import annotations

import collections
import json
import math
import sys
import threading

_SINKS: tuple = ()          # lock-free hot-path read
_LOCK = threading.Lock()    # guards mutations of _SINKS only


def enabled() -> bool:
    """True when at least one sink is installed (i.e. building an event
    payload will not be wasted work)."""
    return bool(_SINKS)


def emit(event: str | None, **fields) -> None:
    """Publish one event to every installed sink.

    ``event`` becomes the dict's ``"event"`` key (omitted when None —
    the trainer's bare per-step record predates the schema and keeps
    its historical shape). A sink that raises does not stop delivery
    to the remaining sinks.
    """
    sinks = _SINKS
    if not sinks:
        return
    ev = fields if event is None else {"event": event, **fields}
    for s in sinks:
        try:
            s.write(ev)
        except Exception:  # one broken sink must not kill the producer
            pass


def add_sink(sink) -> None:
    global _SINKS
    with _LOCK:
        _SINKS = _SINKS + (sink,)


def remove_sink(sink) -> None:
    global _SINKS
    with _LOCK:
        _SINKS = tuple(s for s in _SINKS if s is not sink)


class installed:
    """Scope a sink to a ``with`` block (install on enter, remove on
    exit — the pattern every CLI entry point uses so a crashed run
    cannot leak its sink into the caller's process)."""

    def __init__(self, sink):
        self.sink = sink

    def __enter__(self):
        add_sink(self.sink)
        return self.sink

    def __exit__(self, *exc):
        remove_sink(self.sink)
        return False


# -- JSON safety ----------------------------------------------------

def json_safe(obj):
    """Recursively replace non-finite floats with their ``repr`` string
    (the NaN-as-string rule: ``json.dumps`` would happily emit bare
    ``NaN``, which is not JSON and breaks strict consumers)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else repr(obj)
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def dumps_strict(ev: dict) -> str:
    """One event as strict JSON (never bare NaN/Infinity)."""
    try:
        return json.dumps(ev, allow_nan=False)
    except (ValueError, TypeError):
        # the slow path: sanitize non-finite floats / stringify the rest
        return json.dumps(json_safe(ev), default=repr)


# -- sinks ----------------------------------------------------------

class Sink:
    """Sink interface: ``write(ev: dict)``; ``close()`` optional."""

    def write(self, ev: dict) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


class JsonlSink(Sink):
    """Strict-JSON lines to a stream.

    ``stream`` is a file-like object, or the string ``"stdout"`` /
    ``"stderr"`` — the string form resolves at *write* time, so the
    sink follows redirections like pytest's ``capsys`` swapping
    ``sys.stdout`` between tests.

    ``filter``, when given, is a predicate over the event dict; events
    it rejects are dropped by this sink only. The trainer's stdout
    record sink uses it to keep diagnostic streams (``chaos.*`` probe
    decisions) off the CLI's record contract.
    """

    def __init__(self, stream="stderr", filter=None):
        self._stream = stream
        self._filter = filter

    def _resolve(self):
        if self._stream == "stdout":
            return sys.stdout
        if self._stream == "stderr":
            return sys.stderr
        return self._stream

    def write(self, ev: dict) -> None:
        if self._filter is not None and not self._filter(ev):
            return
        self._resolve().write(dumps_strict(ev) + "\n")


class RingSink(Sink):
    """Bounded in-memory ring — the test-assertion sink ("which events
    fired, in what order?") and the flight recorder for postmortems."""

    def __init__(self, capacity: int = 4096):
        self._ring = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def write(self, ev: dict) -> None:
        with self._lock:
            self._ring.append(ev)

    @property
    def events(self) -> list:
        with self._lock:
            return list(self._ring)

    def of_type(self, event: str) -> list:
        return [e for e in self.events if e.get("event") == event]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


class FileSink(Sink):
    """Strict-JSON lines appended to a file, flushed per event (the
    ChunkCheckpoint durability discipline: a crash loses at most the
    event in flight)."""

    def __init__(self, path):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a")

    def write(self, ev: dict) -> None:
        with self._lock:
            if self._f.closed:
                return  # late event after close: drop, never crash
            self._f.write(dumps_strict(ev) + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()
