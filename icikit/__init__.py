"""icikit — TPU-native parallel-computing framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of the reference
MPI suite (masrul/Parallel-Computing-MPI): hand-rolled collective
communication algorithms expressed as ``ppermute`` schedules on a device
mesh, four distributed sorting algorithms, and a dynamic-load-balancing
study over a peg-solitaire DFS workload — each with self-verifying
benchmark harnesses turned into real tests.

Layer map (mirrors SURVEY.md §1, made explicit):

- ``icikit.utils``    — L1' runtime: mesh, deterministic RNG, timing,
                        watchdog, algorithm registry (replaces the
                        reference's compile-time ``#define`` config).
- ``icikit.parallel`` — L2' collective algorithms: ring, recursive
                        doubling, e-cube, hypercube, naive, wraparound,
                        plus XLA-native baselines (the "vendor MPI" role).
- ``icikit.ops``      — Pallas/local compute kernels (sort, merge).
- ``icikit.models``   — L3' workloads: distributed sorts, peg solitaire.
- ``icikit.bench``    — L4' benchmark harness: sweeps, verification,
                        timing, backend comparison.
"""

__version__ = "0.1.0"

from icikit.utils.mesh import make_mesh, mesh_axis_size  # noqa: F401
from icikit.utils.registry import get_algorithm, list_algorithms  # noqa: F401
