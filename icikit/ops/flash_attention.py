"""Pallas TPU flash attention: fused O(s)-memory attention, fwd + bwd.

Why this exists: the dense attention oracle
(``icikit/models/attention/dense.py``) materializes the (b, h, s, s)
logits in HBM — two full score-matrix round trips per forward (write +
softmax read) and four more in the backward. At s = 4096, bf16, that is
the whole HBM budget of the layer. This kernel streams K/V blocks
through VMEM against a resident Q block, carrying the online-softmax
(m, l, acc) state in VMEM scratch across the K grid dimension, so HBM
traffic is O(s·d) per head — the same blockwise construction the ring
schedule (``icikit/models/attention/ring.py``) uses *across* devices,
here executed *within* a chip (SURVEY.md §5.7: the reference's ring
all-to-all ``Communication/src/main.cc:190-223`` is the cross-device
ancestor of exactly this tiling).

The backward follows the standard two-pass flash recipe: residuals are
(out, lse) only; dK/dV accumulate over the Q grid, dQ over the K grid,
each recomputing the probability tile from q, k and the saved lse.

Numerics: matmuls run in the inputs' dtype on the MXU with fp32
accumulation; softmax statistics and all accumulators are fp32. Falls
back to the dense oracle for shapes the tiling cannot cover (sequence
not a multiple of 8, cross-attention with causal=True). On non-TPU
backends the kernels run in Pallas interpreter mode, so CPU-mesh tests
exercise the same code path.
"""

from __future__ import annotations

import math
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (TPU lowering)

from icikit.ops.attention import NEG_INF, dense_attention, masked_logits

_BLOCKS = (1024, 512, 256, 128, 64, 32, 16, 8)

# Base-2 softmax constants: the kernels fold log2(e) into the logit
# scale so the per-element transcendental is exp2, and convert the
# emitted lse back to nats. The forward statistics and the backward
# probability recompute must share the same fold — single-source it.
from icikit.ops.pallas_common import LN2 as _LN2
from icikit.ops.pallas_common import LOG2E as _LOG2E
from icikit.ops.pallas_common import out_struct as _out_struct
from icikit.ops.pallas_common import tpu_compiler_params


def _pick_block(s: int) -> int | None:
    """K-side block: any power-of-two divisor >= 8."""
    for b in _BLOCKS:
        if b <= s and s % b == 0:
            return b
    return None


def _pick_q_block(s: int) -> int | None:
    """Q-side block. The (b, h, 1, s) softmax-stats residual makes the
    q block the lane dimension of its BlockSpec, so Mosaic requires a
    multiple of 128 — or a single block covering the whole sequence.
    One whole-sequence block wins when it fits (measured on v5e: +3.4%
    end-to-end train step at s=1024 vs bq=512 — fewer grid revisits of
    the K stream). Past that, bq=1024 beats 512 (70 vs 50 TFLOP/s fwd
    at s=16k causal on v5e: per-step overhead amortizes over a 4×
    larger score tile); bq=2048 regresses and bq·bk ≥ 2048·2048 tiles
    fail to compile (VMEM), so 1024 is the long-sequence choice."""
    if s <= 1024 and s % 8 == 0:
        return s
    for b in (1024, 512, 256, 128):
        if s % b == 0:
            return b
    return None


def _last_valid_k(iq, bq, bk):
    """Highest K block index the causal mask lets q block ``iq`` see.
    Grid steps past it re-request this block, so Pallas elides their
    DMAs (the fetch-elision clamp; see _fwd_call)."""
    return (iq * bq + bq - 1) // bk


def _first_valid_q(ik, bq, bk):
    """Lowest Q block index that sees K block ``ik`` under the causal
    mask — the mirror clamp for K-outer grids."""
    return (ik * bk) // bq


# Finiteness invariant: NEG_INF must be a finite float32 (it is
# float32.min, not -inf). The banked-ksplit forward executes
# fully-masked sub-blocks and relies on exp2(NEG_INF - m*)
# underflowing to exactly 0 in the bank merge; with a true -inf mask
# a fully-masked bank would compute exp2(-inf - -inf) = NaN and
# poison the merge. Do not switch the masking to -jnp.inf.
assert math.isfinite(NEG_INF), "bank merge requires a finite mask value"


def _tri_bias(bq, bk):
    """The diagonal tile's additive causal mask: 0 where q >= k,
    NEG_INF above — the single source for every kernel's bias init.
    NEG_INF is finite by invariant (see assertion above)."""
    qpos = lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(qpos >= kpos, 0.0, NEG_INF)


def _init_mask_bias(bias_s, iq, ik, bq, bk, base: float = 0.0):
    """Fill the (3·bq, bk) additive-mask scratch at the first grid step:
    rows [0, bq) hold all-NEG_INF (tiles strictly above the diagonal —
    reachable only as the upper half of a coarse K block that straddles
    it), rows [bq, 2·bq) the diagonal tile's mask (0 where q >= k),
    rows [2·bq, 3·bq) zeros for interior tiles. With square tiles
    (bq == bk) every diagonal-crossing tile shares one relative
    pattern, so the per-tile iota/compare/select collapses to one
    dynamic-slice read folded into the scale fma.

    ``base`` shifts the valid entries (the constant-shift kernel folds
    its −shift here, so the shift costs zero runtime ops)."""
    first = ((pl.program_id(0) == 0) & (pl.program_id(1) == 0)
             & (iq == 0) & (ik == 0))

    @pl.when(first)
    def _():
        bias_s[pl.ds(0, bq), :] = jnp.full((bq, bk), NEG_INF, jnp.float32)
        bias_s[pl.ds(bq, bq), :] = _tri_bias(bq, bk) + base
        bias_s[pl.ds(2 * bq, bq), :] = jnp.full((bq, bk), base,
                                                jnp.float32)


def _mask_bias(bias_s, iq, ik, bq):
    """The additive mask for tile (iq, ik): full mask above the
    diagonal (iq < ik), diagonal pattern at iq == ik, zeros interior."""
    idx = jnp.clip(iq - ik + 1, 0, 2)
    return bias_s[pl.ds(idx * bq, bq), :]


def _causal_mask(s, iq, ik, bq, bk):
    qpos = iq * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = ik * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc,
                *bias_s, scale, causal, nk, bq, bk, ks):
    """Streaming forward. Each grid step covers ``ks`` K sub-blocks of
    width ``bk`` (one coarse DMA block of ks·bk rows), and each
    sub-block lane j owns an INDEPENDENT (m, l, acc) accumulator bank
    (rows [j·bq, (j+1)·bq) of the scratches), merged once at the final
    store. Independent banks make the whole per-sub-block chain (dot →
    mask → softmax → accumulate) data-independent across j, so Mosaic's
    scheduler can run lane j+1's MXU dots underneath lane j's VPU
    softmax — the ks = 1 structure serializes the two units, and a
    shared accumulator would re-serialize them at every update."""
    iq, ik = pl.program_id(2), pl.program_id(3)

    if bias_s:  # square tiles: precompute the mask once as an additive
        _init_mask_bias(bias_s[0], iq, ik, bq, bk)  # bias (see helper)

    @pl.when(ik == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, -jnp.inf)
        l_s[:] = jnp.zeros_like(l_s)
        acc[:] = jnp.zeros_like(acc)

    if causal:  # any sub-block of the coarse block visible?
        run = ik * (ks * bk) <= iq * bq + bq - 1
    else:
        run = ik >= 0

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        # base-2 softmax: fold log2(e) into the logit scale (free — the
        # scale multiply exists anyway) so the transcendental is exp2,
        # skipping exp's internal x*log2(e) pass on every tile element.
        # All statistics live in base-2 space; the emitted lse converts
        # back to nats at the end.
        for j in range(ks):
            k = k_ref[0, 0, j * bk:(j + 1) * bk]
            v = v_ref[0, 0, j * bk:(j + 1) * bk]
            ikj = ik * ks + j  # sub-block column index
            raw = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
            if bias_s:
                # one fma: the scale multiply and the mask add fuse, so
                # interior tiles (bias slice = zeros) pay nothing extra
                s = (raw * (scale * _LOG2E)
                     + _mask_bias(bias_s[0], iq, ikj, bq))
            elif causal:
                s = _causal_mask(raw * (scale * _LOG2E), iq, ikj, bq, bk)
            else:
                s = raw * (scale * _LOG2E)
            rows = pl.ds(j * bq, bq)                 # bank j
            m_prev = m_s[rows]                       # (bq, 128), lane-dup
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp2(m_prev - m_new)
            w = jnp.exp2(s - m_new[:, :1])
            l_s[rows] = l_s[rows] * alpha + jnp.sum(w, axis=1,
                                                    keepdims=True)
            acc[rows] = acc[rows] * alpha[:, :1] + lax.dot_general(
                w.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_s[rows] = m_new

    @pl.when(ik == nk - 1)
    def _():
        # merge the ks banks: m* = max_j m_j, rescale each (l, acc)
        m_star = m_s[pl.ds(0, bq)]
        for j in range(1, ks):
            m_star = jnp.maximum(m_star, m_s[pl.ds(j * bq, bq)])
        l_tot = jnp.zeros((bq, 1), jnp.float32)
        o_tot = jnp.zeros((bq, acc.shape[1]), jnp.float32)
        for j in range(ks):
            rows = pl.ds(j * bq, bq)
            beta = jnp.exp2(m_s[rows] - m_star)
            l_tot = l_tot + l_s[rows][:, :1] * beta[:, :1]
            o_tot = o_tot + acc[rows] * beta[:, :1]
        o_ref[0, 0] = (o_tot / l_tot).astype(o_ref.dtype)
        # ln sum(e^z) = m2*ln2 + ln(l) with m2 = max in base-2 space
        lse_ref[0, 0, 0] = (m_star[:, 0] * _LN2
                            + jnp.log(l_tot[:, 0]))


def _fwd_const_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, l_s, acc,
                      *bias_s, scale, causal, nk, bq, bk, ks,
                      shift: float):
    """Constant-shift streaming forward: ``w = exp2(s − shift)`` with a
    FIXED shift instead of the online rowmax. The tile-floor ablations
    (``bench/tile_floor.py``) showed the exposed per-tile cost of the
    d=64 forward is the rowmax chain (~0.5 µs/tile), not the exp2
    (~0) — removing the max dependency lets Mosaic overlap the rest.
    The shift folds into the mask-bias scratch (square tiles) or the
    scale fma, so it costs zero extra ops.

    Numerical contract: safe while max_row |s·scale·log2e − shift|
    stays within fp32 exp2 range (~±126). Overflow (scores ≫ shift)
    makes ``l`` inf → lse inf; total underflow makes l = 0 → lse
    −inf. Both are DETECTABLE from the returned lse (callers check
    ``jnp.isfinite(lse)``) and the wrapper re-runs the online-softmax
    kernel on detection — the same optimistic-with-fallback discipline
    as the sorts' capacity retry. Opt-in via ``softmax_shift``; the
    default path keeps exact online softmax."""
    iq, ik = pl.program_id(2), pl.program_id(3)

    if bias_s:  # shift pre-folded into the bias tiles (base=-shift)
        _init_mask_bias(bias_s[0], iq, ik, bq, bk, base=-shift)

    @pl.when(ik == 0)
    def _():
        l_s[:] = jnp.zeros_like(l_s)
        acc[:] = jnp.zeros_like(acc)

    if causal:
        run = ik * (ks * bk) <= iq * bq + bq - 1
    else:
        run = ik >= 0

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        for j in range(ks):
            k = k_ref[0, 0, j * bk:(j + 1) * bk]
            v = v_ref[0, 0, j * bk:(j + 1) * bk]
            ikj = ik * ks + j
            raw = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
            if bias_s:
                s = (raw * (scale * _LOG2E)
                     + _mask_bias(bias_s[0], iq, ikj, bq))
            elif causal:
                s = _causal_mask(raw * (scale * _LOG2E) - shift,
                                 iq, ikj, bq, bk)
            else:
                s = raw * (scale * _LOG2E) - shift
            w = jnp.exp2(s)
            rows = pl.ds(j * bq, bq)
            l_s[rows] += jnp.sum(w, axis=1, keepdims=True)
            acc[rows] += lax.dot_general(
                w.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _():
        # bank merge is a plain sum — no max, no rescale
        l_tot = l_s[pl.ds(0, bq)][:, :1]
        o_tot = acc[pl.ds(0, bq)]
        for j in range(1, ks):
            rows = pl.ds(j * bq, bq)
            l_tot = l_tot + l_s[rows][:, :1]
            o_tot = o_tot + acc[rows]
        o_ref[0, 0] = (o_tot / l_tot).astype(o_ref.dtype)
        # lse = ln Σ e^z = shift·ln2 + ln(l): same form as the online
        # kernel with the constant standing in for the rowmax
        lse_ref[0, 0, 0] = shift * _LN2 + jnp.log(l_tot[:, 0])


def _fwd_single_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *bias_s,
                       scale, causal, bq, bk, shift=None):
    """One K block covers the whole row (nk == 1, the s <= 1024 train
    case): no online-softmax carry — direct rowwise max/sum with no
    (m, l, acc) scratch, no -inf init pass and no alpha rescale. The
    causal mask is a VMEM bias tile computed once per launch and folded
    into the scale multiply as a single fma."""
    if bias_s:
        first = (pl.program_id(0) == 0) & (pl.program_id(1) == 0)

        @pl.when(first)
        def _():
            bias_s[0][:] = _tri_bias(bq, bk)

    @pl.when(pl.program_id(1) >= 0)  # always true; see _bwd_fused_kernel
    def _():
        q, k, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        raw = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
        if bias_s:
            s = raw * (scale * _LOG2E) + bias_s[0][:]
        elif causal:
            s = _causal_mask(raw * (scale * _LOG2E), 0, 0, bq, bk)
        else:
            s = raw * (scale * _LOG2E)
        if shift is None:
            m = jnp.max(s, axis=1, keepdims=True)
        else:
            # constant-shift variant: the rowmax chain is the tile
            # loop's exposed VPU cost (bench/tile_floor.py); a fixed
            # shift removes it, overflow is detectable from lse
            m = jnp.full((bq, 1), shift, jnp.float32)
        w = jnp.exp2(s - m)
        l = jnp.sum(w, axis=1, keepdims=True)
        acc = lax.dot_general(w.astype(v.dtype), v,
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
        o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
        lse_ref[0, 0, 0] = m[:, 0] * _LN2 + jnp.log(l[:, 0])


def _fwd_single_call(qt, kt, vt, causal, scale, bq, bk, interpret,
                     shift=None):
    b, h, sq, d = qt.shape
    at = lambda ib, ih: (ib, ih, 0, 0)  # noqa: E731
    bias_scratch = ([pltpu.VMEM((bq, bk), jnp.float32)] if causal else [])
    return pl.pallas_call(
        partial(_fwd_single_kernel, scale=scale, causal=causal,
                bq=bq, bk=bk, shift=shift),
        grid=(b, h),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), at),
            pl.BlockSpec((1, 1, bk, d), at),
            pl.BlockSpec((1, 1, bk, d), at),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), at),
            pl.BlockSpec((1, 1, 1, bq), at),
        ],
        out_shape=[
            _out_struct((b, h, sq, d), qt.dtype, qt, kt, vt),
            _out_struct((b, h, 1, sq), jnp.float32, qt, kt, vt),
        ],
        scratch_shapes=bias_scratch,
        # the (bq, bk) f32 score/bias tiles exceed the default 16 MB
        # scoped budget at bq = bk = 1024
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(qt, kt, vt)


def _fwd_call(qt, kt, vt, causal, scale, bq, bk, interpret, ksplit=1,
              shift=None):
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    if sq // bq == 1 and sk // bk == 1:
        return _fwd_single_call(qt, kt, vt, causal, scale, bq, bk,
                                interpret, shift)
    if sk % (bk * ksplit):
        ksplit = 1
    cbk = bk * ksplit  # coarse (DMA) K block: ksplit sub-blocks
    nq, nk = sq // bq, sk // cbk
    if shift is None:
        kernel = partial(_fwd_kernel, scale=scale, causal=causal,
                         nk=nk, bq=bq, bk=bk, ks=ksplit)
        stat_scratch = [
            pltpu.VMEM((ksplit * bq, 128), jnp.float32),  # running max
            pltpu.VMEM((ksplit * bq, 128), jnp.float32),  # normalizer
            pltpu.VMEM((ksplit * bq, d), jnp.float32),    # out accum
        ]
    else:
        kernel = partial(_fwd_const_kernel, scale=scale, causal=causal,
                         nk=nk, bq=bq, bk=bk, ks=ksplit,
                         shift=float(shift))
        stat_scratch = [
            pltpu.VMEM((ksplit * bq, 128), jnp.float32),  # normalizer
            pltpu.VMEM((ksplit * bq, d), jnp.float32),    # out accum
        ]
    use_bias = causal and bq == bk and nk * ksplit > 1
    bias_scratch = ([pltpu.VMEM((3 * bq, bk), jnp.float32)]
                    if use_bias else [])
    if causal:
        # Clamp the K/V fetch index to the causal bound: grid steps
        # above the diagonal (run=False) then ask for the *same* block
        # as their predecessor, and Pallas elides the repeat DMA — the
        # skipped half of the grid stops costing HBM fetch slots
        # (+15-20% fwd at s=16k, bq=512 on v5e; neutral at bq=1024).
        k_at = lambda ib, ih, iq, ik: (  # noqa: E731
            ib, ih, jnp.minimum(ik, _last_valid_k(iq, bq, cbk)), 0)
    else:
        k_at = lambda ib, ih, iq, ik: (ib, ih, ik, 0)  # noqa: E731
    # NOTE: the bias scratch is initialized only at the single global
    # first grid step (_init_mask_bias) and read by every later (b, h,
    # iq, ik) step. That is safe because this grid uses the default
    # 'arbitrary' (serial) dimension semantics; if any grid dimension is
    # ever marked parallel / megacore-partitioned (v4/v5p), the init
    # must move to per-(b, h) first steps ((iq == 0) & (ik == 0)).
    return pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, cbk, d), k_at),
            pl.BlockSpec((1, 1, cbk, d), k_at),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda ib, ih, iq, ik: (ib, ih, 0, iq)),
        ],
        out_shape=[
            _out_struct((b, h, sq, d), qt.dtype, qt, kt, vt),
            _out_struct((b, h, 1, sq), jnp.float32, qt, kt, vt),
        ],
        scratch_shapes=[
            # ks independent accumulator banks, rows [j*bq, (j+1)*bq)
            *stat_scratch,
            *bias_scratch,                        # additive causal mask
        ],
        # the (3·bq, bk) bias tile overflows Mosaic's default 16 MB
        # scoped-VMEM budget at bq = bk = 1024 (v5e has 128 MB); other
        # configurations keep the default guardrail
        **({"compiler_params": tpu_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024)} if use_bias else {}),
        interpret=interpret,
    )(qt, kt, vt)


# --------------------------------------------------------------- backward

def _p_tile(q, k, lse, iq, ik, bq, bk, scale, causal, bias=None):
    """Recompute the probability tile exp(s·scale − lse) in fp32 —
    in base-2 space (cf. the forward): the log2(e) factor folds into
    the existing scale multiply and a per-row lse conversion, so the
    per-element transcendental is a bare exp2. With ``bias`` (the
    precomputed additive causal mask) the mask folds into the scale
    multiply as one fma instead of the per-tile iota/compare/select."""
    raw = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    if bias is not None:
        s = raw * (scale * _LOG2E) + bias
    elif causal:
        s = _causal_mask(raw * (scale * _LOG2E), iq, ik, bq, bk)
    else:
        s = raw * (scale * _LOG2E)
    return jnp.exp2(s - (lse * _LOG2E)[:, None])


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref,
                   dq_acc, *, scale, causal, nk, bq, bk):
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ik * bk <= iq * bq + bq - 1) if causal else (ik >= 0)

    @pl.when(run)
    def _():
        q, k, v, do = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], do_ref[0, 0]
        p = _p_tile(q, k, lse_ref[0, 0, 0], iq, ik, bq, bk, scale, causal)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0, 0, 0][:, None]) * scale
        dq_acc[:] += lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, nq, bq, bk):
    ik, iq = pl.program_id(2), pl.program_id(3)

    @pl.when(iq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (iq * bq + bq - 1 >= ik * bk) if causal else (iq >= 0)

    @pl.when(run)
    def _():
        q, k, v, do = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], do_ref[0, 0]
        p = _p_tile(q, k, lse_ref[0, 0, 0], iq, ik, bq, bk, scale, causal)
        dv_acc[:] += lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0, 0, 0][:, None]) * scale
        dk_acc[:] += lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                      dq_ref, dk_ref, dv_ref, *bias_s, scale, causal,
                      bq, bk):
    """Single-block backward: when the whole sequence fits one (bq, bk)
    tile (the common case at s <= 1024), dq/dk/dv share one recompute
    of the probability tile — 5 matmuls and one operand read instead
    of the two-kernel path's 7 and two."""
    if bias_s:
        first = (pl.program_id(0) == 0) & (pl.program_id(1) == 0)

        @pl.when(first)
        def _():
            bias_s[0][:] = _tri_bias(bq, bk)

    @pl.when(pl.program_id(3) == 0)  # always true; the stores sit
    def _():                         # under a cond like the tiled
        q, k, v, do = (q_ref[0, 0], k_ref[0, 0], v_ref[0, 0],
                       do_ref[0, 0])  # kernels', which the interpret-
        # mode vma discharge requires (bare stores trip its
        # dynamic_slice check under shard_map)
        p = _p_tile(q, k, lse_ref[0, 0, 0], 0, 0, bq, bk, scale, causal,
                    bias_s[0][:] if bias_s else None)
        dv_ref[0, 0] = lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0, 0, 0][:, None]) * scale
        dq_ref[0, 0] = lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_ref[0, 0] = lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _bwd_fused_tiled_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                            dq_ref, dk_ref, dv_ref,
                            dq_full, dk_acc, dv_acc,
                            *bias_s, scale, causal, nq, nk, bq, bk):
    """Fused multi-block backward: one pass over the (ik outer, iq
    inner) grid computes dq, dk and dv from a single recompute of each
    probability tile — 5 matmuls and one operand stream where the
    two-kernel path costs 7 and two. dk/dv accumulate in per-K-block
    scratch across the inner Q sweep; dq accumulates into a
    whole-sequence fp32 VMEM scratch (``dq_full``) and is flushed to
    HBM exactly once, during the final K row (the output index map
    parks on block 0 until then, so no intermediate write-backs
    occur)."""
    ik, iq = pl.program_id(2), pl.program_id(3)

    if bias_s:  # square tiles: one (diag, interior) additive-mask pair
        _init_mask_bias(bias_s[0], iq, ik, bq, bk)

    @pl.when((ik == 0) & (iq == 0))
    def _():
        dq_full[:] = jnp.zeros_like(dq_full)

    @pl.when(iq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (iq * bq + bq - 1 >= ik * bk) if causal else (iq >= 0)

    @pl.when(run)
    def _():
        q, k, v, do = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], do_ref[0, 0]
        p = _p_tile(q, k, lse_ref[0, 0, 0], iq, ik, bq, bk, scale, causal,
                    _mask_bias(bias_s[0], iq, ik, bq) if bias_s else None)
        dv_acc[:] += lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0, 0, 0][:, None]) * scale
        dk_acc[:] += lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq_full[pl.ds(iq * bq, bq), :] += lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[0, 0] = dq_full[pl.ds(iq * bq, bq), :].astype(dq_ref.dtype)

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


# The fused tiled backward holds the full (s_q, d) fp32 dq accumulator
# in VMEM; past this budget (48 MB covers s=131072 at d=64 with room
# for the streaming tiles in v5e's 128 MB) fall back to the two-kernel
# path.
_DQ_SCRATCH_BYTES_MAX = 48 * 1024 * 1024


def _bwd_fused_tiled_call(qt, kt, vt, do, lse, delta, causal, scale,
                          bq, bk, interpret):
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    nq, nk = sq // bq, sk // bk
    if causal:
        # Mirror of the forward clamp: steps left of the causal bound
        # (run=False, at the *start* of each inner Q sweep) re-request
        # the first valid Q block, so their fetches are elided.
        q_at = lambda ib, ih, ik, iq: (  # noqa: E731
            ib, ih, jnp.maximum(iq, _first_valid_q(ik, bq, bk)), 0)
        r_at = lambda ib, ih, ik, iq: (  # noqa: E731
            ib, ih, 0, jnp.maximum(iq, _first_valid_q(ik, bq, bk)))
    else:
        q_at = lambda ib, ih, ik, iq: (ib, ih, iq, 0)   # noqa: E731
        r_at = lambda ib, ih, ik, iq: (ib, ih, 0, iq)   # noqa: E731
    k_at = lambda ib, ih, ik, iq: (ib, ih, ik, 0)       # noqa: E731
    # dq flushes only during the final K row: park on block 0 before
    # that (constant index map = no write-back), then walk the Q blocks.
    dq_at = lambda ib, ih, ik, iq: (                    # noqa: E731
        ib, ih, jnp.where(ik == nk - 1, iq, 0), 0)
    use_bias = causal and bq == bk
    bias_scratch = ([pltpu.VMEM((3 * bq, bk), jnp.float32)]
                    if use_bias else [])
    return pl.pallas_call(
        partial(_bwd_fused_tiled_kernel, scale=scale, causal=causal,
                nq=nq, nk=nk, bq=bq, bk=bk),
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), q_at),
            pl.BlockSpec((1, 1, bk, d), k_at),
            pl.BlockSpec((1, 1, bk, d), k_at),
            pl.BlockSpec((1, 1, bq, d), q_at),
            pl.BlockSpec((1, 1, 1, bq), r_at),
            pl.BlockSpec((1, 1, 1, bq), r_at),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), dq_at),
            pl.BlockSpec((1, 1, bk, d), k_at),
            pl.BlockSpec((1, 1, bk, d), k_at),
        ],
        out_shape=[
            _out_struct((b, h, sq, d), qt.dtype, qt, kt, vt, do, lse, delta),
            _out_struct((b, h, sk, d), kt.dtype, qt, kt, vt, do, lse, delta),
            _out_struct((b, h, sk, d), vt.dtype, qt, kt, vt, do, lse, delta),
        ],
        scratch_shapes=[
            pltpu.VMEM((sq, d), jnp.float32),   # dq accumulator
            pltpu.VMEM((bk, d), jnp.float32),   # dk accumulator
            pltpu.VMEM((bk, d), jnp.float32),   # dv accumulator
            *bias_scratch,                      # additive causal mask
        ],
        # The whole-sequence dq accumulator deliberately exceeds
        # Mosaic's default 16 MB scoped-VMEM budget; v5e has 128 MB.
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta)


def _bwd_call(qt, kt, vt, do, lse, delta, causal, scale, bq, bk, interpret):
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    nq, nk = sq // bq, sk // bk

    if nq == 1 and nk == 1:
        # index via the (size-1) grid vars, not literal zeros: the
        # interpreter's vma discharge accepts program-id-derived starts
        at = lambda ib, ih, iq, ik: (ib, ih, iq, ik)  # noqa: E731
        rt = at  # residuals share the whole-block index map
        bias_scratch = ([pltpu.VMEM((bq, bk), jnp.float32)]
                        if causal else [])
        return pl.pallas_call(
            partial(_bwd_fused_kernel, scale=scale, causal=causal,
                    bq=bq, bk=bk),
            grid=(b, h, 1, 1),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), at),
                pl.BlockSpec((1, 1, bk, d), at),
                pl.BlockSpec((1, 1, bk, d), at),
                pl.BlockSpec((1, 1, bq, d), at),
                pl.BlockSpec((1, 1, 1, bq), rt),
                pl.BlockSpec((1, 1, 1, bq), rt),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, bq, d), at),
                pl.BlockSpec((1, 1, bk, d), at),
                pl.BlockSpec((1, 1, bk, d), at),
            ],
            out_shape=[
                _out_struct((b, h, sq, d), qt.dtype, qt, kt, vt, do,
                            lse, delta),
                _out_struct((b, h, sk, d), kt.dtype, qt, kt, vt, do,
                            lse, delta),
                _out_struct((b, h, sk, d), vt.dtype, qt, kt, vt, do,
                            lse, delta),
            ],
            scratch_shapes=bias_scratch,
            # the (bq, bk) f32 bias tile exceeds the 16 MB default
            # scoped budget at bq = bk = 1024
            **({"compiler_params": tpu_compiler_params(
                vmem_limit_bytes=64 * 1024 * 1024)} if causal else {}),
            interpret=interpret,
        )(qt, kt, vt, do, lse, delta)

    if sq * d * 4 <= _DQ_SCRATCH_BYTES_MAX:
        return _bwd_fused_tiled_call(qt, kt, vt, do, lse, delta, causal,
                                     scale, bq, bk, interpret)

    q_at = lambda ib, ih, iq, ik: (ib, ih, iq, 0)       # noqa: E731
    if causal:  # fetch-elision clamp, as in the fused paths
        k_at = lambda ib, ih, iq, ik: (  # noqa: E731
            ib, ih, jnp.minimum(ik, _last_valid_k(iq, bq, bk)), 0)
    else:
        k_at = lambda ib, ih, iq, ik: (ib, ih, ik, 0)   # noqa: E731
    r_at = lambda ib, ih, iq, ik: (ib, ih, 0, iq)       # noqa: E731
    dq = pl.pallas_call(
        partial(_bwd_dq_kernel, scale=scale, causal=causal, nk=nk,
                bq=bq, bk=bk),
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), q_at),
            pl.BlockSpec((1, 1, bk, d), k_at),
            pl.BlockSpec((1, 1, bk, d), k_at),
            pl.BlockSpec((1, 1, bq, d), q_at),
            pl.BlockSpec((1, 1, 1, bq), r_at),
            pl.BlockSpec((1, 1, 1, bq), r_at),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), q_at),
        out_shape=_out_struct((b, h, sq, d), qt.dtype, qt, kt, vt, do, lse,
                              delta),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta)

    if causal:
        qk_at = lambda ib, ih, ik, iq: (  # noqa: E731
            ib, ih, jnp.maximum(iq, _first_valid_q(ik, bq, bk)), 0)
    else:
        qk_at = lambda ib, ih, ik, iq: (ib, ih, iq, 0)  # noqa: E731
    kk_at = lambda ib, ih, ik, iq: (ib, ih, ik, 0)      # noqa: E731
    rk_at = lambda ib, ih, ik, iq: (ib, ih, 0, iq)      # noqa: E731
    dk, dv = pl.pallas_call(
        partial(_bwd_dkv_kernel, scale=scale, causal=causal, nq=nq,
                bq=bq, bk=bk),
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), qk_at),
            pl.BlockSpec((1, 1, bk, d), kk_at),
            pl.BlockSpec((1, 1, bk, d), kk_at),
            pl.BlockSpec((1, 1, bq, d), qk_at),
            pl.BlockSpec((1, 1, 1, bq), rk_at),
            pl.BlockSpec((1, 1, 1, bq), rk_at),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), kk_at),
            pl.BlockSpec((1, 1, bk, d), kk_at),
        ],
        out_shape=[
            _out_struct((b, h, sk, d), kt.dtype, qt, kt, vt, do, lse, delta),
            _out_struct((b, h, sk, d), vt.dtype, qt, kt, vt, do, lse, delta),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------- custom_vjp

def _fwd_with_fallback(qt, kt, vt, causal, scale, bq, bk, ks,
                       interpret, shift):
    """Constant-shift forward with the exact-fallback INSIDE the
    custom_vjp boundary: overflow (non-finite lse) re-runs the online
    kernel via a traced cond, so the residuals the backward sees are
    always the final, correct (out, lse). A fallback outside the
    custom_vjp would leave the shift-branch's backward always live
    under grad, and on overflow its NaN/inf residuals poison the
    gradients (delta = 0 x NaN) even though the forward fell back."""
    out, lse = _fwd_call(qt, kt, vt, causal, scale, bq, bk, interpret,
                         ks, shift)
    if shift is None:
        return out, lse
    return lax.cond(
        jnp.isfinite(lse).all(),
        lambda: (out, lse),
        lambda: _fwd_call(qt, kt, vt, causal, scale, bq, bk,
                          interpret, ks, None))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(qt, kt, vt, causal, scale, bq, bk, ks, interpret,
           shift=None):
    return _fwd_with_fallback(qt, kt, vt, causal, scale, bq, bk, ks,
                              interpret, shift)


def _flash_fwd(qt, kt, vt, causal, scale, bq, bk, ks, interpret,
               shift=None):
    out, lse = _fwd_with_fallback(qt, kt, vt, causal, scale, bq, bk,
                                  ks, interpret, shift)
    return (out, lse), (qt, kt, vt, out, lse)


def _flash_bwd(causal, scale, bq, bk, ks, interpret, shift, res, g):
    g_out, g_lse = g
    qt, kt, vt, out, lse = res
    # delta_i = sum_d dO_i·O_i — the rowwise dot that closes the softmax
    # jacobian; cheap (one O(s·d) pass), so computed outside the kernels.
    # The lse cotangent folds into the same tile formula: d lse_i/d s_ij
    # = p_ij, so ds = p ∘ (dp − delta + g_lse) — passing (delta − g_lse)
    # through the kernels' delta operand needs no kernel changes (dV is
    # lse-independent).
    delta = jnp.sum(g_out.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, :, None, :]
    dq, dk, dv = _bwd_call(qt, kt, vt, g_out, lse,
                           delta - g_lse.astype(jnp.float32),
                           causal, scale, bq, bk, interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


# ----------------------------------------------------------------- public

def _dense_with_lse(q, k, v, causal, scale):
    """Oracle fallback returning (out, lse) — materializes the logits.
    Masks with true -inf so fully-masked rows (causal with s_q > s_kv)
    honor the blockwise-merge contract: lse = -inf, zero output."""
    logits = masked_logits(q, k, causal, scale, fill=-jnp.inf)
    lse = jax.nn.logsumexp(logits, axis=-1)
    w = jnp.where(jnp.isneginf(lse)[..., None], 0.0,
                  jnp.exp(logits - lse[..., None]))
    out = jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype), lse


def _flash_supported(sq, sk, causal):
    bq, bk = _pick_q_block(sq), _pick_block(sk)
    if bq is None or bk is None or (causal and sq != sk):
        return None
    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        # No Mosaic lowering (e.g. GPU): the compiled dense oracle beats
        # the Pallas interpreter by orders of magnitude.
        return None
    return bq, bk, backend == "cpu"  # CPU meshes run the same kernels


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                             causal: bool = False,
                             scale: float | None = None,
                             block_q: int | None = None,
                             block_k: int | None = None,
                             softmax_shift: float | None = None):
    """Flash attention returning the per-row log-sum-exp as well.

    Returns ``(out (b, s_q, h, d), lse (b, h, s_q))``. The lse is what
    blockwise consumers (the ring schedule) need to merge partial
    attention results exactly; its cotangent is handled by the custom
    backward. Unsupported shapes/backends fall back to the dense oracle
    with an explicit logsumexp.

    ``softmax_shift`` opts into the constant-shift forward: a fixed
    base-2 shift replaces the online rowmax (the measured exposed cost
    of the d=64 tile loop — see ``bench/tile_floor.py``), with a
    traced exact-fallback on overflow (non-finite lse). Use only for
    full causal/dense attention where a −inf lse cannot occur by
    design; 16.0 is a good value for unit-variance inputs.

    ``block_q``/``block_k`` override the automatic tile choice (e.g.
    the benchmark's cross-tiling oracle). ``block_q`` must be the whole
    sequence or a multiple of 128 dividing it (Mosaic lane constraint
    on the lse residual); ``block_k`` a divisor of the K length.
    """
    sup = _flash_supported(q.shape[1], k.shape[1], causal)
    if sup is None:
        if block_q or block_k:
            raise ValueError(
                f"shape (s_q={q.shape[1]}, s_kv={k.shape[1]}, "
                f"causal={causal}) has no flash tiling to override")
        return _dense_with_lse(q, k, v, causal, scale)
    bq, bk, interpret = sup
    if block_q is not None:
        sq = q.shape[1]
        if not (block_q == sq or (block_q % 128 == 0 and sq % block_q == 0)):
            raise ValueError(
                f"block_q={block_q} must be the whole sequence or a "
                f"multiple of 128 dividing s_q={sq}")
        bq = block_q
    if block_k is not None:
        if block_k < 8 or block_k % 8 or k.shape[1] % block_k:
            raise ValueError(f"block_k={block_k} must be a multiple of "
                             f"8 dividing s_kv={k.shape[1]}")
        bk = block_k
    if scale is None:
        scale = q.shape[-1] ** -0.5
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    # Two K sub-blocks per grid step on the long-sequence path: the
    # sub-blocks' score matmuls are independent, so the scheduler can
    # overlap sub-block j+1's MXU dot with sub-block j's VPU softmax
    # (ks = 1 serializes the units). Needs >= 4 K blocks to matter.
    ks = 2 if (bq == bk and k.shape[1] // bk >= 4) else 1
    # The constant-shift path carries its exact-fallback INSIDE the
    # custom_vjp (_fwd_with_fallback): overflow re-runs the online
    # kernel via a traced cond, no host sync, and the backward always
    # sees the final correct (out, lse). NOTE: shift is only valid
    # where a -inf lse cannot occur by design (full causal/dense
    # attention — every row sees the diagonal); ring/blockwise
    # schedules with fully-masked rows must keep the online path.
    out, lse = _flash(qt, kt, vt, bool(causal), float(scale), bq, bk,
                      ks, interpret,
                      None if softmax_shift is None
                      else float(softmax_shift))
    # Names for rematerialization policies: a checkpointed layer whose
    # policy saves these skips re-running the forward kernel in the
    # backward pass (TransformerConfig.remat_policy = "dots_attn").
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out.transpose(0, 2, 1, 3), lse[:, :, 0, :]


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    scale: float | None = None,
                    softmax_shift: float | None = None) -> jax.Array:
    """Fused flash attention; drop-in for ``dense_attention``.

    Args:
      q: ``(b, s_q, h, d)``; k, v: ``(b, s_kv, h, d)``.
      causal: lower-triangular masking (requires ``s_q == s_kv``).
      scale: logit scale, default ``d ** -0.5``.

    Returns:
      ``(b, s_q, h, d)`` in ``q.dtype``, numerically equal to the dense
      oracle up to fp32-accumulation reassociation. Shapes the tiling
      cannot cover fall back to the oracle.
    """
    if _flash_supported(q.shape[1], k.shape[1], causal) is None:
        return dense_attention(q, k, v, causal=causal, scale=scale)
    return flash_attention_with_lse(q, k, v, causal=causal, scale=scale,
                                    softmax_shift=softmax_shift)[0]


def resolve_attention_impl(name: str):
    """Map a config string to the local attention kernel (the single
    selection point for the sp=1, pipeline, and Ulysses paths)."""
    impls = {"flash": flash_attention, "dense": dense_attention}
    if name not in impls:
        raise ValueError(f"unknown attention impl {name!r} "
                         f"(known: {', '.join(sorted(impls))})")
    return impls[name]


# ----------------------------------------------------- fused decode step

def _rope_rotate(x, cos2, sin2, dh):
    """Split-half RoPE on a (rows, dh) tile: ``cos2``/``sin2`` are the
    duplicated tables ``concat([c, c])``/``concat([s, s])`` (1, dh), so
    the rotation is two fmas plus one half-lane swap."""
    x32 = x.astype(jnp.float32)
    h = dh // 2
    rot = jnp.concatenate([-x32[:, h:], x32[:, :h]], axis=1)
    return x32 * cos2 + rot * sin2


def _decode_step_kernel(cur_ref, q_ref, k_ref, v_ref, cos_ref, sin_ref,
                        kc_ref, vc_ref, o_ref, ko_ref, vo_ref, *,
                        scale, rope, total, dh):
    """One (batch*head) row of the fused decode attention inner step:
    RoPE-apply on the new q/k, KV-cache column write at ``cur``, and
    the masked flash-decode read — the ops the round-5 profile charged
    ~8 serialized sub-µs fusions per layer at b=1 (DECODE.md),
    collapsed into one kernel launch per layer.

    The cache rides twice: as a read-only VMEM input block (the
    attention operand) and as a 1-row *aliased* output block addressed
    by the scalar-prefetched ``cur`` (stack_write's discipline), so the
    HBM write-back per step is one (1, dh) row, not the whole cache.
    The just-written column therefore isn't in the input block — its
    logit/value contributions are patched in from the fresh q/k/v
    registers instead (``t == cur`` select below)."""
    cur = cur_ref[0]
    q = q_ref[...]                                   # (1, dh)
    k = k_ref[...]
    v = v_ref[...]
    if rope:
        cos2, sin2 = cos_ref[...], sin_ref[...]
        q = _rope_rotate(q, cos2, sin2, dh).astype(q_ref.dtype)
        k = _rope_rotate(k, cos2, sin2, dh).astype(k_ref.dtype)
    ko_ref[0] = k.astype(ko_ref.dtype)               # cache column write
    vo_ref[0] = v.astype(vo_ref.dtype)
    kc = kc_ref[0]                                   # (total, dh), stale
    raw = lax.dot_general(q, kc, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)   # (1, T)
    qk = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)    # (1, 1)
    t_idx = lax.broadcasted_iota(jnp.int32, (1, total), 1)
    logits = jnp.where(t_idx < cur, raw * scale, NEG_INF)
    logits = jnp.where(t_idx == cur, qk * scale, logits)
    m = jnp.max(logits, axis=1, keepdims=True)
    w = jnp.exp(logits - m)     # masked lanes: exp(NEG_INF - m) -> 0
    l = jnp.sum(w, axis=1, keepdims=True)
    w_cur = jnp.sum(jnp.where(t_idx == cur, w, 0.0), axis=1,
                    keepdims=True)
    w_past = jnp.where(t_idx < cur, w, 0.0)
    acc = lax.dot_general(w_past.astype(vc_ref.dtype), vc_ref[0],
                          (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    acc = acc + w_cur * v.astype(jnp.float32)
    o_ref[...] = (acc / l).astype(o_ref.dtype)


def decode_step_supported(d_head: int, n_rep: int, dtype) -> bool:
    """Gate for the fused decode-step kernel: MHA only (the GQA grouped
    einsum keeps the un-repeated-cache structure the kernel doesn't
    model), lane-exact head dim, and a backend with a Mosaic lowering
    (CPU runs interpret mode so the same path is testable off-TPU).
    Callers pad the cache length to ``decode_step_cache_len`` — the
    sublane rule lives there, not here."""
    if n_rep != 1 or d_head % 128 or d_head < 128:
        return False
    return jax.default_backend() in ("tpu", "cpu")


def decode_step_cache_len(total: int, dtype, lane: bool = False) -> int:
    """Cache columns the fused step's block wants: ``total`` rounded up
    to the dtype's sublane multiple (the (total, dh) cache block's
    second-minor dim). The pad columns are dead — the kernel's
    ``t <= cur`` mask never reaches them. ``lane=True`` rounds to the
    128-lane multiple instead: the int8 step's per-column scale rows
    ``(rows, total)`` put the column axis on the LANE dim, so the int8
    cache pads to the stricter of the two (128 covers int8's 32-row
    sublane too)."""
    from icikit.ops.pallas_common import sublane
    sub = 128 if lane else sublane(dtype)
    return (total + sub - 1) // sub * sub


def decode_step_attention(q, k, v, kcache, vcache, cur, cos, sin, *,
                          scale: float, rope: bool,
                          interpret: bool | None = None):
    """Fused single-token decode attention step (MHA).

    Args:
      q, k, v: this step's projections, ``(rows, dh)`` with
        ``rows = b * h`` (heads flattened into the grid).
      kcache, vcache: ``(rows, total, dh)`` padded caches; returned
        updated **in place** (buffers are donated via
        ``input_output_aliases``; only the written column moves).
      cur: traced scalar — the column to write / last visible position.
      cos, sin: duplicated RoPE tables ``(1, dh)`` fp32 (ignored when
        ``rope=False`` but must be passed for a stable operand list).
      scale: logit scale.

    Returns ``(attn (rows, dh), kcache', vcache')``.

    Collapses RoPE-apply + cache column write + masked flash-decode
    read into one launch per layer — the fused-single-token arm of the
    multi-token decode study (DECODE.md "Multi-token decode"). Callers
    must check ``decode_step_supported`` first.
    """
    rows, dh = q.shape
    total = kcache.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    idx = jnp.asarray(cur, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rows,),
        in_specs=[
            pl.BlockSpec((1, dh), lambda g, i: (g, 0)),       # q
            pl.BlockSpec((1, dh), lambda g, i: (g, 0)),       # k
            pl.BlockSpec((1, dh), lambda g, i: (g, 0)),       # v
            pl.BlockSpec((1, dh), lambda g, i: (0, 0)),       # cos
            pl.BlockSpec((1, dh), lambda g, i: (0, 0)),       # sin
            pl.BlockSpec((1, total, dh), lambda g, i: (g, 0, 0)),  # kc
            pl.BlockSpec((1, total, dh), lambda g, i: (g, 0, 0)),  # vc
        ],
        out_specs=[
            pl.BlockSpec((1, dh), lambda g, i: (g, 0)),       # attn
            # one-row cache write-back, addressed by the prefetched cur
            pl.BlockSpec((1, 1, dh), lambda g, i: (g, i[0], 0)),
            pl.BlockSpec((1, 1, dh), lambda g, i: (g, i[0], 0)),
        ],
    )
    attn, kc, vc = pl.pallas_call(
        partial(_decode_step_kernel, scale=float(scale), rope=bool(rope),
                total=total, dh=dh),
        grid_spec=grid_spec,
        out_shape=[
            _out_struct((rows, dh), q.dtype, q, k, v, kcache, vcache),
            _out_struct(kcache.shape, kcache.dtype, q, k, v, kcache,
                        vcache),
            _out_struct(vcache.shape, vcache.dtype, q, k, v, kcache,
                        vcache),
        ],
        input_output_aliases={6: 1, 7: 2},   # donate both caches
        interpret=interpret,
    )(idx, q, k, v, cos, sin, kcache, vcache)
    return attn, kc, vc


def _decode_step_q8_kernel(cur_ref, q_ref, kq_ref, vq_ref, kdq_ref,
                           vdq_ref, kc_ref, vc_ref, ksc_ref, vsc_ref,
                           o_ref, ko_ref, vo_ref, *, scale, total, dh):
    """int8-KV row of the fused decode step: the caches arrive (and
    stay) int8; the dequant FOLDS — K's per-column scale multiplies the
    logit row after the int8 dot, V's folds into the attention weights
    before the value dot — so no high-precision copy of the cache is
    ever formed, in VMEM or HBM. The fresh column arrives pre-quantized
    (``kq``/``vq``; rope + round happen on the tiny (rows, dh)
    projection outside — the scale is a per-row scalar whose (1, 1)
    write-back Mosaic's lane tiling cannot express, so the scale ROW
    update is one dus outside the launch) together with its dequantized
    value (``kdq``/``vdq``) for the ``t == cur`` patch."""
    cur = cur_ref[0]
    q = q_ref[...].astype(jnp.float32)               # (1, dh)
    kdq = kdq_ref[...].astype(jnp.float32)
    vdq = vdq_ref[...].astype(jnp.float32)
    ko_ref[0] = kq_ref[...].astype(ko_ref.dtype)     # int8 column write
    vo_ref[0] = vq_ref[...].astype(vo_ref.dtype)
    kc = kc_ref[0]                                   # (total, dh) int8
    ksc = ksc_ref[...]                               # (1, total) fp32
    vsc = vsc_ref[...]
    raw = lax.dot_general(q, kc.astype(jnp.float32),
                          (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)   # (1, T)
    raw = raw * ksc                                  # folded K dequant
    qk = lax.dot_general(q, kdq, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)    # (1, 1)
    t_idx = lax.broadcasted_iota(jnp.int32, (1, total), 1)
    logits = jnp.where(t_idx < cur, raw * scale, NEG_INF)
    logits = jnp.where(t_idx == cur, qk * scale, logits)
    m = jnp.max(logits, axis=1, keepdims=True)
    w = jnp.exp(logits - m)
    l = jnp.sum(w, axis=1, keepdims=True)
    w_cur = jnp.sum(jnp.where(t_idx == cur, w, 0.0), axis=1,
                    keepdims=True)
    w_past = jnp.where(t_idx < cur, w, 0.0) * vsc    # folded V dequant
    acc = lax.dot_general(w_past, vc_ref[0].astype(jnp.float32),
                          (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    acc = acc + w_cur * vdq
    o_ref[...] = (acc / l).astype(o_ref.dtype)


def decode_step_attention_q8(q, kq, vq, kdq, vdq, kcache, vcache,
                             kscale, vscale, cur, *, scale: float,
                             interpret: bool | None = None):
    """Fused single-token decode step over INT8 KV caches (MHA).

    Args:
      q: this step's (already rope-rotated) queries, ``(rows, dh)``.
      kq, vq: the fresh K/V column, quantized ``(rows, dh)`` int8.
      kdq, vdq: the same column dequantized ``(rows, dh)`` fp32 (the
        ``t == cur`` logit/value patch — the kernel's input cache block
        is stale at the written column, exactly as in the fp kernel).
      kcache, vcache: ``(rows, total, dh)`` int8 caches, donated and
        returned updated in place (one int8 row moves per step).
      kscale, vscale: ``(rows, total)`` fp32 per-column scales, ALREADY
        holding the fresh column's scale at ``cur`` (the caller's dus;
        the kernel reads only the ``t < cur`` lanes).
      cur: traced scalar — the column to write / last visible position.

    Returns ``(attn (rows, dh) fp32, kcache', vcache')``. Callers must
    check ``decode_step_supported`` first and pad ``total`` with
    ``decode_step_cache_len(..., lane=True)`` (the scale rows put the
    column axis on the lane dim).
    """
    rows, dh = q.shape
    total = kcache.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    idx = jnp.asarray(cur, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rows,),
        in_specs=[
            pl.BlockSpec((1, dh), lambda g, i: (g, 0)),        # q
            pl.BlockSpec((1, dh), lambda g, i: (g, 0)),        # kq
            pl.BlockSpec((1, dh), lambda g, i: (g, 0)),        # vq
            pl.BlockSpec((1, dh), lambda g, i: (g, 0)),        # kdq
            pl.BlockSpec((1, dh), lambda g, i: (g, 0)),        # vdq
            pl.BlockSpec((1, total, dh), lambda g, i: (g, 0, 0)),  # kc
            pl.BlockSpec((1, total, dh), lambda g, i: (g, 0, 0)),  # vc
            pl.BlockSpec((1, total), lambda g, i: (g, 0)),     # kscale
            pl.BlockSpec((1, total), lambda g, i: (g, 0)),     # vscale
        ],
        out_specs=[
            pl.BlockSpec((1, dh), lambda g, i: (g, 0)),        # attn
            pl.BlockSpec((1, 1, dh), lambda g, i: (g, i[0], 0)),
            pl.BlockSpec((1, 1, dh), lambda g, i: (g, i[0], 0)),
        ],
    )
    attn, kc, vc = pl.pallas_call(
        partial(_decode_step_q8_kernel, scale=float(scale),
                total=total, dh=dh),
        grid_spec=grid_spec,
        out_shape=[
            _out_struct((rows, dh), jnp.float32, q, kcache, vcache),
            _out_struct(kcache.shape, kcache.dtype, q, kcache, vcache),
            _out_struct(vcache.shape, vcache.dtype, q, kcache, vcache),
        ],
        input_output_aliases={6: 1, 7: 2},   # donate both int8 caches
        interpret=interpret,
    )(idx, q, kq, vq, kdq, vdq, kcache, vcache, kscale, vscale)
    return attn, kc, vc
