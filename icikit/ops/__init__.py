"""Local compute kernels (the per-core work under the distributed sorts).

The reference's per-rank hot kernels are ``std::sort`` and the linear
compare-split merge (``Parallel-Sorting/src/psort.cc:116-164``). Here the
local sort is XLA's sort and the merge is a Batcher bitonic-merge
network (``icikit.ops.merge``) — O(n log n) vectorized min/max stages
that map straight onto the TPU VPU, with an optional Pallas kernel.
"""

from icikit.ops.pallas_sort import local_sort, merge_bitonic as merge_bitonic_pallas  # noqa: F401
from icikit.ops.merge import (  # noqa: F401
    bitonic_merge,
    compare_split_max,
    compare_split_min,
)
