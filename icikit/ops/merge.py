"""Bitonic merge network and compare-split halves.

The reference's compare-split (``Parallel-Sorting/src/psort.cc:116-164``)
exchanges full buffers then does a *linear merge from one end*, keeping
exactly ``loc_size`` elements (max variant merges tail-down ``:127-137``,
min variant head-up ``:152-162``). A sequential two-pointer merge is
hostile to a vector unit, so the TPU design uses Batcher's classic
identity instead: for ascending sorted ``a`` and ``b``,

    L = min(a, reverse(b)),  H = max(a, reverse(b))

are each *bitonic*, every element of L <= every element of H, and
{L, H} = the n smallest / n largest of the 2n inputs. One elementwise
min/max pass replaces the merge decision, and a log2(n)-stage bitonic
merge network (pure min/max on strided halves — VPU-shaped work) turns
the kept half back into sorted order.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from icikit.utils.mesh import is_pow2


def bitonic_merge(v: jax.Array, backend: str = "auto") -> jax.Array:
    """Sort a *bitonic* vector ascending via Batcher's merge network.

    log2(n) stages of elementwise min/max over halves; requires
    power-of-2 length (callers pad — see ``models.sort.common``).
    Falls back to ``jnp.sort`` for non-power-of-2 lengths. On TPU,
    large merges dispatch to the fused Pallas network
    (``icikit.ops.pallas_sort.merge_bitonic``), which runs the whole
    stage cascade in VMEM instead of one HBM pass per stage.
    """
    n = v.shape[0]
    if not is_pow2(n):
        return jnp.sort(v)
    from icikit.ops.pallas_sort import _resolve_backend, merge_bitonic
    resolved = _resolve_backend(backend, v.dtype, n)
    if resolved in ("pallas", "interpret"):
        return merge_bitonic(v, backend=resolved)
    k = n // 2
    while k >= 1:
        w = v.reshape(-1, 2, k)
        lo = jnp.minimum(w[:, 0], w[:, 1])
        hi = jnp.maximum(w[:, 0], w[:, 1])
        v = jnp.concatenate([lo[:, None], hi[:, None]], axis=1).reshape(-1)
        k //= 2
    return v


def compare_split_min(a: jax.Array, b: jax.Array) -> jax.Array:
    """The n smallest of sorted ``a`` + sorted ``b``, sorted ascending
    (reference ``compare_split_min``, ``psort.cc:142-164``)."""
    return bitonic_merge(jnp.minimum(a, b[::-1]))


def compare_split_max(a: jax.Array, b: jax.Array) -> jax.Array:
    """The n largest of sorted ``a`` + sorted ``b``, sorted ascending
    (reference ``compare_split_max``, ``psort.cc:116-140``)."""
    return bitonic_merge(jnp.maximum(a, b[::-1]))
