"""Fused one-pass Adam: the optimizer tail at its HBM floor.

The train step's optimizer tail is pure memory traffic — every param
leaf's master (fp32), both moments (fp32) and gradient must cross HBM
once. The optax pipeline costs more than that floor two ways: the
gradient is materialized as an fp32 copy before ``update`` (the
moments must accumulate from fp32 — ``make_train_step`` casts), and
``scale_by_adam`` + ``apply_updates`` emit separate fusions whose
intermediate (the update tree) makes an extra HBM round trip. This
kernel does the whole update in one pass per leaf: read p, m, v, g
(g in its stored dtype, upcast in-register — bf16→fp32 is exact, so
the numerics match optax's cast-then-update exactly), write p', m',
v'. Nothing else touches HBM: 28 B/element for fp32 grads, 26 B for
bf16 — the floor.

Semantics are ``optax.adam`` (scale_by_adam with eps_root=0)::

    m' = b1·m + (1−b1)·g
    v' = b2·v + (1−b2)·g²
    p' = p − lr · (m'/(1−b1^t)) / (sqrt(v'/(1−b2^t)) + eps)

with the bias corrections computed outside the kernel as traced
scalars and shipped through SMEM (they change every step; baking them
in would retrace).

Reference lineage: the reference has no optimizer (it is an MPI
algorithms suite, SURVEY.md §Scale note); this is framework
infrastructure the match-or-beat mandate requires of the flagship
train step. Tested against optax.adam bit-for-bit-close in
``tests/test_optim.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from icikit.ops.pallas_common import out_struct, sublane as _sublane

# Rows per grid step; (1024, 128) fp32 blocks are 512 KiB — seven live
# buffers (4 in, 3 out) double-buffered stay well inside VMEM.
_BLOCK_ROWS = 1024
_LANES = 128


def _adam_kernel(sc_ref, p_ref, m_ref, v_ref, g_ref,
                 po_ref, mo_ref, vo_ref, *, b1: float, b2: float,
                 eps: float):
    """One block: full Adam update, no HBM intermediates."""
    lr = sc_ref[0]
    c1 = sc_ref[1]  # 1/(1-b1^t)
    c2 = sc_ref[2]  # 1/(1-b2^t)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32) * b1 + g * (1.0 - b1)
    v = v_ref[...].astype(jnp.float32) * b2 + (g * g) * (1.0 - b2)
    mo_ref[...] = m.astype(mo_ref.dtype)
    vo_ref[...] = v.astype(vo_ref.dtype)
    po_ref[...] = p_ref[...] - lr * (m * c1) / (
        jnp.sqrt(v * c2) + eps)


def _leaf_update_pallas(p, m, v, g, scalars, b1, b2, eps, interpret):
    rows = p.size // _LANES
    br = min(_BLOCK_ROWS, rows)
    shape2 = (rows, _LANES)
    spec = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    po, mo, vo = pl.pallas_call(
        partial(_adam_kernel, b1=b1, b2=b2, eps=eps),
        grid=(pl.cdiv(rows, br),),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            spec, spec, spec, spec,
        ],
        out_specs=[spec, spec, spec],
        out_shape=[
            out_struct(shape2, jnp.float32, p, m, v, g),
            out_struct(shape2, m.dtype, p, m, v, g),
            out_struct(shape2, v.dtype, p, m, v, g),
        ],
        interpret=interpret,
    )(scalars, p.reshape(shape2), m.reshape(shape2),
      v.reshape(shape2), g.reshape(shape2))
    return (po.reshape(p.shape), mo.reshape(p.shape),
            vo.reshape(p.shape))


def _leaf_update_xla(p, m, v, g, scalars, b1, b2, eps):
    """Fallback for leaves the (rows, 128) view can't express and for
    backends without Mosaic — XLA fuses the elementwise chain; only
    the update-tree round trip is saved (the math is identical).

    Moments may be stored narrow (r5 structural route: bf16 second
    moments halve the nu stream): they are upcast in-register, the
    update arithmetic is always fp32, and the new moment is rounded
    once on the store — the only precision loss is the storage
    rounding itself."""
    lr, c1, c2 = scalars[0], scalars[1], scalars[2]
    g = g.astype(jnp.float32)
    m32 = m.astype(jnp.float32) * b1 + g * (1.0 - b1)
    v32 = v.astype(jnp.float32) * b2 + (g * g) * (1.0 - b2)
    p = p - lr * (m32 * c1) / (jnp.sqrt(v32 * c2) + eps)
    return p, m32.astype(m.dtype), v32.astype(v.dtype)


def _use_pallas(p, m, v, g) -> bool:
    """Whether the Pallas path covers this leaf. Every operand rides
    the same (rows, 128) view, so the row count must satisfy the
    STRICTEST operand's sublane rule — bf16 moments (r5) need
    rows % 16 == 0 where fp32-everything needed 8. Narrow/odd-row
    leaves fall back to the XLA formulation (identical math), instead
    of handing Mosaic a block its tiling cannot express."""
    if jax.default_backend() not in ("tpu", "cpu"):
        return False
    if p.size % _LANES:
        return False
    rows = p.size // _LANES
    sub = max(_sublane(x.dtype) for x in (p, m, v, g))
    return rows >= 8 and rows % sub == 0


def adam_scalars(lr, step, b1: float = 0.9, b2: float = 0.999):
    """(3,) fp32 SMEM payload: [lr, 1/(1−b1^t), 1/(1−b2^t)] for a
    traced step count ``step`` (1-based, optax's count_inc)."""
    t = step.astype(jnp.float32) if hasattr(step, "astype") else float(step)
    c1 = 1.0 / (1.0 - jnp.power(jnp.float32(b1), t))
    c2 = 1.0 / (1.0 - jnp.power(jnp.float32(b2), t))
    return jnp.stack([jnp.asarray(lr, jnp.float32), c1, c2])


def adam_apply(params: dict, m: dict, v: dict, grads: dict, lr, step,
               b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
               use_pallas: bool = False):
    """Whole-tree fused Adam. ``lr``/``step`` may be traced scalars.

    Returns ``(params', m', v')``. Call on local shards (inside
    shard_map) or on a single device — the update is elementwise, so
    sharding composes trivially.

    ``use_pallas=False`` (default) emits the one-pass update as plain
    XLA. This is a *measured* choice, not a hedge
    (``icikit.bench.adam`` + the step-level A/B in ``bench.train``):

    - Standalone, both forms stream near the HBM floor (pallas 89%,
      XLA 95% of measured bandwidth at 211M params, 26 B/element).
    - Inside the full train step the Pallas path pins default
      row-major layouts on every operand and XLA inserts
      layout-conversion copies for every leaf whose steady-state
      layout is matmul-optimized — measured +15 ms/step at the base
      preset (100.3 vs 85.4 ms), swamping any tail saving. The XLA
      form is layout-agnostic, and the profile shows XLA already runs
      every per-leaf update fusion at the HBM floor (and fuses the
      update directly into the dw matmul for non-scan-stacked
      leaves).
    - Donating p/m/v aliases the kernel's inputs to its outputs, and
      the in-place hazard serializes Mosaic's block DMA pipeline:
      266-451 GB/s aliased vs 664 fresh. The step's chained-loop
      carry is donated, which would put the kernel on its slow path.
    """
    interpret = jax.default_backend() == "cpu"
    scalars = adam_scalars(lr, step, b1, b2)
    new_p, new_m, new_v = {}, {}, {}
    for k in params:
        p, mm, vv, g = params[k], m[k], v[k], grads[k]
        if not jnp.issubdtype(p.dtype, jnp.floating):
            new_p[k], new_m[k], new_v[k] = p, mm, vv
            continue
        if use_pallas and _use_pallas(p, mm, vv, g):
            new_p[k], new_m[k], new_v[k] = _leaf_update_pallas(
                p, mm, vv, g, scalars, b1, b2, eps, interpret)
        else:
            new_p[k], new_m[k], new_v[k] = _leaf_update_xla(
                p, mm, vv, g, scalars, b1, b2, eps)
    return new_p, new_m, new_v
