"""Single-device multi-head attention — the oracle the sequence-parallel
schedules are verified against (the role the reference's closed-form
payload expectations play for its collectives, ``main.cc:436-441``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = float(jnp.finfo(jnp.float32).min)


def masked_logits(q: jax.Array, k: jax.Array, causal: bool,
                  scale: float | None, fill: float = NEG_INF) -> jax.Array:
    """fp32 ``(b, h, s_q, s_kv)`` attention logits with the causal mask
    applied (end-aligned convention); shared by the dense softmax path
    and the explicit-logsumexp path (``fill=-inf`` there, so empty rows
    read as lse = -inf rather than a finite floor)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # Inputs' dtype on the MXU, fp32 accumulation/softmax (bf16 inputs
    # take the fast path; fp32 inputs match the always-upcast result).
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s_q, s_kv = q.shape[1], k.shape[1]
        q_pos = jnp.arange(s_q)[:, None] + (s_kv - s_q)
        k_pos = jnp.arange(s_kv)[None, :]
        logits = jnp.where(q_pos >= k_pos, logits, fill)
    return logits


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    scale: float | None = None) -> jax.Array:
    """Scaled dot-product attention, softmax in float32.

    Args:
      q: queries ``(batch, s_q, heads, head_dim)``.
      k, v: keys/values ``(batch, s_kv, heads, head_dim)``.
      causal: mask position i from attending to positions > i (query and
        key positions aligned at the sequence end, standard decoder
        convention; here ``s_q == s_kv`` is assumed by the callers).
      scale: logit scale, default ``head_dim ** -0.5``.

    Returns:
      ``(batch, s_q, heads, head_dim)`` in q's dtype.
    """
    logits = masked_logits(q, k, causal, scale)
    w = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
