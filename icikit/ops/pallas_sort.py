"""Pallas TPU sorting kernels: HBM-pass-minimizing bitonic networks.

Why this exists: XLA lowers ``jnp.sort`` on TPU to a sorting network that
streams the whole array through HBM roughly once per compare-exchange
stage — O(log^2 n) full-array HBM passes. For 2^27 int32 keys that is
~378 passes (~380 GB of traffic), which makes the sort HBM-bound. The
kernels here run every stage whose stride fits in a VMEM tile *inside*
the tile, so the array only crosses HBM once per *group* of stages:

- ``_net_call``   — grid over VMEM tiles; all stages with stride < tile
  size execute back-to-back on-chip. Sub-lane strides (>= 128) pair
  partners with a lane-preserving reshape; lane strides (< 128) pair
  them with two ``pltpu.roll`` lane rotations (no cross-lane reshape,
  which Mosaic restricts).
- ``_cross_call`` — stages with stride >= tile size. Viewing the array
  as a (blocks, Q, tile) matrix turns *all* such stages of one merge
  round into min/max along bit-axes of the Q dimension, so one kernel
  pass covers the whole round's cross-tile stages; columns are
  independent, so the grid tiles them.

Total: ~2 HBM passes per merge round instead of one per stage — for
2^27 keys, ~16 passes instead of ~378. The compare network itself is
the reference's algorithm family: ``parallel_bitonic_sort``
(``Parallel-Sorting/src/psort.cc:167-201``) run *within* a chip instead
of across ranks.

Direction handling (the round-3 redesign): the reference keeps
per-stage direction tests (``ibit``/``jbit`` rank parity,
``psort.cc:184-195``); a literal translation spends 1-2 vector selects
per element per stage on them, and measurement shows directed stages
cost 2-3x a plain min/max merge stage on the VPU. Instead, every stage
here is a *plain ascending* compare-exchange, and direction is applied
by conditionally order-reversing the descending spans at round
boundaries: two's-complement NOT reverses int32/uint32 order and
arithmetic negation reverses float32 order, so
``directed-CE(a, b, desc)  ==  undo(plain-CE(flip(a), flip(b)))``.
The flip masks are iota-derived constants (or a scalar from the grid
index), consecutive rounds fuse into a single combined mask, and the
whole direction apparatus costs one cheap VPU op per round boundary
instead of 1-2 selects per stage.

int32/float32 take the Pallas path natively (TPU widths); uint32 rides
the int32 kernel through the order-preserving bijection
``bitcast_i32(u ^ 0x80000000)`` (Mosaic has no unsigned vector min/max
— ``arith.minui`` fails to legalize, so a direct uint32 kernel cannot
compile); bf16/f16 ride the f32 kernel by exact monotone widening;
other dtypes and small arrays fall back to ``jnp.sort``. NaN ordering in the
float Pallas paths (f32 native and the half-precision widening)
follows min/max semantics, not ``jnp.sort``'s NaN-last contract —
callers with NaNs should pass ``backend='xla'``. (-0.0 vs 0.0 compare
equal under min/max, so their relative order is arbitrary, as before.)
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from icikit.utils.mesh import ilog2 as _ilog2
from icikit.utils.mesh import is_pow2 as _is_pow2

LANES = 128

# Default tile geometry (elements, power of 2). T_GRID is the VMEM tile
# for gridded passes; T_BIG is the largest single-tile kernel we allow —
# rounds whose whole span fits run in one pass. Mosaic compile time
# grows superlinearly with the number of fused stages per kernel
# (measured: 91 stages 1.5 s, 120 stages 11 s, 153 stages 269 s), and
# throughput grows with tile size (v5e, 2^27 int32 keys: t_grid 2^13 ->
# 362 M keys/s, 2^14 -> 460 M, 2^15 -> 514 M, 2^16 -> 525 M but ~60 s
# compile), so the defaults take the knee of that curve: 120-stage
# phase-1 kernels (~11 s compile, amortized by the lru_cache). G_MAX
# bounds how many Q-axis bits one cross pass covers (VMEM block is
# 2^g * cb elements); 12 and t_big 2^18 overflow the v5e compiler.
T_GRID = 1 << 15
T_BIG = 1 << 17
G_MAX = 11

# Below this size the fixed overhead of a pallas_call loses to jnp.sort.
MIN_PALLAS = 1 << 13

_PALLAS_DTYPES = (jnp.int32, jnp.uint32, jnp.float32)


def pallas_supported(dtype, n: int) -> bool:
    return any(jnp.dtype(dtype) == d for d in _PALLAS_DTYPES) and n >= MIN_PALLAS


def _u32_as_i32(x):
    """Order-preserving bijection uint32 -> int32 (Mosaic has no
    unsigned vector min/max, so the kernels sort the signed image)."""
    return lax.bitcast_convert_type(x ^ jnp.uint32(0x80000000), jnp.int32)


def _i32_as_u32(x):
    """Inverse of :func:`_u32_as_i32`."""
    return lax.bitcast_convert_type(x, jnp.uint32) ^ jnp.uint32(0x80000000)


# ---------------------------------------------------------------------------
# In-kernel compare-exchange. All operate on a VMEM-resident value of
# shape (S, LANES) holding tile elements row-major: e = s*LANES + c.
# Every stage is a plain ascending compare-exchange with partner e ^ k;
# direction is handled by the flip masks below, never inside a stage.


def _plain_lane(x, k: int):
    """Stride < 128: partners sit k lanes apart. Two lane rotations give
    both neighbours; min-with-forward at low lanes, max-with-backward at
    high lanes (the wrapped values land only on lanes that don't select
    them)."""
    c = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    is_lo = (c & k) == 0
    fwd = pltpu.roll(x, LANES - k, 1)  # value at lane c + k
    bwd = pltpu.roll(x, k, 1)          # value at lane c - k
    return jnp.where(is_lo, jnp.minimum(x, fwd), jnp.maximum(x, bwd))


def _plain_sublane(x, k: int):
    """Stride >= 128: partners sit k/128 rows apart; pair via a
    lane-preserving leading-dim reshape (no data movement)."""
    s_rows = x.shape[0]
    kk = k // LANES
    g = s_rows // (2 * kk)
    y = x.reshape(g, 2, kk, LANES)
    a, b = y[:, 0], y[:, 1]
    return jnp.stack([jnp.minimum(a, b), jnp.maximum(a, b)],
                     axis=1).reshape(s_rows, LANES)


def _plain_stage(x, k: int):
    return _plain_lane(x, k) if k < LANES else _plain_sublane(x, k)


# ---------------------------------------------------------------------------
# Direction flips. ``_dir_bit`` returns the 0/1 "descending" indicator
# for direction bit ``db`` of the global element index — a lane iota
# (db < 7), a sublane iota (7 <= db < log2t), or a traced scalar from
# the grid index (db >= log2t). ``_apply_flip`` order-reverses the
# elements where the bit is 1: bitwise NOT for ints, negation for
# floats — both exact, involutive, and one VPU op.


def _dir_bit(db, s_rows: int, log2t: int, pid):
    if db is None:
        return None
    if db >= log2t:
        return (pid >> (db - log2t)) & 1  # scalar, traced
    if db < 7:
        c = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        return (c >> db) & 1
    s = lax.broadcasted_iota(jnp.int32, (s_rows, 1), 0)
    return (s >> (db - 7)) & 1


def _xor_bits(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a ^ b


def _apply_flip(x, bit):
    """Order-reverse x where bit == 1 (bit: 0/1 int32, scalar or
    broadcastable to x's shape)."""
    if bit is None:
        return x
    if x.dtype == jnp.float32:
        return x * (1 - 2 * bit).astype(jnp.float32)
    return x ^ (-bit).astype(x.dtype)


# ---------------------------------------------------------------------------
# Kernel builders. ``rounds`` is a tuple of (db, strides): all stages of
# one entry run as plain ascending merges under the direction flip of
# bit ``db`` (None = already ascending). Consecutive entries fuse their
# un-flip/re-flip into one combined mask.


def _net_call(x2d, tile: int, rounds, *, interpret: bool):
    """Gridded pass: each grid step loads one tile of `tile` elements
    as (tile/128, 128) into VMEM and runs every round in `rounds`."""
    rows_total, s_rows = x2d.shape[0], tile // LANES
    log2t = _ilog2(tile)
    rounds = tuple((db, tuple(strides)) for db, strides in rounds)

    def kernel(x_ref, o_ref):
        pid = pl.program_id(0)
        x = x_ref[:]
        prev = None
        for db, strides in rounds:
            cur = _dir_bit(db, s_rows, log2t, pid)
            x = _apply_flip(x, _xor_bits(prev, cur))
            prev = cur
            for k in strides:
                x = _plain_stage(x, k)
        o_ref[:] = _apply_flip(x, prev)

    return pl.pallas_call(
        kernel,
        grid=(rows_total // s_rows,),
        in_specs=[pl.BlockSpec((s_rows, LANES), lambda g: (g, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((s_rows, LANES), lambda g: (g, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=interpret,
    )(x2d)


def _cross_call(x, span: int, tile: int, lo_bit: int, hi_bit: int, *,
                merge_only: bool, interpret: bool):
    """Cross-tile stages of one round whose Q-axis bit sits in
    [lo_bit, hi_bit], in one pass.

    View the array as (n/span, A, G, B·tile) with Q = span/tile =
    A*G*B, G = 2^(hi-lo+1) covering the target bits, B = 2^lo_bit the
    bits below. A stage of stride 2^j (j-log2(tile) in [lo,hi]) is a
    min/max along the matching bit of the G axis. Everything else is
    independent, so (n/span, A, B, columns) fold into the grid; the
    VMEM block is (G, cb) — the B/column position selects a cb-wide
    slice of the fused trailing axis (cb divides tile, so a block never
    straddles a B boundary; keeping G as a full middle axis also
    satisfies Mosaic's block-shape divisibility rule, which a
    (..., 1, cb) block over a B-sized axis would not). The round's
    direction (span-index parity) is applied as a whole-block flip —
    pairing is xor-symmetric, so flipping the block, merging ascending
    and unflipping equals the directed stages."""
    n = x.shape[0]
    q = span // tile
    nb = n // span
    g = 1 << (hi_bit - lo_bit + 1)
    b_lo = 1 << lo_bit
    a_hi = q // (g * b_lo)
    cb = max(LANES, min(tile, (1 << 17) // g))
    dists = [1 << d for d in range(hi_bit - lo_bit, -1, -1)]
    fold = a_hi * b_lo  # A and B grid positions folded with NB

    def kernel(x_ref, o_ref):
        if merge_only:
            desc = None
        else:
            desc = (pl.program_id(0) // fold) & 1
        v = x_ref[0, 0, :, :]  # (G, cb)
        v = _apply_flip(v, desc)
        for d in dists:
            y = v.reshape(g // (2 * d), 2, d, cb)
            p, r = y[:, 0], y[:, 1]
            v = jnp.stack([jnp.minimum(p, r), jnp.maximum(p, r)],
                          axis=1).reshape(g, cb)
        o_ref[0, 0, :, :] = _apply_flip(v, desc)

    def idx(f, c):
        blk = f // fold
        a = (f // b_lo) % a_hi
        bb = f % b_lo
        return (blk, a, 0, bb * (tile // cb) + c)

    x4 = x.reshape(nb, a_hi, g, b_lo * tile)
    out = pl.pallas_call(
        kernel,
        grid=(nb * fold, tile // cb),
        in_specs=[pl.BlockSpec((1, 1, g, cb), idx,
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, 1, g, cb), idx,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(x4.shape, x4.dtype),
        interpret=interpret,
    )(x4)
    return out.reshape(n)


def _sort_rounds(log2n: int):
    """Every round of a full bitonic sort of 2^log2n elements:
    round i has strides 2^i..1, direction bit i+1 (psort.cc:184-195)."""
    return [(i + 1, tuple(1 << j for j in range(i, -1, -1)))
            for i in range(log2n)]


def _one_round(i: int, lo_stride: int = 1):
    """Merge round i with strides >= lo_stride, direction bit i+1."""
    return [(i + 1, tuple(1 << j
                          for j in range(i, _ilog2(lo_stride) - 1, -1)))]


def _merge_rounds(hi_stride: int, lo_stride: int = 1):
    """Ascending-everywhere merge (for merging a bitonic input)."""
    return [(None, tuple(1 << j
                         for j in range(_ilog2(hi_stride),
                                        _ilog2(lo_stride) - 1, -1)))]


# ---------------------------------------------------------------------------
# Drivers (built per shape, cached).


@lru_cache(maxsize=None)
def _build_sort(n: int, dtype_name: str, t_grid: int, t_big: int,
                g_max: int, interpret: bool):
    log2n = _ilog2(n)

    def run(x):
        x2d = x.reshape(n // LANES, LANES)
        # Single-tile full-sort only up to t_grid: the full network has
        # log2n*(log2n+1)/2 stages, and past ~120 stages Mosaic compile
        # time explodes (see the tile-geometry comment above). Larger n
        # always takes the phased path, whose per-kernel stage counts
        # stay at phase-1's _sort_rounds(log2 t_grid) or a round's
        # <= log2n. t_big only bounds the *span* a merge round may run
        # as one cheap gridded kernel.
        if n <= t_grid:
            return _net_call(x2d, n, _sort_rounds(log2n),
                             interpret=interpret).reshape(n)
        # Phase 1: sort each t_grid tile (rounds 0..log2(t_grid)-1),
        # alternating direction by tile parity.
        x2d = _net_call(x2d, t_grid, _sort_rounds(_ilog2(t_grid)),
                        interpret=interpret)
        x = x2d.reshape(n)
        # Phase 2: one merge round per remaining level.
        for i in range(_ilog2(t_grid), log2n):
            span = 1 << (i + 1)
            if span <= t_big:
                x = _net_call(x.reshape(n // LANES, LANES), span,
                              _one_round(i), interpret=interpret
                              ).reshape(n)
            else:
                hi = i - _ilog2(t_grid)
                while hi >= 0:
                    lo = max(0, hi - g_max + 1)
                    x = _cross_call(x, span, t_grid, lo, hi,
                                    merge_only=False, interpret=interpret)
                    hi = lo - 1
                intra = [(i + 1, tuple(1 << j
                                       for j in range(_ilog2(t_grid) - 1,
                                                      -1, -1)))]
                x = _net_call(x.reshape(n // LANES, LANES), t_grid,
                              intra, interpret=interpret).reshape(n)
        return x

    return jax.jit(run)


@lru_cache(maxsize=None)
def _build_merge(n: int, dtype_name: str, t_grid: int, t_big: int,
                 g_max: int, interpret: bool):
    def run(v):
        if n <= t_big:
            return _net_call(v.reshape(n // LANES, LANES), n,
                             _merge_rounds(n // 2), interpret=interpret
                             ).reshape(n)
        hi = _ilog2(n // t_grid) - 1
        while hi >= 0:
            lo = max(0, hi - g_max + 1)
            v = _cross_call(v, n, t_grid, lo, hi, merge_only=True,
                            interpret=interpret)
            hi = lo - 1
        return _net_call(v.reshape(n // LANES, LANES), t_grid,
                         _merge_rounds(t_grid // 2), interpret=interpret
                         ).reshape(n)

    return jax.jit(run)


def _resolve_backend(backend: str, dtype, n: int) -> str:
    if backend != "auto":
        return backend
    if os.environ.get("ICIKIT_PALLAS", "") == "interpret":
        return "interpret" if pallas_supported(dtype, n) else "xla"
    if jax.default_backend() == "tpu" and pallas_supported(dtype, n):
        return "pallas"
    return "xla"


def local_sort(x: jax.Array, backend: str = "auto", *,
               t_grid: int = T_GRID, t_big: int = T_BIG,
               g_max: int | None = None) -> jax.Array:
    """Sort flat ``x`` ascending on one device.

    backend: 'auto' (Pallas on TPU for supported dtypes/sizes, else
    XLA), 'pallas', 'interpret' (Pallas interpreter — for CPU tests),
    or 'xla' (``jnp.sort``).
    """
    n = x.shape[0]
    # Half-precision floats ride the fp32 kernel when the Pallas path
    # is taken (bf16/f16 embed exactly in f32, monotonically — widen-
    # sort-narrow is exact, with the same NaN caveat as native f32);
    # the XLA path keeps jnp.sort's native bf16 handling (NaN-last).
    in_dtype = jnp.dtype(x.dtype)
    half = in_dtype in (jnp.bfloat16, jnp.float16)
    usgn = in_dtype == jnp.uint32
    kernel_dtype = (jnp.float32 if half
                    else jnp.int32 if usgn else in_dtype)
    backend = _resolve_backend(backend, kernel_dtype, n)
    if backend == "xla" or n < 2:
        return jnp.sort(x)
    if backend not in ("pallas", "interpret"):
        raise ValueError(f"unknown backend {backend!r}")
    if not pallas_supported(kernel_dtype, n):
        raise ValueError(
            f"pallas sort supports int32/uint32/float32 (bf16/f16 via "
            f"the f32 kernel) and n >= {MIN_PALLAS}; got {in_dtype} "
            f"n={n} (use backend='xla')")
    if half:
        x = x.astype(jnp.float32)
    if usgn:
        x = _u32_as_i32(x)
    interpret = backend == "interpret"
    np2 = n if _is_pow2(n) else 1 << n.bit_length()
    if np2 != n:
        from icikit.utils.dtypes import sentinel_for
        x = jnp.concatenate(
            [x, jnp.full((np2 - n,), sentinel_for(x.dtype), x.dtype)])
    out = _build_sort(np2, jnp.dtype(x.dtype).name, t_grid, t_big,
                      g_max or G_MAX, interpret)(x)
    out = out[:n] if np2 != n else out
    if usgn:
        return _i32_as_u32(out)
    return out.astype(in_dtype) if half else out


def merge_bitonic(v: jax.Array, backend: str = "auto", *,
                  t_grid: int = T_GRID, t_big: int = T_BIG,
                  g_max: int | None = None) -> jax.Array:
    """Sort a *bitonic* power-of-2 vector ascending (the reference's
    compare-split completion step, psort.cc:121-137, as one fused
    merge network)."""
    n = v.shape[0]
    backend = _resolve_backend(backend, v.dtype, n)
    if backend == "xla":
        from icikit.ops.merge import bitonic_merge
        return bitonic_merge(v, backend="xla")
    if backend not in ("pallas", "interpret"):
        raise ValueError(f"unknown backend {backend!r}")
    if not _is_pow2(n):
        raise ValueError("merge_bitonic requires power-of-2 length")
    if not pallas_supported(v.dtype, n):
        raise ValueError(
            f"pallas merge supports int32/uint32/float32 and n >= "
            f"{MIN_PALLAS}; got {v.dtype} n={n} (use backend='xla')")
    usgn = jnp.dtype(v.dtype) == jnp.uint32
    if usgn:
        v = _u32_as_i32(v)
    out = _build_merge(n, jnp.dtype(v.dtype).name, t_grid, t_big,
                       g_max or G_MAX, backend == "interpret")(v)
    return _i32_as_u32(out) if usgn else out
