"""Rotary position embeddings (RoPE, Su et al. 2021).

Split-half (NeoX) convention: the head dimension is viewed as d/2
complex pairs ``(x[..., :d/2], x[..., d/2:])`` and pair ``j`` at
position ``m`` is rotated by angle ``m · theta^(-2j/d)``. Rotation acts
on Q and K after projection, so attention logits depend only on
*relative* positions — which is what lets every parallel schedule
(ring over sp, pipeline stages, the decode cache) apply it locally with
its own global position indices and still agree globally.

Pure VPU elementwise work; XLA fuses it into the surrounding projection
matmuls, so no Pallas kernel is warranted.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_angles(positions: jax.Array, d: int,
                theta: float = 10000.0) -> jax.Array:
    """Angles ``(..., s, d/2)`` in fp32. ``positions`` is ``(s,)``
    (shared across the batch) or ``(b, s)`` (per-row positions — the
    speculative decode path, where rows accept different token counts
    and their windows sit at different offsets)."""
    if d % 2:
        raise ValueError(f"head dim must be even for RoPE, got {d}")
    inv = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    return positions.astype(jnp.float32)[..., :, None] * inv


def rope_sincos(positions: jax.Array, d: int, theta: float = 10000.0):
    """Precomputed ``(cos, sin)`` tables, each ``(s, d/2)`` fp32 (or
    ``(b, s, d/2)`` for per-row positions) — for callers that apply the
    same positions to many tensors (the decode loop applies one
    position across every layer; computing the angle chain per layer
    was pure serialized-fusion overhead at b=1)."""
    ang = rope_angles(positions, d, theta)
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float = 10000.0, sincos=None) -> jax.Array:
    """Rotate ``x (b, s, h, d)`` by its positions ``(s,)`` — or
    per-row ``(b, s)`` — keeping the dtype. ``sincos``: optional
    precomputed ``rope_sincos`` tables (positions is then ignored)."""
    d = x.shape[-1]
    if sincos is None:
        sincos = rope_sincos(positions, d, theta)
    if sincos[0].ndim == 3:            # per-row tables (b, s, d/2)
        cos = sincos[0][:, :, None, :]
        sin = sincos[1][:, :, None, :]
    else:                              # shared tables (s, d/2)
        cos = sincos[0][None, :, None, :]
        sin = sincos[1][None, :, None, :]
    x1 = x[..., :d // 2].astype(jnp.float32)
    x2 = x[..., d // 2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)
