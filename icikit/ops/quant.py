"""Per-channel symmetric int8 quantization + the int8 matvec kernel.

Why this exists: the r7 decode cost model (DECODE.md, `bench.decode
spec_cost_model`) proved b=1 decode is BYTES-dominated — 0.703 ms/token
at the measured 700 GB/s streaming ceiling, with the 67 MB fp32/bf16
unembedding flooring every shallow-draft scheme. Every byte the weight
and KV streams shed comes straight off that floor, so an int8 path cuts
the largest cost term in the inference stack in half (ROADMAP item 2:
"the single biggest raw-speed lever on record").

Scheme — per-channel symmetric, contraction-dim-last:

- every quantized tensor stores its **contraction axis last** (weights
  are re-laid-out ``(out..., K)`` at quantize time, the KV cache is
  already ``(..., d_head)``), so one convention covers weights and
  cache: ``scale = max|x| / 127`` over the last axis, ``q = round(x /
  scale)`` clipped to ``[-127, 127]``. Symmetric (no zero point): the
  dequant is one multiply, which *folds out of the matmul* — ``x @
  dequant(q, s)`` per output channel equals ``(x @ q) * s`` exactly, so
  the int8 operand feeds the MXU directly and the fp32 accumulator is
  scaled once per output element. Zero channels store ``scale = 0`` and
  dequantize to exact zeros (no epsilon fuzz; the divisor is made safe
  separately).
- the formats are parameterized by ``qdtype`` so the fp8 variants slot
  in behind the same API when a session prices them (``QDTYPES`` maps
  name -> (dtype, qmax)); only int8 is wired through the model configs
  today.

Kernel: ``quant_matvec`` — one Pallas launch computing ``(x @ w8^T) *
scale`` with fp32 accumulation, gridded over output-channel tiles so
the int8 weight block streams HBM->VMEM once and never materializes in
high precision. Decode's matvecs are tiny in FLOPs and huge in bytes;
the kernel's job is to keep the stream at 1 byte/param. The gate
(``quant_matvec_supported``) mirrors ``decode_step_supported``:
lane-exact contraction dim, tileable channel count, a backend with a
Mosaic lowering (CPU runs interpret mode for parity tests). Off-gate
callers use ``qmm`` below, whose XLA formulation computes the same
factored math (dequant fused by XLA on TPU; the int8 operand is still
what HBM streams).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from icikit.ops.pallas_common import out_struct as _out_struct

# name -> (storage dtype, symmetric max). The fp8 rows are the promised
# plumbing: quantize/dequantize/qmm accept them today, the model-layer
# wiring (TransformerConfig.decode_quant) arms only "int8" until a TPU
# session prices the fp8 variants (their win over int8 is MXU-native
# fp8 matmul throughput, invisible on the CPU protocol).
QDTYPES = {
    "int8": (jnp.int8, 127.0),
    "fp8_e4m3": (getattr(jnp, "float8_e4m3fn", None), 448.0),
    "fp8_e5m2": (getattr(jnp, "float8_e5m2", None), 57344.0),
}


def _qdtype(name: str):
    if name not in QDTYPES:
        raise ValueError(f"unknown quant dtype {name!r} "
                         f"(known: {', '.join(sorted(QDTYPES))})")
    dt, qmax = QDTYPES[name]
    if dt is None:
        raise ValueError(f"quant dtype {name!r} is not available in "
                         "this jax build")
    return jnp.dtype(dt), qmax


def quantize_last(x, qdtype: str = "int8"):
    """Per-channel symmetric quantization over the LAST axis.

    Returns ``(q, scale)`` with ``q`` of ``x.shape`` in the storage
    dtype and ``scale`` fp32 of ``x.shape[:-1]``. Channels that are
    identically zero store ``scale = 0`` (their dequant is exact zero);
    the divisor is replaced by 1 where the scale vanishes, so no
    NaN/inf ever enters the quantized tensor. Values at the channel
    max land exactly on ±qmax (saturation is the clip, not overflow).
    """
    dt, qmax = _qdtype(qdtype)
    x32 = jnp.asarray(x, jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = amax / qmax
    safe = jnp.where(scale > 0, scale, 1.0)[..., None]
    scaled = jnp.clip(x32 / safe, -qmax, qmax)
    if jnp.issubdtype(dt, jnp.integer):
        scaled = jnp.round(scaled)
    # float qdtypes (fp8): the storage cast IS the rounding — fp8
    # round-to-nearest happens in astype; an integer jnp.round here
    # would collapse every |x| < scale/2 to zero and double-round the
    # rest (fp8's value grid is not the integers)
    return scaled.astype(dt), scale.astype(jnp.float32)


def dequantize_last(q, scale):
    """Inverse of :func:`quantize_last`: fp32 ``q * scale`` with the
    scale broadcast over the last axis."""
    return q.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]


# ------------------------------------------------------------ the kernel

def _matvec_kernel(x_ref, w_ref, s_ref, o_ref):
    """One output-channel tile: fp32-accum ``x (rows, K) @ w8 (bn, K)^T``
    scaled per channel. The int8 block is upcast in VMEM registers only
    — HBM streamed it at 1 byte/element, which is the whole point."""
    acc = lax.dot_general(x_ref[...].astype(jnp.float32),
                          w_ref[...].astype(jnp.float32),
                          (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    o_ref[...] = acc * s_ref[...]


def _pick_n_block(n: int) -> int | None:
    for bn in (512, 256, 128):
        if n % bn == 0:
            return bn
    return None


def quant_matvec_supported(rows: int, n: int, k: int) -> bool:
    """Gate for the Pallas int8 matvec: lane-exact contraction dim,
    tileable output-channel count, and a backend with a Mosaic lowering
    (CPU runs interpret mode, so the same path is testable off-TPU).
    Mirrors ``flash_attention.decode_step_supported``'s contract:
    callers check first; forcing the kernel off-gate fails loudly."""
    if k % 128 or k < 128:
        return False
    if _pick_n_block(n) is None:
        return False
    return jax.default_backend() in ("tpu", "cpu")


def quant_matvec(x, w8, scale, *, interpret: bool | None = None):
    """``(x @ w8^T) * scale`` in one Pallas launch, fp32 out.

    Args:
      x: ``(rows, K)`` float activations (any float dtype; upcast to
        fp32 in-register for the accumulation).
      w8: ``(N, K)`` quantized weights, contraction dim last — the
        layout ``quantize_last`` produces for re-laid-out weights.
      scale: ``(N,)`` fp32 per-output-channel scales.

    Returns ``(rows, N)`` fp32. Callers must check
    :func:`quant_matvec_supported` first; this function raises on an
    unsupported geometry rather than silently falling back (an A/B row
    must never measure the fallback by accident).
    """
    from jax.experimental import pallas as pl

    rows, k = x.shape
    n = w8.shape[0]
    if not quant_matvec_supported(rows, n, k):
        raise ValueError(
            f"quant_matvec unsupported for rows={rows}, n={n}, k={k} "
            "(need k % 128 == 0 and n tileable by 128) — gate with "
            "quant_matvec_supported")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bn = _pick_n_block(n)
    s2 = scale.reshape(1, n).astype(jnp.float32)
    out = pl.pallas_call(
        _matvec_kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((rows, k), lambda i: (0, 0)),
            pl.BlockSpec((bn, k), lambda i: (i, 0)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((rows, bn), lambda i: (0, i)),
        out_shape=_out_struct((rows, n), jnp.float32, x, w8, scale),
        interpret=interpret,
    )(x, w8, s2)
    return out


def quant_matvec_reference(x, w8, scale):
    """The reference dequant matmul the kernel's exact-logit tests pin
    against: fp32 ``x @ w8^T`` scaled per channel — the same factored
    math, formulated as one XLA dot."""
    acc = lax.dot_general(jnp.asarray(x, jnp.float32),
                          jnp.asarray(w8, jnp.float32),
                          (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    return acc * jnp.asarray(scale, jnp.float32)[None, :]


# -------------------------------------------------- model-facing helper

def qmm(x, w8, scale, k_ndim: int = 1, impl: str = "auto"):
    """Quantized matmul with arbitrary leading/output dims, fp32 out.

    ``x (..., K1..Kk)`` against ``w8 (out..., K1..Kk)`` whose LAST
    ``k_ndim`` axes are the contraction (the quantize-time layout);
    ``scale (out...)``. Returns ``(..., out...)`` fp32 — the factored
    dequant ``(x @ q) * s``, exact in exact arithmetic and the fused
    form on every path (the scale multiplies the accumulator, the int8
    weight feeds the matmul directly).

    ``impl``: ``"pallas"`` forces the kernel (loud failure off-gate,
    the ``decode_step="fused"`` discipline), ``"xla"`` forces the
    einsum formulation, ``"auto"`` uses the kernel on TPU when the
    gate accepts the flattened shape.
    """
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"unknown quant impl {impl!r} "
                         "(known: auto, pallas, xla)")
    bshape = x.shape[:-k_ndim] if k_ndim else x.shape
    kshape = x.shape[len(bshape):]
    oshape = w8.shape[:w8.ndim - k_ndim]
    if tuple(w8.shape[w8.ndim - k_ndim:]) != tuple(kshape):
        raise ValueError(f"contraction mismatch: x {x.shape} vs "
                         f"w8 {w8.shape} (k_ndim={k_ndim})")
    rows = 1
    for d in bshape:
        rows *= d
    k = 1
    for d in kshape:
        k *= d
    n = 1
    for d in oshape:
        n *= d
    use_kernel = impl == "pallas"
    if impl == "auto":
        use_kernel = (jax.default_backend() == "tpu"
                      and quant_matvec_supported(rows, n, k))
    if use_kernel:
        out = quant_matvec(x.reshape(rows, k), w8.reshape(n, k),
                           scale.reshape(n))
        return out.reshape(*bshape, *oshape)
    acc = lax.dot_general(
        x.reshape(rows, k).astype(jnp.float32), w8.reshape(n, k),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (acc * scale.reshape(1, n)).reshape(*bshape, *oshape)
