"""Pallas save-stack writer: per-layer residuals into the scan-carry
stack, in the layout the backward reads.

Why this exists: the rematerialized layer scan saves per-layer
residuals by stacking them into (L, ...) buffers. Under ``lax.scan``
that stacking belongs to XLA — it picks the stacked buffers' layouts
for the dynamic-update-slice that writes them, while the backward's
matmuls want the same data in their operand layouts, and the
round-5 profile attributes ~4 ms/step at the base preset to the
layout-conversion copies between the two (VERDICT r5 weak #1 demanded
a measured attempt instead of "unreachable from JAX"). This module is
that attempt: an explicit residual stack owned by the model, written
slice-by-slice with a Pallas kernel whose operands are layout-pinned
(Pallas calls require default layouts on both sides, so XLA cannot
interpose a conversion), read back by the backward with the matching
reader.

Mechanics: ``stack_write(stack, x, i)`` writes ``x`` into
``stack[i]`` **in place** — the slice index rides as a scalar-prefetch
operand so the output BlockSpec can address slice ``i`` directly, and
``input_output_aliases`` donates the stack buffer, so only the written
slice moves (no full-stack copy; the reference analog is psort's
in-place chunk commit, ``psort.cc:497-520``). Slices whose trailing
size is not lane-divisible (or whose row count breaks the sublane
rule) fall back to ``lax.dynamic_update_index_in_dim`` — the gate is
``stack_supported``.

``remat_scan_stacked`` is the consumer: a ``lax.scan``-equivalent
layer loop that saves each layer's input through the writer and
rebuilds the layer under ``jax.vjp`` in the backward (full-layer
rematerialization — the explicit stack cannot reuse XLA's
policy-saved dot outputs, which is exactly the trade the measured
A/B prices; see docs/DESIGN.md "Round-6"). Gradient leaf stacks are
written through the same kernel — gradient stacks are save stacks
too.

Measured verdict (train_ab_r6.jsonl, base preset, b=8): the writer
removes the layout copies but the full-layer relinearization it
forces re-pays the per-layer dots the ``except_attn``+dots policy
kept — net **+6.3 ms/step**. A measured dead-end: the XLA scan stays
the shipped default (``TransformerConfig.save_stack = "xla"``), and
the stack path stays reachable (``--save-stack pallas``) for
re-measuring on future XLA/Mosaic releases.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from icikit.ops.pallas_common import out_struct as _out_struct
from icikit.ops.pallas_common import sublane as _sublane

_LANES = 128
# widest block that keeps the copy's double buffering comfortably
# under the scoped-VMEM budget at any dtype
_MAX_BLOCK_ROWS = 1024


def _row_tiles(slice_size: int, dtype):
    """(rows, block_rows) of the (rows, 128) view of one stack slice,
    or None when the slice cannot be tiled (callers fall back)."""
    if slice_size % _LANES:
        return None
    rows = slice_size // _LANES
    sub = _sublane(dtype)
    if rows % sub:
        return None
    for br in (_MAX_BLOCK_ROWS, 512, 256, 128, 64, 32, 16, 8):
        if br >= sub and rows % br == 0:
            return rows, br
    return None


def stack_supported(slice_shape, dtype) -> bool:
    """Whether the Pallas writer/reader covers one (L, *slice_shape)
    stack's slices — else ``stack_write``/``stack_read`` silently use
    the XLA dynamic-slice path for that leaf."""
    size = int(np.prod(slice_shape)) if slice_shape else 1
    return _row_tiles(size, dtype) is not None


def _write_kernel(i_ref, x_ref, s_ref, o_ref):
    # the stack operand rides in ANY space purely to carry the alias;
    # only the addressed slice's blocks are touched
    del i_ref, s_ref
    o_ref[0] = x_ref[...]


def _read_kernel(i_ref, s_ref, o_ref):
    del i_ref
    o_ref[...] = s_ref[0]


def stack_write(stack: jax.Array, x: jax.Array, i,
                interpret: bool | None = None) -> jax.Array:
    """``stack[i] = x`` through the layout-pinned Pallas writer; the
    stack buffer is donated (in-place on TPU). Unsupported slices fall
    back to ``lax.dynamic_update_index_in_dim``."""
    tiles = _row_tiles(x.size, stack.dtype)
    if tiles is None:
        return lax.dynamic_update_index_in_dim(
            stack, x.astype(stack.dtype), i, 0)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rows, br = tiles
    L = stack.shape[0]
    s2 = stack.reshape(L, rows, _LANES)
    x2 = x.astype(stack.dtype).reshape(rows, _LANES)
    idx = jnp.asarray(i, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, _LANES), lambda g, i: (g, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, br, _LANES), lambda g, i: (i[0], g, 0)),
    )
    out = pl.pallas_call(
        _write_kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct(s2.shape, s2.dtype, stack, x),
        input_output_aliases={2: 0},   # donate the stack buffer
        interpret=interpret,
    )(idx, x2, s2)
    return out.reshape(stack.shape)


def stack_read(stack: jax.Array, i, slice_shape=None,
               interpret: bool | None = None) -> jax.Array:
    """``stack[i]`` through the matching layout-pinned reader."""
    slice_shape = tuple(slice_shape or stack.shape[1:])
    size = int(np.prod(slice_shape)) if slice_shape else 1
    tiles = _row_tiles(size, stack.dtype)
    if tiles is None:
        return lax.dynamic_index_in_dim(stack, i, 0, keepdims=False)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rows, br = tiles
    L = stack.shape[0]
    s2 = stack.reshape(L, rows, _LANES)
    idx = jnp.asarray(i, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((1, br, _LANES), lambda g, i: (i[0], g, 0)),
        ],
        out_specs=pl.BlockSpec((br, _LANES), lambda g, i: (g, 0)),
    )
    out = pl.pallas_call(
        _read_kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct((rows, _LANES), s2.dtype, stack),
        interpret=interpret,
    )(idx, s2)
    return out.reshape(slice_shape)


def _tree_index(tree, l):
    return jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, l, 0, keepdims=False), tree)


def _writer(impl, interpret):
    if impl == "pallas":
        return partial(stack_write, interpret=interpret)
    return lambda s, x, i: lax.dynamic_update_index_in_dim(
        s, x.astype(s.dtype), i, 0)


def _reader(impl, interpret):
    if impl == "pallas":
        return partial(stack_read, interpret=interpret)
    return lambda s, i: lax.dynamic_index_in_dim(s, i, 0, keepdims=False)


def remat_scan_stacked(layer_fn, x0: jax.Array, stacked_params,
                       positions: jax.Array, impl: str = "pallas",
                       interpret: bool | None = None):
    """Explicit-save-stack layer scan: ``lax.scan`` semantics with the
    residual stack owned by the model instead of XLA's AD machinery.

    ``layer_fn(x, layer_slice, positions) -> (x_next, aux_scalar)``
    must close over statics only (schedule callables, config) —
    ``positions`` carries the one traced value the attention schedules
    need, explicitly, so the custom-vjp boundary sees every tracer as
    an argument. Returns ``(x_final, aux_sum)``.

    Forward: each layer's input residual is written into a
    preallocated (L, ...) stack by the ``impl`` writer. Backward: a
    reverse loop reads each residual back and rebuilds the layer under
    ``jax.vjp`` (full-layer rematerialization), writing each gradient
    leaf into its own (L, ...) stack through the same writer.
    ``impl="xla"`` runs the identical structure with dynamic-slice
    writes — the A/B control that isolates the writer itself.
    """
    if impl not in ("pallas", "xla"):
        raise ValueError(f"unknown save-stack impl {impl!r} "
                         "(known: pallas, xla)")
    leaves = jax.tree.leaves(stacked_params)
    if not leaves:
        raise ValueError("remat_scan_stacked needs stacked params")
    n_layers = leaves[0].shape[0]
    write = _writer(impl, interpret)
    read = _reader(impl, interpret)

    @jax.custom_vjp
    def run(x0, lps, positions):
        def body(l, carry):
            x, aux = carry
            x, a = layer_fn(x, _tree_index(lps, l), positions)
            return x, aux + a
        return lax.fori_loop(0, n_layers, body,
                             (x0, jnp.zeros((), jnp.float32)))

    def run_fwd(x0, lps, positions):
        stack0 = jnp.zeros((n_layers,) + x0.shape, x0.dtype)

        def body(l, carry):
            x, aux, stack = carry
            stack = write(stack, x, l)
            x, a = layer_fn(x, _tree_index(lps, l), positions)
            return x, aux + a, stack

        x, aux, stack = lax.fori_loop(
            0, n_layers, body, (x0, jnp.zeros((), jnp.float32), stack0))
        return (x, aux), (stack, lps, positions)

    def run_bwd(res, ct):
        stack, lps, positions = res
        dx, daux = ct
        daux = jnp.asarray(daux, jnp.float32)
        dlps0 = jax.tree.map(
            lambda a: jnp.zeros(a.shape, a.dtype), lps)

        def body(k, carry):
            dx, dlps = carry
            l = n_layers - 1 - k
            x_l = read(stack, l)
            lp = _tree_index(lps, l)
            _, vjp_fn = jax.vjp(
                lambda x, p: layer_fn(x, p, positions), x_l, lp)
            dx, dlp = vjp_fn((dx, daux))
            dlps = jax.tree.map(lambda s, v: write(s, v, l), dlps, dlp)
            return dx, dlps

        dx0, dlps = lax.fori_loop(0, n_layers, body, (dx, dlps0))
        # positions is integer-typed: its cotangent space is float0
        dpos = np.zeros(positions.shape, jax.dtypes.float0)
        return dx0, dlps, dpos

    run.defvjp(run_fwd, run_bwd)
    return run(x0, stacked_params, positions)
