"""Shared plumbing for the Pallas kernels in this package."""

from __future__ import annotations

import jax

LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453


def out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct carrying the union of the operands' varying
    mesh axes, so pallas_call composes with shard_map's (default-on)
    replication checking instead of forcing check_vma=False."""
    vma = frozenset()
    for x in operands:
        vma = vma | getattr(jax.typeof(x), "vma", frozenset())
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax: no vma argument, no check either
        return jax.ShapeDtypeStruct(shape, dtype)
