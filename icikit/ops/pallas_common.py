"""Shared plumbing for the Pallas kernels in this package."""

from __future__ import annotations

import jax

LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453


def sublane(dtype) -> int:
    """Mosaic's second-minor tiling multiple for a dtype: 8 fp32 rows,
    16 bf16, 32 int8 — (32 / itemsize), floored at 8. The shared rule
    every (rows, 128)-view kernel gate checks before handing Mosaic a
    block its tiling cannot express."""
    import jax.numpy as jnp
    return max(8, 32 // max(1, jnp.dtype(dtype).itemsize))


def tpu_compiler_params(**kw):
    """``pltpu.CompilerParams`` across the rename (older jax calls the
    same dataclass ``TPUCompilerParams``)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kw)


def varying_axes(x) -> frozenset:
    """The value's varying-manual-axes tags. Empty on jax versions
    without ``jax.typeof``/vma tracking — which do not check
    replication either, so "no tags" is the correct answer there."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return getattr(typeof(x), "vma", frozenset())


def out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct carrying the union of the operands' varying
    mesh axes, so pallas_call composes with shard_map's (default-on)
    replication checking instead of forcing check_vma=False."""
    vma = frozenset()
    for x in operands:
        vma = vma | varying_axes(x)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax: no vma argument, no check either
        return jax.ShapeDtypeStruct(shape, dtype)
