"""Pallas TPU fused softmax cross-entropy head (vocab-chunked).

Why this exists: the unfused head computes ``logits = x @ w`` (b·s, V),
casts them to fp32 (1 GB at the base preset), runs ``log_softmax`` over
them (two more full reads plus an fp32 write), gathers the target
column, and in the backward materializes fp32 ``dlogits`` of the same
size — ~5 GB of HBM traffic that exists only because the (T, V) logits
matrix is materialized between the head matmul and the loss. This
kernel streams vocab chunks of the logits through VMEM against a
resident x block, carrying online max / sum-exp statistics in scratch
(the flash-attention construction applied to the classifier head), so
per-token ``lse`` and the target logit come out of one pass and the
full logits never touch HBM.

The backward rebuilds each chunk of ``g = (softmax − onehot) · dnll``
— from a recomputed logits chunk, or (``save_exp``) from the forward's
saved shifted exponentials. Two backward formulations ship:

- **fused** (default, r6): ``dx`` and ``dw`` come straight out of two
  Pallas kernels that rebuild the g chunk in VMEM and immediately
  contract it — ``dx[it] = Σ_iv g·w[iv]`` accumulated over the vocab
  grid, ``dw[iv] = Σ_it gᵀ·x[it]`` accumulated over the token grid —
  so the (T, V) g matrix never exists in HBM. At the base bench
  preset the unfused g round-trip (one bf16 write + two reads of
  536 MB) was ~2.3 ms of pure HBM traffic; the fused form replaces it
  with one extra in-VMEM rebuild of each chunk (free on the saved-exp
  path, one repeated 550-GFLOP dot on the recompute path).
- **matmul** (``fused_bwd=False``, the pre-r6 path): the backward
  kernel writes g in bf16 and ``dx = g @ w`` / ``dw = gᵀ @ x`` are
  plain MXU matmuls — kept reachable for the A/B.

The head weight is taken **(V, D)** — embedding orientation — so both
cotangents come out in their params' natural layouts (the (D, V)
orientation produced a transposed-layout ``dw`` that made the
optimizer update on the head run ~4× its roofline; round-3 profile
notes in ROADMAP.md).

Numerics: the matmuls accumulate fp32 on the MXU; softmax statistics
are fp32 in base-2 space (log2(e) folds into one VPU multiply per tile,
the per-element transcendental is a bare ``exp2`` — same recipe as
``flash_attention``). Reference lineage: the reference has no ML head;
this is the TPU-first replacement for the L4-driver pattern of
"compute, verify, reduce" applied to the training loss
(``Parallel-Sorting/src/psort.cc:497-520`` is the analogous fused
check-while-reducing pass).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from icikit.ops.pallas_common import LN2 as _LN2
from icikit.ops.pallas_common import LOG2E as _LOG2E
from icikit.ops.pallas_common import out_struct as _out_struct
from icikit.ops.pallas_common import tpu_compiler_params

# Default tile geometry. bt rows of x stay resident while bv-wide vocab
# chunks stream; (bt, bv) = (1024, 2048) puts the fp32 score tile at
# 8 MB and the streamed w tile at 4 MB bf16 — comfortably double-
# buffered under a 64 MB scoped-VMEM budget.
BLOCK_T = 1024
BLOCK_V = 2048


def _fwd_kernel(x_ref, w_ref, t_ref, lse_ref, tgt_ref, m_s, l_s, t_s,
                *, nv, bv, e_ref=None, mrun_ref=None):
    """``e_ref``/``mrun_ref`` non-None = the save-exp variant (r5
    structural route): the shifted exponentials ``exp2(sb − m_i)``
    this pass already computes for the online sum are written out
    (bf16) together with each chunk's running max ``m_i``, so the
    backward can rebuild the softmax by rescaling —
    ``p = e · exp2(m_i − lse)`` — without re-running the logits
    matmul (the "fourth 550-GFLOP dot" of ROADMAP's head
    accounting)."""
    iv = pl.program_id(1)

    @pl.when(iv == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, -jnp.inf)
        l_s[:] = jnp.zeros_like(l_s)
        t_s[:] = jnp.zeros_like(t_s)

    # the always-true guard keeps the interpret-mode vma discharge
    # happy under shard_map (bare stores trip its dynamic_slice
    # varying-manual-axes check; real-TPU lowering is unaffected)
    @pl.when(iv >= 0)
    def _():
        x, w = x_ref[:], w_ref[:]
        s = lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (bt, bv)
        # target-logit extraction in natural units, pre base-2 scale
        tgt = t_ref[0, 0, :][:, None]                        # (bt, 1)
        cols = iv * bv + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        t_s[:] += jnp.sum(jnp.where(cols == tgt, s, 0.0), axis=1,
                          keepdims=True)
        sb = s * _LOG2E                                      # base-2
        m_prev = m_s[:]
        m_new = jnp.maximum(m_prev, jnp.max(sb, axis=1, keepdims=True))
        e = jnp.exp2(sb - m_new)
        l_s[:] = l_s[:] * jnp.exp2(m_prev - m_new) + jnp.sum(
            e, axis=1, keepdims=True)
        m_s[:] = m_new
        if e_ref is not None:
            e_ref[:] = e.astype(e_ref.dtype)
            mrun_ref[0, 0, 0, :] = m_new[:, 0]

    @pl.when(iv == nv - 1)
    def _():
        lse = (m_s[:] + jnp.log2(l_s[:])) * _LN2             # nats
        lse_ref[0, 0, :] = lse[:, 0]
        tgt_ref[0, 0, :] = t_s[:][:, 0]


def _fwd_kernel_save(x_ref, w_ref, t_ref, lse_ref, tgt_ref, e_ref,
                     mrun_ref, m_s, l_s, t_s, *, nv, bv):
    _fwd_kernel(x_ref, w_ref, t_ref, lse_ref, tgt_ref, m_s, l_s, t_s,
                nv=nv, bv=bv, e_ref=e_ref, mrun_ref=mrun_ref)


def _g_chunk_recompute(x, w, t_ref, lse_ref, dnll_ref, iv, bv):
    """Rebuild one (bt, bv) chunk of g = (softmax − onehot)·dnll from
    the resident operands — the per-chunk body shared by the fused dx
    and dw kernels (recompute flavor)."""
    s = lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)      # (bt, bv)
    lse_b2 = (lse_ref[0, 0, :] * _LOG2E)[:, None]
    p = jnp.exp2(s * _LOG2E - lse_b2)
    tgt = t_ref[0, 0, :][:, None]
    cols = iv * bv + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    onehot = (cols == tgt).astype(jnp.float32)
    return (p - onehot) * dnll_ref[0, 0, :][:, None]


def _g_chunk_saved(e_ref, mrun_ref, t_ref, lse_ref, dnll_ref, iv, bv):
    """Rebuild one g chunk from the saved shifted exponentials — the
    rescale identity of _g_saved_kernel, shared by the fused dx/dw
    kernels (saved flavor): no logits matmul at all."""
    lse_b2 = (lse_ref[0, 0, :] * _LOG2E)[:, None]
    scale = jnp.exp2(mrun_ref[0, 0, 0, :][:, None] - lse_b2)
    p = e_ref[:].astype(jnp.float32) * scale
    tgt = t_ref[0, 0, :][:, None]
    cols = iv * bv + lax.broadcasted_iota(jnp.int32, p.shape, 1)
    onehot = (cols == tgt).astype(jnp.float32)
    return (p - onehot) * dnll_ref[0, 0, :][:, None]


def _g_saved_kernel(e_ref, mrun_ref, t_ref, lse_ref, dnll_ref, g_ref,
                    *, bv):
    """Backward g from the saved exponentials: no logits matmul.
    ``p = e · exp2(m_i − lse)`` — ``m_i`` is the running max the
    forward used for this chunk, so the rescale is exact up to the
    bf16 storage rounding of ``e``."""
    iv = pl.program_id(1)

    @pl.when(iv >= 0)  # always true; see the forward kernel's note
    def _():
        g = _g_chunk_saved(e_ref, mrun_ref, t_ref, lse_ref, dnll_ref,
                           iv, bv)
        g_ref[:] = g.astype(g_ref.dtype)


def _bwd_kernel(x_ref, w_ref, t_ref, lse_ref, dnll_ref, g_ref, *, bv):
    iv = pl.program_id(1)

    @pl.when(iv >= 0)  # always true; see the forward kernel's note
    def _():
        g = _g_chunk_recompute(x_ref[:], w_ref[:], t_ref, lse_ref,
                               dnll_ref, iv, bv)
        g_ref[:] = g.astype(g_ref.dtype)


def _dx_kernel(x_ref, w_ref, t_ref, lse_ref, dnll_ref, dx_ref, acc,
               *, nv, bv, e_ref=None, mrun_ref=None):
    """Fused dx: for each resident x row-block, stream the vocab chunks,
    rebuild g in VMEM and accumulate ``dx += g @ w[iv]`` into fp32
    scratch — the g matrix never touches HBM. The w tile read feeds
    both the rebuild matmul and the dx contraction (one fetch, two
    dots). ``e_ref``/``mrun_ref`` non-None = the saved-exp flavor."""
    iv = pl.program_id(1)

    @pl.when(iv == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    @pl.when(iv >= 0)  # always true; see the forward kernel's note
    def _():
        if e_ref is None:
            g = _g_chunk_recompute(x_ref[:], w_ref[:], t_ref, lse_ref,
                                   dnll_ref, iv, bv)
        else:
            g = _g_chunk_saved(e_ref, mrun_ref, t_ref, lse_ref,
                               dnll_ref, iv, bv)
        acc[...] += lax.dot_general(
            g, w_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                 # (bt, d)

    @pl.when(iv == nv - 1)
    def _():
        dx_ref[...] = acc[...].astype(dx_ref.dtype)


def _dx_saved_kernel(e_ref, mrun_ref, w_ref, t_ref, lse_ref, dnll_ref,
                     dx_ref, acc, *, nv, bv):
    _dx_kernel(None, w_ref, t_ref, lse_ref, dnll_ref, dx_ref, acc,
               nv=nv, bv=bv, e_ref=e_ref, mrun_ref=mrun_ref)


def _dw_kernel(x_ref, w_ref, t_ref, lse_ref, dnll_ref, dw_ref, acc,
               *, nt, bv, e_ref=None, mrun_ref=None):
    """Fused dw: the transposed grid — for each resident w vocab-block,
    stream the token blocks, rebuild g and accumulate ``dw += gᵀ @
    x[it]`` into fp32 scratch. Grid is (nv, nt) so the token dimension
    is innermost (the accumulator's revisits are consecutive)."""
    iv = pl.program_id(0)
    it = pl.program_id(1)

    @pl.when(it == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    @pl.when(it >= 0)  # always true; see the forward kernel's note
    def _():
        if e_ref is None:
            g = _g_chunk_recompute(x_ref[:], w_ref[:], t_ref, lse_ref,
                                   dnll_ref, iv, bv)
        else:
            g = _g_chunk_saved(e_ref, mrun_ref, t_ref, lse_ref,
                               dnll_ref, iv, bv)
        acc[...] += lax.dot_general(
            g, x_ref[:].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                 # (bv, d)

    @pl.when(it == nt - 1)
    def _():
        dw_ref[...] = acc[...].astype(dw_ref.dtype)


def _dw_saved_kernel(e_ref, mrun_ref, x_ref, t_ref, lse_ref, dnll_ref,
                     dw_ref, acc, *, nt, bv):
    _dw_kernel(x_ref, None, t_ref, lse_ref, dnll_ref, dw_ref, acc,
               nt=nt, bv=bv, e_ref=e_ref, mrun_ref=mrun_ref)


def _tiles(t, v, block_t, block_v):
    bt = min(block_t, t)
    bv = min(block_v, v)
    if t % bt or v % bv:
        return None
    return bt, bv


def _fwd_call(x, w, targets, bt, bv, interpret, save=False):
    t, d = x.shape
    v = w.shape[0]
    nt, nv = t // bt, v // bv
    # row-vector operands ride as (nt, 1, bt): Mosaic requires the
    # last two block dims to divide (8, 128) or equal the array dims —
    # a size-1 middle dim satisfies the sublane rule exactly.
    t2 = targets.reshape(nt, 1, bt)
    row_spec = pl.BlockSpec((1, 1, bt), lambda it, iv: (it, 0, 0))
    out_specs = [row_spec, row_spec]
    out_shape = [
        _out_struct((nt, 1, bt), jnp.float32, x, w, targets),
        _out_struct((nt, 1, bt), jnp.float32, x, w, targets),
    ]
    kernel = partial(_fwd_kernel, nv=nv, bv=bv)
    if save:
        kernel = partial(_fwd_kernel_save, nv=nv, bv=bv)
        out_specs += [
            pl.BlockSpec((bt, bv), lambda it, iv: (it, iv)),
            pl.BlockSpec((1, 1, 1, bt), lambda it, iv: (it, iv, 0, 0)),
        ]
        out_shape += [
            _out_struct((t, v), x.dtype, x, w, targets),
            _out_struct((nt, nv, 1, bt), jnp.float32, x, w, targets),
        ]
    outs = pl.pallas_call(
        kernel,
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((bt, d), lambda it, iv: (it, 0)),
            pl.BlockSpec((bv, d), lambda it, iv: (iv, 0)),
            row_spec,
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bt, 1), jnp.float32),   # running max (base-2)
            pltpu.VMEM((bt, 1), jnp.float32),   # running sum-exp
            pltpu.VMEM((bt, 1), jnp.float32),   # target logit (nats)
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(x, w, t2)
    lse2, tgt2 = outs[0], outs[1]
    if save:
        return lse2.reshape(t), tgt2.reshape(t), outs[2], outs[3]
    return lse2.reshape(t), tgt2.reshape(t)


def _g_call(x, w, targets, lse, dnll, bt, bv, interpret):
    t, d = x.shape
    v = w.shape[0]
    nt, nv = t // bt, v // bv
    return pl.pallas_call(
        partial(_bwd_kernel, bv=bv),
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((bt, d), lambda it, iv: (it, 0)),
            pl.BlockSpec((bv, d), lambda it, iv: (iv, 0)),
            pl.BlockSpec((1, 1, bt), lambda it, iv: (it, 0, 0)),
            pl.BlockSpec((1, 1, bt), lambda it, iv: (it, 0, 0)),
            pl.BlockSpec((1, 1, bt), lambda it, iv: (it, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bt, bv), lambda it, iv: (it, iv)),
        out_shape=_out_struct((t, v), x.dtype, x, w, targets, lse, dnll),
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(x, w, targets.reshape(nt, 1, bt), lse.reshape(nt, 1, bt),
      dnll.reshape(nt, 1, bt))


def _g_saved_call(e, mrun, targets, lse, dnll, bt, bv, interpret):
    t, v = e.shape
    nt, nv = t // bt, v // bv
    row_spec = pl.BlockSpec((1, 1, bt), lambda it, iv: (it, 0, 0))
    return pl.pallas_call(
        partial(_g_saved_kernel, bv=bv),
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((bt, bv), lambda it, iv: (it, iv)),
            pl.BlockSpec((1, 1, 1, bt), lambda it, iv: (it, iv, 0, 0)),
            row_spec, row_spec, row_spec,
        ],
        out_specs=pl.BlockSpec((bt, bv), lambda it, iv: (it, iv)),
        out_shape=_out_struct((t, v), e.dtype, e, mrun, targets, lse,
                              dnll),
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(e, mrun, targets.reshape(nt, 1, bt), lse.reshape(nt, 1, bt),
      dnll.reshape(nt, 1, bt))


def _dx_call(x, w, targets, lse, dnll, bt, bv, interpret, e=None,
             mrun=None):
    t, d = (e.shape[0], w.shape[1]) if x is None else x.shape
    v = w.shape[0]
    nt, nv = t // bt, v // bv
    row_spec = pl.BlockSpec((1, 1, bt), lambda it, iv: (it, 0, 0))
    w_spec = pl.BlockSpec((bv, d), lambda it, iv: (iv, 0))
    if e is None:
        kernel = partial(_dx_kernel, nv=nv, bv=bv)
        in_specs = [pl.BlockSpec((bt, d), lambda it, iv: (it, 0)),
                    w_spec, row_spec, row_spec, row_spec]
        operands = (x, w)
        out_dtype = x.dtype
    else:
        kernel = partial(_dx_saved_kernel, nv=nv, bv=bv)
        in_specs = [
            pl.BlockSpec((bt, bv), lambda it, iv: (it, iv)),
            pl.BlockSpec((1, 1, 1, bt), lambda it, iv: (it, iv, 0, 0)),
            w_spec, row_spec, row_spec, row_spec]
        operands = (e, mrun, w)
        out_dtype = e.dtype
    return pl.pallas_call(
        kernel,
        grid=(nt, nv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bt, d), lambda it, iv: (it, 0)),
        out_shape=_out_struct((t, d), out_dtype, *operands, targets,
                              lse, dnll),
        scratch_shapes=[pltpu.VMEM((bt, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(*operands, targets.reshape(nt, 1, bt), lse.reshape(nt, 1, bt),
      dnll.reshape(nt, 1, bt))


def _dw_call(x, w, targets, lse, dnll, bt, bv, interpret, e=None,
             mrun=None):
    t, d = x.shape
    v = e.shape[1] if w is None else w.shape[0]
    nt, nv = t // bt, v // bv
    row_spec = pl.BlockSpec((1, 1, bt), lambda iv, it: (it, 0, 0))
    x_spec = pl.BlockSpec((bt, d), lambda iv, it: (it, 0))
    if e is None:
        kernel = partial(_dw_kernel, nt=nt, bv=bv)
        in_specs = [x_spec,
                    pl.BlockSpec((bv, d), lambda iv, it: (iv, 0)),
                    row_spec, row_spec, row_spec]
        operands = (x, w)
        out_dtype = w.dtype
    else:
        kernel = partial(_dw_saved_kernel, nt=nt, bv=bv)
        in_specs = [
            pl.BlockSpec((bt, bv), lambda iv, it: (it, iv)),
            pl.BlockSpec((1, 1, 1, bt), lambda iv, it: (it, iv, 0, 0)),
            x_spec, row_spec, row_spec, row_spec]
        operands = (e, mrun, x)
        out_dtype = x.dtype
    return pl.pallas_call(
        kernel,
        grid=(nv, nt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bv, d), lambda iv, it: (iv, 0)),
        out_shape=_out_struct((v, d), out_dtype, *operands, targets,
                              lse, dnll),
        scratch_shapes=[pltpu.VMEM((bv, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(*operands, targets.reshape(nt, 1, bt), lse.reshape(nt, 1, bt),
      dnll.reshape(nt, 1, bt))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _xent(x, w, targets, bt, bv, interpret, save, fuse):
    lse, tgt = _fwd_call(x, w, targets, bt, bv, interpret)[:2]
    return lse - tgt


def _xent_fwd(x, w, targets, bt, bv, interpret, save, fuse):
    if save:
        lse, tgt, e, mrun = _fwd_call(x, w, targets, bt, bv, interpret,
                                      save=True)
        return lse - tgt, (x, w, targets, lse, e, mrun)
    lse, tgt = _fwd_call(x, w, targets, bt, bv, interpret)
    return lse - tgt, (x, w, targets, lse, None, None)


def _xent_bwd(bt, bv, interpret, save, fuse, res, dnll):
    x, w, targets, lse, e, mrun = res
    dnll32 = dnll.astype(jnp.float32)
    if fuse:
        # fused backward (r6): each kernel rebuilds the g chunk in
        # VMEM (from saved exponentials, or from a recomputed logits
        # chunk) and contracts it on the spot — g never round-trips
        # through HBM (the measured ~2.3 ms of pure traffic the
        # matmul formulation pays at the base preset)
        if save:
            dx = _dx_call(None, w, targets, lse, dnll32, bt, bv,
                          interpret, e=e, mrun=mrun)
            dw = _dw_call(x, None, targets, lse, dnll32, bt, bv,
                          interpret, e=e, mrun=mrun)
        else:
            dx = _dx_call(x, w, targets, lse, dnll32, bt, bv, interpret)
            dw = _dw_call(x, w, targets, lse, dnll32, bt, bv, interpret)
        return dx.astype(x.dtype), dw.astype(w.dtype), None
    if save:
        # recompute-free backward (r5): g is rebuilt from the saved
        # shifted exponentials — the 2·T·V·D logits matmul is gone;
        # the price is the forward's bf16 e write + this read
        g = _g_saved_call(e, mrun, targets, lse, dnll32, bt, bv,
                          interpret)
    else:
        g = _g_call(x, w, targets, lse, dnll32, bt, bv, interpret)
    # dx: (T, V) @ (V, D) — contract vocab; dw: (T, V)ᵀ @ (T, D) —
    # contract tokens; both land in their params' natural layouts.
    dx = lax.dot_general(g, w, (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dw = lax.dot_general(g, x, (((0,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    return dx.astype(x.dtype), dw.astype(w.dtype), None


_xent.defvjp(_xent_fwd, _xent_bwd)


def xent_supported(t: int, d: int, v: int, dtype,
                   block_t: int = BLOCK_T, block_v: int = BLOCK_V):
    """Whether the fused head covers this shape/backend (else callers
    should take the unfused log_softmax path)."""
    if jnp.dtype(dtype) not in (jnp.bfloat16, jnp.float32):
        return False
    if jax.default_backend() not in ("tpu", "cpu"):
        return False
    if d % 128 or _tiles(t, v, block_t, block_v) is None:
        return False
    return True


def fused_xent(x: jax.Array, w: jax.Array, targets: jax.Array,
               block_t: int = BLOCK_T, block_v: int = BLOCK_V,
               interpret: bool | None = None,
               save_exp: bool = False,
               fused_bwd: bool = True) -> jax.Array:
    """Per-token cross-entropy ``-log softmax(x @ w)[target]``.

    Args:
      x: ``(T, D)`` activations (bf16 or f32).
      w: ``(V, D)`` head weights (embedding orientation), same dtype.
      targets: ``(T,)`` int32 class ids in ``[0, V)``.
      save_exp: save the forward's bf16 shifted-exponential chunks
        (+ per-chunk running maxes) as residuals so the backward
        rebuilds softmax by rescaling instead of re-running the
        logits matmul — trades one 2·T·V·D dot for T·V bf16 of HBM
        write+read and holds the (T, V) residual live between
        forward and backward (r5 structural A/B; gradients agree
        with the recompute path to bf16 storage rounding).
      fused_bwd: compute dx and dw inside the backward kernels (one
        pass over the vocab dimension per cotangent, g rebuilt in
        VMEM and contracted on the spot — no (T, V) g matrix in HBM;
        the r6 default, measured −2.1 ms/step at the base preset).
        ``False`` restores the matmul formulation (g materialized
        bf16, dx/dw as separate XLA dots) for the A/B.

    Returns:
      ``(T,)`` fp32 NLL per token, numerically equal to the unfused
      ``-take_along_axis(log_softmax(x @ w), targets)`` up to fp32
      reassociation. Differentiable in ``x`` and ``w``; the ``w``
      cotangent accumulates in fp32 and is cast to ``w.dtype`` once.

    Raises ``ValueError`` for shapes the tiling cannot cover — callers
    gate on :func:`xent_supported`.
    """
    t, d = x.shape
    v = w.shape[0]
    if w.shape[1] != d or targets.shape != (t,):
        raise ValueError(f"shape mismatch: x {x.shape}, w {w.shape}, "
                         f"targets {targets.shape}")
    if x.dtype != w.dtype:
        # the kernels assume one shared operand dtype (residual e is
        # stored in it; the saved-flavor dw accumulator drains through
        # it before the final cast) — a mixed-dtype call would not
        # fail, it would silently degrade dw to the narrower dtype
        raise ValueError(f"dtype mismatch: x {x.dtype} vs w {w.dtype} "
                         "(the fused head requires one shared dtype; "
                         "cast the narrower operand up, or both down)")
    tiles = _tiles(t, v, block_t, block_v)
    if tiles is None or d % 128:
        raise ValueError(
            f"fused xent needs T divisible by min(block_t={block_t}, T), "
            f"V divisible by min(block_v={block_v}, V) and D % 128 == 0; "
            f"got T={t} D={d} V={v} (use the unfused path)")
    bt, bv = tiles
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _xent(x, w, targets.astype(jnp.int32), bt, bv,
                 bool(interpret), bool(save_exp), bool(fused_bwd))
