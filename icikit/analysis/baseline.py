"""Committed baseline: grandfathered findings the gate tolerates.

``tools/analysis_baseline.json`` holds a list of entries::

    {"rule": ..., "path": ..., "msg": ..., "note": "why this is
     grandfathered instead of fixed"}

An entry matches findings on the stable ``(rule, path, msg)`` triple
— line numbers shift under unrelated edits and are deliberately not
part of the identity. Each entry absorbs at most ``count`` matching
findings (default 1): a NEW violation that happens to render the
same message as a grandfathered one must NOT ride its exemption —
the (n+1)-th match comes out unbaselined and fails the gate. Every
entry MUST carry a non-empty ``note``: a baseline without a recorded
reason is just a muted alarm, and the loader fails loudly on one.
The gate reports (without failing) any STALE entry whose findings no
longer exist (or an over-counted entry), so fixed code sheds its
baseline in the next PR instead of accreting dead exemptions.
"""

from __future__ import annotations

import json
import os

from icikit.analysis.core import Finding

DEFAULT_BASELINE = os.path.join("tools", "analysis_baseline.json")


def load(path: str) -> list[dict]:
    if not os.path.isfile(path):
        return []
    with open(path, encoding="utf-8") as f:
        entries = json.load(f)
    if not isinstance(entries, list):
        raise ValueError(f"{path}: baseline must be a JSON list")
    for e in entries:
        missing = {"rule", "path", "msg"} - set(e)
        if missing:
            raise ValueError(
                f"{path}: baseline entry missing {sorted(missing)}: "
                f"{e}")
        if not str(e.get("note", "")).strip():
            raise ValueError(
                f"{path}: baseline entry for {e['rule']} @ "
                f"{e['path']} has no justification note — say why "
                "it is grandfathered or fix it")
        if not isinstance(e.get("count", 1), int) \
                or e.get("count", 1) < 1:
            raise ValueError(
                f"{path}: baseline entry for {e['rule']} @ "
                f"{e['path']} has a non-positive count")
    return entries


def split(findings: list[Finding], entries: list[dict]):
    """Partition ``findings`` into (unbaselined, baselined) and
    report stale entries. Each entry absorbs at most its ``count``
    matches (findings in sorted order, so the allocation is
    deterministic); the overflow is fresh — a new same-message
    violation cannot hide behind a grandfathered one. An entry whose
    budget is not fully consumed is stale (partially or wholly): the
    code improved, shrink or drop the entry."""
    budget: dict[tuple, int] = {}
    for e in entries:
        key = (e["rule"], e["path"], e["msg"])
        budget[key] = budget.get(key, 0) + int(e.get("count", 1))
    fresh, grandfathered = [], []
    for f in sorted(findings):
        k = f.baseline_key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            grandfathered.append(f)
        else:
            fresh.append(f)
    stale = [e for e in entries
             if budget.get((e["rule"], e["path"], e["msg"]), 0) > 0]
    return fresh, grandfathered, stale


def write(path: str, findings: list[Finding],
          note: str = "grandfathered at baseline capture — "
                      "revisit before relying on this entry") -> int:
    """Capture ``findings`` as the new baseline (CLI
    ``--write-baseline``): one entry per (rule, path, msg) with its
    exact match count. The shared placeholder note satisfies the
    loader mechanically; replace it with the real reason per entry
    before committing."""
    counts: dict[tuple, int] = {}
    for f in findings:
        counts[f.baseline_key()] = counts.get(f.baseline_key(), 0) + 1
    entries = [{"rule": rule, "path": path, "msg": msg,
                "count": n, "note": note}
               for (rule, path, msg), n in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=2, sort_keys=True)
        f.write("\n")
    return len(entries)
