"""``icikit.analysis`` — one AST static-analysis pass over the tree.

The repo's invariants used to be enforced by six disconnected scripts
in ``tools/`` plus two grep pipelines in the Makefile, each with its
own file walking, parsing, and escape-hatch conventions. This package
is the consolidation: ONE tree walker with a per-file parse cache, a
shared :class:`~icikit.analysis.core.Finding` model, per-line
``# icikit-lint: off[rule]`` suppressions, a committed baseline file
for grandfathered findings, and a single gated CLI entry point
(``python -m icikit.analysis --gate``) that ``make check`` runs.

Rules (see docs/ANALYSIS.md for the catalog):

- ported, semantics pinned by tests: ``serve-key``, ``chaos-site``,
  ``tree-accept``, ``obs-catalog``, ``quant-arena`` (runtime), plus
  the two former Makefile greps ``obs-print`` and ``serve-clock``;
- new hot-path analyses: ``host-sync`` (implicit device->host
  synchronization inside the engine step / decode / train loops) and
  ``lock-discipline`` (bus emits, device dispatch, file I/O and
  ``time.*`` calls lexically under ``with self._lock``-style blocks).

The old ``tools/*_lint.py`` scripts remain as thin shims re-exporting
their rule for backward compatibility.
"""

from icikit.analysis.core import (  # noqa: F401
    Finding,
    Project,
    Rule,
    all_rules,
    get_rule,
    rule,
    run_rules,
    shim_main,
)
