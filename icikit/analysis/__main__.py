from icikit.analysis.cli import main

raise SystemExit(main())
