"""``python -m icikit.analysis`` — the one analysis entry point.

Modes:

- default: run all rules, print findings (baseline-annotated), exit 0
  — the explorer's view;
- ``--gate``: exit nonzero on any UNBASELINED finding — what ``make
  check`` runs;
- ``--json PATH|-``: machine-readable findings (``make
  analysis-smoke`` asserts the shape);
- ``--self-check``: seed one violation per seedable rule into a
  synthetic mini-tree and assert each rule catches it — the drill
  that proves the gate can actually fail;
- ``--write-baseline``: capture current findings as the baseline
  (placeholder notes — edit in the real reasons before committing);
- ``--budget S``: fail if the whole invocation exceeded S seconds
  (CI asserts the gate stays cheap enough to run on every PR).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from icikit.analysis import baseline as _baseline
from icikit.analysis.core import (
    Project,
    all_rules,
    repo_root,
    run_rules,
)


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="python -m icikit.analysis",
        description="unified AST static-analysis suite (docs/"
                    "ANALYSIS.md)")
    p.add_argument("--root", default=None,
                   help="repo root to analyze (default: this repo)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset (default: all)")
    p.add_argument("--gate", action="store_true",
                   help="exit nonzero on any unbaselined finding")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write machine-readable findings ('-' = "
                        "stdout)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file (default: tools/"
                        "analysis_baseline.json under --root)")
    p.add_argument("--write-baseline", action="store_true",
                   help="capture current findings as the baseline")
    p.add_argument("--self-check", action="store_true",
                   help="seeded-violation drill: prove each seedable "
                        "rule still fires")
    p.add_argument("--budget", type=float, default=None, metavar="S",
                   help="fail if the run took more than S seconds")
    p.add_argument("--list", action="store_true",
                   help="list registered rules and exit")
    return p.parse_args(argv)


def main(argv=None) -> int:
    t0 = time.monotonic()
    args = _parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    if args.list:
        for r in all_rules():
            kind = "runtime" if r.runtime else "static"
            print(f"{r.name:16s} [{kind}] {r.doc}")
        return 0

    root = os.path.abspath(args.root or repo_root())
    names = ([n.strip() for n in args.rules.split(",") if n.strip()]
             if args.rules else None)
    project = Project(root)
    findings = run_rules(project, names)

    bl_path = args.baseline or os.path.join(
        root, _baseline.DEFAULT_BASELINE)
    if args.write_baseline:
        n = _baseline.write(bl_path, findings)
        print(f"analysis: wrote {n} baseline entries to {bl_path} — "
              "replace the placeholder notes with real reasons")
        return 0
    rule_names = [r.name for r in all_rules()] if names is None \
        else names
    # a --rules subset judges only its own entries: an entry for a
    # rule that did not run is unjudgeable, not stale
    entries = [e for e in _baseline.load(bl_path)
               if e["rule"] in set(rule_names)]
    fresh, grandfathered, stale = _baseline.split(findings, entries)
    if args.json:
        # identity, not baseline key: with a count-capped entry, the
        # overflow finding shares the key with absorbed ones but must
        # report baselined:false (it is the fresh violation)
        fresh_set = set(fresh)
        payload = {
            "version": 1,
            "root": root,
            "rules": rule_names,
            "elapsed_s": round(time.monotonic() - t0, 3),
            "counts": {"findings": len(findings),
                       "unbaselined": len(fresh),
                       "baselined": len(grandfathered),
                       "stale_baseline": len(stale)},
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "msg": f.msg,
                 "baselined": f not in fresh_set}
                for f in findings],
        }
        text = json.dumps(payload, indent=2, sort_keys=True)
        if args.json == "-":
            sys.stdout.write(text + "\n")
        else:
            with open(args.json, "w", encoding="utf-8") as f:
                f.write(text + "\n")

    for f in fresh:
        print(f.render())
    for f in grandfathered:
        print(f"{f.render()}   [baselined]")
    for e in stale:
        print(f"analysis: stale baseline entry (nothing matches it "
              f"any more — drop it): {e['rule']} @ {e['path']}: "
              f"{e['msg']!r}")

    rc = 0
    if args.self_check:
        rc = max(rc, _self_check())
    elapsed = time.monotonic() - t0
    if args.budget is not None and elapsed > args.budget:
        print(f"analysis FAILED: run took {elapsed:.1f}s, over the "
              f"--budget {args.budget:.0f}s ceiling — a gate this "
              "slow stops being run on every PR")
        rc = max(rc, 1)
    n_rules = len(rule_names)
    if fresh:
        print(f"analysis: {len(fresh)} unbaselined finding(s) "
              f"({len(grandfathered)} baselined) across {n_rules} "
              f"rules in {elapsed:.1f}s")
        if args.gate:
            return 1
        return rc
    print(f"analysis OK: {n_rules} rules, "
          f"{len(grandfathered)} baselined finding(s), 0 unbaselined, "
          f"{elapsed:.1f}s")
    return rc


# -- the seeded-violation drill --------------------------------------

# rule -> (relative path, file content): ONE violation each, planted
# in a synthetic mini-tree. Runtime rules (quant-arena, chaos-site's
# registry half) need the real package and are proven by the pytest
# corpus instead; the drill covers every purely-static rule.
SEEDS = {
    "serve-key": ("icikit/serve/seeded.py",
                  "import numpy as np\n"
                  "tok = np.random.randint(0, 7)\n"),
    "serve-clock": ("icikit/serve/clocked.py",
                    "import time\nt0 = time.time()\n"),
    "obs-print": (
        "icikit/telemetry_leak.py",
        "import json\n"
        "print(json.dumps({'a': 1}))\n"),  # icikit-lint: off[obs-print]
    "host-sync": ("icikit/serve/engine.py",
                  "def _step(self):\n"
                  "    outs = self._step_fns[0](1)\n"
                  "    for o in range(4):\n"
                  "        x = float(outs)\n"),
    "lock-discipline": ("icikit/obs/locked.py",
                        "import time\n"
                        "class S:\n"
                        "    def f(self):\n"
                        "        with self._lock:\n"
                        "            t = time.monotonic()\n"),
    "tree-accept": (
        "icikit/models/transformer/other.py",
        "def _accept_window(x):\n    return x\n"),  # icikit-lint: off[tree-accept]
}


def _self_check() -> int:
    """Plant each seed in a temp mini-tree and assert its rule fires
    — the drill that distinguishes "the gate passed" from "the gate
    can no longer fail"."""
    import shutil
    import tempfile

    failed = []
    for rule_name, (rel, content) in sorted(SEEDS.items()):
        tmp = tempfile.mkdtemp(prefix="icikit_analysis_drill_")
        try:
            path = os.path.join(tmp, rel)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                f.write(content)
            if rule_name == "tree-accept":
                # the duplicate-definition seed needs the canonical
                # home to exist, or every finding is about absence
                spec = os.path.join(
                    tmp, "icikit/models/transformer/speculative.py")
                with open(spec, "w", encoding="utf-8") as f:
                    f.write("def _accept_window(x):\n    return x\n"  # icikit-lint: off[tree-accept]
                            "def _accept_tree(x):\n"  # icikit-lint: off[tree-accept]
                            "    return _accept_window(x)\n")
                eng = os.path.join(tmp, "icikit/serve/engine.py")
                os.makedirs(os.path.dirname(eng), exist_ok=True)
                with open(eng, "w", encoding="utf-8") as f:
                    f.write("# _accept_window _accept_tree\n")
            got = run_rules(Project(tmp), [rule_name])
            if not any(f.rule == rule_name and f.path == rel
                       for f in got):
                failed.append(rule_name)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    if failed:
        print("analysis self-check FAILED: seeded violations not "
              f"caught by: {', '.join(failed)} — the gate cannot "
              "fail any more; fix the rule before trusting a green "
              "run")
        return 1
    print(f"analysis self-check OK: {len(SEEDS)} seeded violations "
          "each caught by their rule")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
