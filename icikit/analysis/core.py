"""Framework core: file/parse cache, Finding model, rule registry,
suppressions.

Every rule sees the repo through one :class:`Project` — files are read
and AST-parsed at most once per run no matter how many rules consume
them, and findings flow back as :class:`Finding` records that the CLI
renders (human or ``--json``), filters through per-line suppressions,
and gates against the committed baseline.

Escape hatches, in order of preference:

- fix the code;
- a per-line suppression ``# icikit-lint: off[rule]`` (or
  ``off[rule-a,rule-b]``, or bare ``off`` for every rule) WITH a
  justification in the surrounding comment — for documented fence
  sites and deliberate negatives;
- a baseline entry in ``tools/analysis_baseline.json`` with a
  ``note`` saying why — for grandfathered findings a fix cannot ride
  the current PR.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

# `# icikit-lint: off` or `# icikit-lint: off[rule-a,rule-b]` anywhere
# in the line suppresses findings (all rules / the named rules) ON
# that line. The legacy `# chaos-site-lint: off` marker is honored by
# the chaos-site rule itself (pre-framework deliberate negatives).
_SUPPRESS_RE = re.compile(
    r"#\s*icikit-lint:\s*off(?:\[([A-Za-z0-9_,\s-]*)\])?")


@dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at one source location. ``path`` is
    repo-relative (posix separators) so findings, suppressions, and
    baseline entries compare stably across machines."""

    rule: str
    path: str
    line: int
    msg: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"

    def baseline_key(self) -> tuple:
        # line numbers shift under unrelated edits; grandfathering
        # keys on the stable triple instead
        return (self.rule, self.path, self.msg)


class SourceFile:
    """One cached source file: text, split lines, lazily-parsed AST,
    and the per-line suppression table."""

    def __init__(self, root: str, rel: str):
        self.rel = rel
        self.abspath = os.path.join(root, rel)
        with open(self.abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self._tree = None
        self._parse_error: SyntaxError | None = None
        self._suppress: dict[int, set | None] | None = None

    @property
    def tree(self) -> ast.Module | None:
        """The parsed AST (cached), or None on a syntax error — the
        runner reports unparsable files once, rules just skip them."""
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text, self.rel)
            except SyntaxError as e:
                self._parse_error = e
        return self._tree

    @property
    def parse_error(self) -> SyntaxError | None:
        _ = self.tree
        return self._parse_error

    def suppressed(self, line: int, rule_name: str) -> bool:
        """Is ``rule_name`` suppressed on 1-based ``line``?"""
        if self._suppress is None:
            table: dict[int, set | None] = {}
            for i, text in enumerate(self.lines, 1):
                m = _SUPPRESS_RE.search(text)
                if not m:
                    continue
                names = m.group(1)
                if names is None or not names.strip():
                    table[i] = None          # bare off: every rule
                else:
                    table[i] = {n.strip() for n in names.split(",")
                                if n.strip()}
            self._suppress = table
        rules = self._suppress.get(line, ())
        return rules is None or rule_name in rules


class Project:
    """The analyzed tree. ``root`` is the repo root; ``file()`` and
    the ``iter_*`` walkers hand out cached :class:`SourceFile`
    objects, so N rules over M files parse each file once."""

    #: data fixtures, not code under the invariants: the seeded-
    #: violation corpus MUST stay out of the real tree's walk or the
    #: gate would flag its own test fixtures
    EXCLUDE = ("tests/analysis_corpus",)

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._files: dict[str, SourceFile | None] = {}

    def file(self, rel: str) -> SourceFile | None:
        """The cached file at repo-relative ``rel`` (None if absent)."""
        rel = rel.replace(os.sep, "/")
        if rel not in self._files:
            abspath = os.path.join(self.root, rel)
            self._files[rel] = (SourceFile(self.root, rel)
                                if os.path.isfile(abspath) else None)
        return self._files[rel]

    def iter_py(self, prefix: str = "", top_only: bool = False):
        """Every ``.py`` file under ``prefix`` (repo-relative, sorted;
        ``top_only`` pins the chaos-site rule's historical
        non-recursive scan of tests/ and tools/)."""
        base = os.path.join(self.root, prefix) if prefix else self.root
        if not os.path.isdir(base):
            return
        if top_only:
            for name in sorted(os.listdir(base)):
                if name.endswith(".py"):
                    rel = f"{prefix}/{name}" if prefix else name
                    if not self._excluded(rel):
                        yield self.file(rel)
            return
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__")
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                rel = os.path.relpath(
                    os.path.join(dirpath, name),
                    self.root).replace(os.sep, "/")
                if not self._excluded(rel):
                    yield self.file(rel)

    def _excluded(self, rel: str) -> bool:
        return any(rel == e or rel.startswith(e + "/")
                   for e in self.EXCLUDE)

    def makefile_text(self) -> str:
        path = os.path.join(self.root, "Makefile")
        if not os.path.isfile(path):
            return ""
        with open(path, encoding="utf-8") as f:
            return f.read()


@dataclass
class Rule:
    """One registered analysis. ``check(project)`` returns raw
    findings; the runner applies suppressions, dedupe, and ordering.
    ``runtime=True`` marks rules that import icikit/jax and execute
    code (the ported quant arena checks) — they are skipped by
    ``--self-check``'s synthetic-tree drill, which has no package to
    import."""

    name: str
    doc: str
    check: object = field(repr=False)
    runtime: bool = False


_REGISTRY: dict[str, Rule] = {}


def rule(name: str, doc: str, runtime: bool = False):
    """Decorator: register ``fn(project) -> list[Finding]`` as a
    rule."""
    def deco(fn):
        if name in _REGISTRY:
            raise ValueError(f"duplicate rule {name!r}")
        _REGISTRY[name] = Rule(name=name, doc=doc, check=fn,
                               runtime=runtime)
        return fn
    return deco


def all_rules() -> list[Rule]:
    _load_rules()
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(name: str) -> Rule:
    _load_rules()
    if name not in _REGISTRY:
        known = ", ".join(sorted(_REGISTRY))
        raise KeyError(f"unknown rule {name!r} (known: {known})")
    return _REGISTRY[name]


def _load_rules() -> None:
    # importing the package registers every rule via the decorator
    import icikit.analysis.rules  # noqa: F401


def repo_root() -> str:
    """The repo root this installed package belongs to (two levels up
    from icikit/analysis/)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def shim_main(rule_name: str, ok_msg: str) -> int:
    """The whole body of a ``tools/*_lint.py`` backward-compat shim:
    run ONE rule against this repo, print findings, keep the old
    exit-code contract (nonzero on a hit, the familiar OK line on a
    pass). Shared here so rendering/exit semantics cannot drift
    between the five shims."""
    findings = run_rules(Project(repo_root()), [rule_name])
    for f in findings:
        print(f.render())
    if findings:
        return 1
    print(ok_msg)
    return 0


def run_rules(project: Project, names=None) -> list[Finding]:
    """Run the named rules (default: all) and return suppressed-
    filtered, deduplicated findings in (path, line, rule) order.
    Unparsable files surface as one ``parse-error`` finding each, so
    a syntax error can never silently blind every rule at once."""
    _load_rules()
    rules = ([get_rule(n) for n in names] if names is not None
             else all_rules())
    findings: set[Finding] = set()
    for r in rules:
        for f in r.check(project):
            sf = project.file(f.path)
            if sf is not None and sf.suppressed(f.line, f.rule):
                continue
            findings.add(f)
    for rel, sf in sorted(project._files.items()):
        # .py only: the Makefile lands in the cache via suppression
        # lookups on its findings and is not meant to parse
        if (rel.endswith(".py") and sf is not None
                and sf.parse_error is not None):
            e = sf.parse_error
            findings.add(Finding("parse-error", rel, e.lineno or 0,
                                 f"syntax error: {e.msg}"))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
