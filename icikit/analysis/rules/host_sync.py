"""``host-sync`` — no implicit device→host synchronization inside
the hot loops.

The discipline this rule enforces was established by hand twice: the
r13 "drain verdicts at fences" fix (per-step device-guard verdicts
accumulate un-synced and materialize at the logging fence) and the
r16 "one async snapshot, no per-page readback" fix (batched eviction
capture). A ``float()``/``int()``/``bool()``/``.item()``/
``np.asarray()`` on a jax value, or iterating one, blocks the
dispatch pipeline for a device round trip — once per call. On the
engine step loop, the decode/speculative loops, and the train step,
a per-item sync in a Python loop is exactly the regression class
reviews keep catching.

Mechanics (dataflow-lite, per scoped function):

- **taint**: values returned by jitted/step-program calls (``*_fn``,
  ``*_fns[...]``, ``_build_*(...)(...)``), ``jnp.*``/``jax.*``
  constructors, pool arenas (``.buffers()``), and the generate entry
  points are device-tainted; taint follows assignment, tuple
  unpacking, method calls on tainted objects, and container append →
  iterate;
- **sinks**: ``float``/``int``/``bool`` on a tainted value,
  ``.item()``/``.tolist()``, ``np.asarray``/``np.array``,
  ``jax.device_get``, ``.block_until_ready()``, and ``for``-iteration
  over a device value;
- **fences**: functions in :data:`SCOPES` marked as fences are the
  DOCUMENTED sync sites (the engine step's one batched
  ``np.asarray`` drain, the prefill-completion tok0 readback, the
  train loop's log-boundary materialization). In a fence, sinks
  outside any loop are the contract and pass; sinks INSIDE a loop
  (or in a non-fence scope) are findings. Iteration over a device
  value is per-item by construction and always flagged.

A sync the discipline genuinely requires per step (the host-guard
sentinel) carries a justified ``# icikit-lint: off[host-sync]``.
"""

from __future__ import annotations

import ast
import re

from icikit.analysis.core import Finding, rule

#: path -> {function name: is_documented_fence}. The hot loops this
#: repo's perf story hangs on; extend when a new loop ships.
SCOPES = {
    "icikit/serve/engine.py": {
        "_step": True, "_prefill_chunk": True, "_prefill_whole": True,
        "_advance_prefill": False, "_advance_waiter": False,
        "_advance_restore": False, "run": False,
    },
    "icikit/models/transformer/train.py": {"_guarded_main": True},
    "icikit/models/transformer/decode.py": {
        "greedy_generate": True, "sample_generate": True,
    },
    "icikit/models/transformer/speculative.py": {
        # the host loop both public entry points delegate to
        "_run_speculative": True,
    },
}

# a call whose result lives on device: jitted/step programs, jax/jnp
# constructors, pool arenas, the generate entry points
_TAINT_CALL = re.compile(
    r"(_fns?\[|\b\w+_fn\b|^fn$|\bjnp\.|\bjax\.(?!device_get)"
    r"|\.buffers$|\b(?:sample|greedy|speculative)_generate$"
    r"|_build_\w+\()")

# host-materializing wrappers: applying one IS the sync event; the
# RESULT is host memory (assignment through one clears taint)
_SYNC_CALL = re.compile(
    r"^(?:np|numpy)\.(?:asarray|array)$|^jax\.device_get$")

_CONVERTERS = {"float", "int", "bool"}
_SYNC_ATTRS = {"item", "tolist"}


def _unparse(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


class _FnScan:
    """One pass over one scoped function, statements in source order;
    nested defs (the train drains) run last so closures see the
    parent's final taint."""

    def __init__(self, sf, fn: ast.FunctionDef, fence: bool):
        self.sf = sf
        self.fn = fn
        self.fence = fence
        self.device: set = set()      # names bound to device values
        self.container: set = set()   # host containers OF device values
        self.loop = 0
        self.findings: list = []
        self._deferred: list = []

    def run(self) -> list:
        for stmt in self.fn.body:
            self.stmt(stmt)
        while self._deferred:
            inner = self._deferred.pop(0)
            self.loop = 0
            for stmt in inner.body:
                self.stmt(stmt)
        return self.findings

    # -- taint queries ----------------------------------------------

    def tainted(self, node) -> bool:
        """Does evaluating ``node`` yield a device value? Sync
        wrappers launder (their result is host); method calls on a
        tainted object and taint-source calls taint."""
        if isinstance(node, ast.Name):
            return node.id in self.device
        if isinstance(node, ast.Call):
            src = _unparse(node.func)
            if _SYNC_CALL.search(src) or (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _CONVERTERS):
                return False
            if _TAINT_CALL.search(src):
                return True
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr not in _SYNC_ATTRS
                    and self.tainted(node.func.value)):
                return True      # m.items() on a device-holding dict
            return any(self.tainted(a) for a in node.args) or any(
                self.tainted(kw.value) for kw in node.keywords)
        if isinstance(node, (ast.Attribute, ast.Subscript,
                             ast.Starred)):
            return self.tainted(node.value)
        if isinstance(node, (ast.BinOp, ast.BoolOp, ast.UnaryOp,
                             ast.Compare, ast.Tuple, ast.List,
                             ast.IfExp)):
            return any(self.tainted(c)
                       for c in ast.iter_child_nodes(node)
                       if isinstance(c, ast.expr))
        return False

    def _sink(self, node, what: str, always: bool = False) -> None:
        """Record a sync event. In a fence function, a sync OUTSIDE
        any loop is the documented contract; everywhere else (and in
        every loop) it is a finding."""
        if not always and self.fence and self.loop == 0:
            return
        where = ("inside a loop — one device round trip PER "
                 "ITERATION; batch the transfer at a fence "
                 "(one jax.device_get / np.asarray of the whole "
                 "batch)" if self.loop
                 else "outside the documented fences — move it to a "
                      "fence or batch it")
        self.findings.append(Finding(
            "host-sync", self.sf.rel, node.lineno,
            f"implicit device->host sync: {what} {where}"))

    # -- expression scan --------------------------------------------

    def scan(self, node) -> None:
        """Find sync events in an expression tree."""
        if node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                src = _unparse(sub.func)
                if (isinstance(sub.func, ast.Name)
                        and sub.func.id in _CONVERTERS
                        and any(self.tainted(a) for a in sub.args)):
                    self._sink(sub, f"{sub.func.id}() materializes a "
                                    "device value")
                elif (_SYNC_CALL.search(src)
                      and (any(self.tainted(a) for a in sub.args)
                           or any(self.tainted(kw.value)
                                  for kw in sub.keywords))):
                    self._sink(sub, f"{src}() materializes a device "
                                    "value")
                elif (isinstance(sub.func, ast.Attribute)
                      and sub.func.attr in _SYNC_ATTRS
                      and self.tainted(sub.func.value)):
                    self._sink(sub, f".{sub.func.attr}() on a device "
                                    "value")
                elif (isinstance(sub.func, ast.Attribute)
                      and sub.func.attr == "block_until_ready"):
                    self._sink(sub, ".block_until_ready()")
            elif (isinstance(sub, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp))):
                self._comp(sub)

    def _comp(self, node) -> None:
        """Comprehensions are loops: taint their targets from the
        iterable and scan their element exprs one loop level down.
        (ast.walk above will also revisit inner calls at the outer
        depth, but a finding found at EITHER depth dedupes on line.)"""
        for gen in node.generators:
            if self.tainted(gen.iter):
                self._sink(gen.iter, "iteration over a device value "
                                     "(one sync per element)",
                           always=True)
            taints = self.tainted(gen.iter) or (
                isinstance(gen.iter, ast.Name)
                and gen.iter.id in self.container)
            for t in ast.walk(gen.target):
                if isinstance(t, ast.Name):
                    # rebinding from a HOST iterable clears stale
                    # taint an enclosing scope left on the name
                    (self.device.add if taints
                     else self.device.discard)(t.id)
        self.loop += 1
        for field in ("elt", "key", "value"):
            self.scan(getattr(node, field, None))
        self.loop -= 1

    # -- statements --------------------------------------------------

    def stmt(self, node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._deferred.append(node)
            return
        if isinstance(node, ast.Assign):
            self.scan(node.value)
            self._assign(node.targets, node.value)
            return
        if isinstance(node, ast.AugAssign):
            self.scan(node.value)
            if self.tainted(node.value):
                self._assign([node.target], node.value)
            return
        if isinstance(node, ast.AnnAssign):
            self.scan(node.value)
            if node.value is not None:
                self._assign([node.target], node.value)
            return
        if isinstance(node, ast.For):
            self.scan(node.iter)
            if self.tainted(node.iter):
                self._sink(node.iter,
                           "for-iteration over a device value (one "
                           "sync per element)", always=True)
            taints = self.tainted(node.iter) or (
                isinstance(node.iter, ast.Name)
                and node.iter.id in self.container)
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    # rebinding from a HOST iterable clears stale
                    # taint an enclosing scope left on the name
                    (self.device.add if taints
                     else self.device.discard)(t.id)
            self.loop += 1
            for s in node.body:
                self.stmt(s)
            self.loop -= 1
            for s in node.orelse:
                self.stmt(s)
            return
        if isinstance(node, ast.While):
            # unlike a for-iter (evaluated once), the test re-runs
            # every iteration: a sync in it is a per-iteration sync
            self.loop += 1
            self.scan(node.test)
            for s in node.body:
                self.stmt(s)
            self.loop -= 1
            for s in node.orelse:
                self.stmt(s)
            return
        if isinstance(node, ast.If):
            self.scan(node.test)
            for s in node.body + node.orelse:
                self.stmt(s)
            return
        if isinstance(node, ast.With):
            for item in node.items:
                self.scan(item.context_expr)
            for s in node.body:
                self.stmt(s)
            return
        if isinstance(node, ast.Try):
            for s in (node.body + node.orelse + node.finalbody):
                self.stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self.stmt(s)
            return
        if isinstance(node, ast.Expr):
            self.scan(node.value)
            # container taint: host_list.append(<device value>)
            v = node.value
            if (isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Attribute)
                    and v.func.attr == "append"
                    and isinstance(v.func.value, ast.Name)
                    and any(self.tainted(a) for a in v.args)):
                self.container.add(v.func.value.id)
            return
        if isinstance(node, (ast.Return, ast.Raise, ast.Assert,
                             ast.Delete)):
            for c in ast.iter_child_nodes(node):
                if isinstance(c, ast.expr):
                    self.scan(c)
            return
        # Pass/Break/Continue/Import/Global/Nonlocal: nothing to do

    def _assign(self, targets, value) -> None:
        tainted = self.tainted(value)
        # assignment THROUGH a sync wrapper launders: x = np.asarray(x)
        if (isinstance(value, ast.Call)
                and (_SYNC_CALL.search(_unparse(value.func))
                     or (isinstance(value.func, ast.Name)
                         and value.func.id in _CONVERTERS))):
            tainted = False
        for tgt in targets:
            for t in ast.walk(tgt):
                if isinstance(t, ast.Name):
                    (self.device.add if tainted
                     else self.device.discard)(t.id)


@rule("host-sync",
      "no implicit device->host sync inside the engine step / decode "
      "/ train hot loops (fences excepted)")
def check_host_sync(project) -> list:
    out = []
    for rel, scope in SCOPES.items():
        sf = project.file(rel)
        if sf is None or sf.tree is None:
            continue
        seen: set = set()
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.FunctionDef)
                    and node.name in scope
                    and node.name not in seen):
                seen.add(node.name)
                out.extend(_FnScan(sf, node, scope[node.name]).run())
    return out
