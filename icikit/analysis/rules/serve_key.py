"""``serve-key`` — no unkeyed randomness inside ``icikit/serve/``.

Port of ``tools/serve_key_lint.py`` (semantics pinned by
tests/test_analysis.py): every random draw in the serving path is
keyed by the schedule-invariant per-request counter
``fold_in(fold_in(key(0), seed), position)``, derived in ONE place
(``icikit.models.transformer.decode.request_stream_data``) and
threaded through as data. Any other randomness — ``np.random``, a
bare ``PRNGKey``/``jax.random.key`` minted at a sample site, host RNG
seeding, a time-seeded key — would silently re-tie sampled tokens to
engine state (batch slot, step count, wall clock) and break both the
engine ≡ ``sample_generate`` identity pin and bitwise reissue after a
lease reap. The ancestor stripped ``#`` comments before matching;
this port does the same.
"""

from __future__ import annotations

import re

from icikit.analysis.core import Finding, rule

# pattern -> why it is banned in icikit/serve/
BANNED = [
    (re.compile(r"np\.random|numpy\.random"),
     "np.random draws are unkeyed — route randomness through the "
     "request's counter stream (decode.request_stream_data)"),
    (re.compile(r"\bPRNGKey\s*\("),
     "bare PRNGKey at a sample site — streams must come from the "
     "per-request seed (decode.request_stream_data)"),
    (re.compile(r"jax\.random\.key\s*\(|random\.key\s*\("),
     "key construction inside icikit/serve — the ONE stream "
     "derivation lives in decode.request_stream_data"),
    (re.compile(r"\brandom\.seed\s*\(|\bdefault_rng\s*\("),
     "host RNG seeding in the serving path"),
    (re.compile(r"key\s*\(\s*int\s*\(\s*time|seed\s*=\s*time\."),
     "time-seeded keys are schedule-dependent by construction"),
]


@rule("serve-key", "no unkeyed randomness inside icikit/serve/")
def check_serve_key(project) -> list:
    out = []
    for sf in project.iter_py("icikit/serve"):
        for ln, text in enumerate(sf.lines, 1):
            stripped = text.split("#", 1)[0]
            for pat, why in BANNED:
                if pat.search(stripped):
                    out.append(Finding("serve-key", sf.rel, ln, why))
    return out
