"""``journal-discipline`` — queue mutations journal, fleet code
never reaches around the journal.

The r18 HA contract: the coordinator's ``RequestQueue`` is rebuilt
bitwise from its journal, which only works if EVERY mutation verb
appends a record before the RPC that caused it is acked. Two ways to
silently break that:

1. a new (or edited) ``RequestQueue`` verb mutates queue state —
   pushes to the heap, touches the lease table, lands a request in
   ``done``/``failed`` — without calling ``self._journal(...)``.
   Replay then reconstructs a queue that never saw the mutation: the
   standby promotes with a DIFFERENT state and the bitwise bar breaks
   at the worst time (mid-failover).
2. fleet-layer code pokes the queue's private state directly
   (``queue._leases[...] = ...``) instead of going through a verb —
   same corruption, committed from outside the file.

Exemptions are the verbs whose non-journaling is the DESIGN:
``renew`` (deadlines are re-based at restore, journaling every
heartbeat would bloat the log), ``expire`` (only poisons deadlines;
the reap that follows journals the effect), and the replay/restore
helpers themselves (``apply_record`` etc. — journaling replay would
double every record).
"""

from __future__ import annotations

import ast
import re

from icikit.analysis.core import Finding, rule

SCHEDULER = "icikit/serve/scheduler.py"

# state-mutating shapes inside RequestQueue methods (comment-stripped
# line text)
MUTATIONS = [
    re.compile(r"heapq\.heappush\(\s*self\._queued"),
    re.compile(r"self\._leases\[[^\]]*\]\s*="),
    re.compile(r"self\._leases\.pop\b"),
    re.compile(r"del\s+self\._leases"),
    re.compile(r"self\.done\[[^\]]*\]\s*="),
    re.compile(r"self\.failed\[[^\]]*\]\s*="),
]

_JOURNAL_CALL = re.compile(r"self\._journal\(")

# verbs whose non-journaling is deliberate (see module docstring) and
# the replay/restore machinery itself
EXEMPT = {
    "__init__", "renew", "expire", "_lease_live",
    "apply_record", "_restore_locked", "_requeue_locked",
    "_apply_handoff_locked", "_discard_entry_locked",
    "finalize_replay",
}

# fleet code reaching into the queue's journaled-state internals
REACH_IN = re.compile(
    r"queue\._(queued|leases|requests|limbo|ids|seq_hwm|lock)\b")

FLEET_PREFIX = "icikit/fleet"
# the journal module IS the replay machinery: it owns the reach
FLEET_EXEMPT = ("icikit/fleet/journal.py",)


def _methods(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "RequestQueue":
            for m in node.body:
                if isinstance(m, ast.FunctionDef):
                    yield m


@rule("journal-discipline",
      "RequestQueue mutation verbs journal before ack; fleet code "
      "never pokes queue internals around the journal")
def check_journal_discipline(project) -> list:
    out = []
    sf = project.file(SCHEDULER)
    if sf is not None and sf.tree is not None:
        for m in _methods(sf.tree):
            if m.name in EXEMPT:
                continue
            body = sf.lines[m.lineno - 1:(m.end_lineno or m.lineno)]
            stripped = [ln.split("#", 1)[0] for ln in body]
            journals = any(_JOURNAL_CALL.search(ln)
                           for ln in stripped)
            if journals:
                continue
            for off, ln in enumerate(stripped):
                if any(pat.search(ln) for pat in MUTATIONS):
                    out.append(Finding(
                        "journal-discipline", sf.rel,
                        m.lineno + off,
                        f"RequestQueue.{m.name}() mutates journaled "
                        "state without self._journal(...) — replay "
                        "would rebuild a queue that never saw this "
                        "mutation (add a verb record, or add the "
                        "method to the rule's EXEMPT set with the "
                        "why)"))
                    break
    for fsf in project.iter_py(FLEET_PREFIX):
        if fsf.rel in FLEET_EXEMPT:
            continue
        for ln_no, text in enumerate(fsf.lines, 1):
            stripped = text.split("#", 1)[0]
            if REACH_IN.search(stripped):
                out.append(Finding(
                    "journal-discipline", fsf.rel, ln_no,
                    "fleet code touches RequestQueue internals "
                    "directly — mutations must go through a "
                    "journaled verb or replay diverges"))
    return out
