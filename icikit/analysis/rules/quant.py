"""``quant-arena`` — no high-precision KV is ALLOCATED on the int8
decode path.

Port of ``tools/quant_lint.py``. Unlike the AST rules this one is a
RUNTIME check (``runtime=True``): it builds the actual pool / traces
the actual programs, because the invariant lives in jaxprs and buffer
dtypes, not in source text. "Allocated" means the persistent cache
stores — pool arenas and the loop-carried cache buffers — not
transient fused values (an int8 operand upcast inside a matmul never
owns HBM). Four mechanical checks, each a finding on violation:

1. ``KVPool(quant="int8")`` holds ONLY int8 arenas + fp32 scale pages;
2. the int8 generate program's decode loop carries int8 caches (no
   floating-point cache-shaped aval in the scan/while carries);
3. the int8 engine's step-program buffer pytree round-trips int8;
4. the sealed-block digest covers the int8 arena's SCALE pages — a
   flipped scale corrupts decoded tokens exactly like a flipped int8
   byte, so it must flip the digest too.

Requires ``JAX_PLATFORMS=cpu`` (the CLI sets it defensively).
"""

from __future__ import annotations

from icikit.analysis.core import Finding, rule

KVPOOL = "icikit/serve/kvpool.py"
DECODE = "icikit/models/transformer/decode.py"


def _tiny_cfg(max_seq: int, **kw):
    from icikit.models.transformer import TransformerConfig
    return TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                             d_ff=32, n_layers=2, max_seq=max_seq,
                             compute_dtype="float32", **kw)


def check_pool() -> list:
    import jax.numpy as jnp

    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(_tiny_cfg(32), mesh, n_blocks=4, block_size=4,
                  quant="int8")
    if pool.kc is not None or pool.vc is not None:
        return [Finding("quant-arena", KVPOOL, 0,
                        "int8 pool allocated a high-precision KV "
                        "arena")]
    out = []
    for name, want in (("qkc", jnp.int8), ("qvc", jnp.int8),
                       ("ksc", jnp.float32), ("vsc", jnp.float32)):
        for buf in getattr(pool, name):
            if buf.dtype != want:
                out.append(Finding(
                    "quant-arena", KVPOOL, 0,
                    f"int8 pool arena {name} is {buf.dtype}, "
                    f"expected {want}"))
    if set(pool.buffers()) != {"qkc", "qvc", "ksc", "vsc"}:
        out.append(Finding(
            "quant-arena", KVPOOL, 0,
            f"int8 pool buffers() exposes {set(pool.buffers())}, "
            "expected exactly qkc/qvc/ksc/vsc"))
    return out


def _float_cache_avals(jaxpr, cache_shape_tail):
    """Recursively collect scan/while carry avals that are floating
    point AND cache-shaped — the allocation smoking gun."""
    import jax.numpy as jnp
    bad = []

    def visit(jx):
        for eqn in jx.eqns:
            sub = []
            if eqn.primitive.name == "scan":
                inner = eqn.params["jaxpr"].jaxpr
                n_carry = eqn.params["num_carry"]
                sub = [v.aval for v in inner.invars[:n_carry]]
                visit(inner)
            elif eqn.primitive.name == "while":
                inner = eqn.params["body_jaxpr"].jaxpr
                sub = [v.aval for v in inner.invars]
                visit(inner)
            else:
                for p in eqn.params.values():
                    core = getattr(p, "jaxpr", None)
                    if core is not None and hasattr(core, "eqns"):
                        visit(core)
            for a in sub:
                shape = getattr(a, "shape", ())
                if (len(shape) >= len(cache_shape_tail)
                        and tuple(shape[-len(cache_shape_tail):])
                        == cache_shape_tail
                        and jnp.issubdtype(a.dtype, jnp.floating)):
                    bad.append(a)

    visit(jaxpr)
    return bad


def check_generate() -> list:
    import dataclasses

    import jax
    import jax.numpy as jnp

    from icikit.models.transformer import init_params
    from icikit.models.transformer.decode import (
        _build_generate,
        maybe_quantize_params,
    )
    from icikit.models.transformer.model import make_model_mesh

    cfg = _tiny_cfg(64, decode_quant="int8")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(
        jax.random.key(0),
        dataclasses.replace(cfg, decode_quant="none"), mesh)
    qp = maybe_quantize_params(params, mesh, cfg)
    s_prompt, n_new = 8, 12
    fn = _build_generate(mesh, cfg, s_prompt, n_new)
    prompt = jnp.zeros((2, s_prompt), jnp.int32)
    seeds = jnp.zeros((2,), jnp.int32)
    key_data = jax.random.key_data(jax.random.key(0))
    knobs = jnp.ones((3,), jnp.float32)
    jaxpr = jax.make_jaxpr(fn)(qp, prompt, seeds, key_data, knobs)
    kv = cfg.n_kv_heads or cfg.n_heads
    tail = (s_prompt + n_new, kv, cfg.d_head)
    bad = _float_cache_avals(jaxpr.jaxpr, tail)
    if bad:
        return [Finding(
            "quant-arena", DECODE, 0,
            "int8 generate carries a high-precision cache-shaped "
            f"buffer through its decode loop: {bad}")]
    return []


def check_engine() -> list:
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from icikit.models.transformer import init_params
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve import Engine, ServeConfig

    cfg = _tiny_cfg(64, decode_quant="int8")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(
        jax.random.key(0),
        dataclasses.replace(cfg, decode_quant="none"), mesh)
    eng = Engine(params, mesh, cfg,
                 ServeConfig(max_rows=2, block_size=4, n_blocks=8,
                             max_prompt=8, max_new=8))
    eng.submit(np.arange(5, dtype=np.int32), 6)
    eng.run()
    bufs = eng.pool.buffers()
    out = []
    if set(bufs) != {"qkc", "qvc", "ksc", "vsc"}:
        out.append(Finding(
            "quant-arena", KVPOOL, 0,
            f"int8 engine pool buffers() exposes {set(bufs)}"))
    elif not all(b.dtype == jnp.int8
                 for b in bufs["qkc"] + bufs["qvc"]):
        out.append(Finding(
            "quant-arena", KVPOOL, 0,
            "int8 engine step program does not round-trip int8 "
            "arenas"))
    return out


def check_block_hash_covers_scales() -> list:
    """Prefix-cache era integrity: the sealed-block digest — the one
    fingerprint every sharer of a page re-verifies — must cover the
    int8 arena's SCALE pages, not just the quantized payload."""
    import numpy as np

    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    cfg = _tiny_cfg(32)
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(cfg, mesh, n_blocks=4, block_size=4, quant="int8")
    [page] = pool.allocators[0].alloc("lint", 1)
    per_layer = len(pool.page_bytes(0, page, "q8")) // cfg.n_layers
    if per_layer != 4:
        return [Finding(
            "quant-arena", KVPOOL, 0,
            "q8 page_bytes must return qk, qv, ksc, vsc per layer, "
            f"got {per_layer} arrays")]
    data = np.arange(4 * 2 * 8, dtype=np.int8).reshape(4, 2, 8)
    pool.poke_page(0, page, 0, data)
    pool.seal(0, page)
    if pool.verify("lint", 0) != []:
        return [Finding("quant-arena", KVPOOL, 0,
                        "freshly sealed page failed its own verify")]
    vsc = list(pool.vsc)
    vsc[1] = vsc[1].at[0, page, 1, 0].add(0.5)   # ONLY a scale moves
    pool.vsc = tuple(vsc)
    if pool.verify("lint", 0) != [0]:
        return [Finding(
            "quant-arena", KVPOOL, 0,
            "a flipped scale page did NOT fail the sealed-block "
            "verify — the block hash does not cover the quantized "
            "payload's scales")]
    return []


@rule("quant-arena",
      "no high-precision KV allocated on the int8 path; block "
      "digests cover scale pages (runtime check)", runtime=True)
def check_quant(project) -> list:
    out = []
    for check in (check_pool, check_generate, check_engine,
                  check_block_hash_covers_scales):
        try:
            out.extend(check())
        except Exception as e:  # a crash is a finding, not a pass
            out.append(Finding(
                "quant-arena", KVPOOL, 0,
                f"{check.__name__} raised {type(e).__name__}: {e}"))
    return out
