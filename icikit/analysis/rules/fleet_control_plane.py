"""``fleet-control-plane`` — the fleet control plane stays host-only.

The coordinator/transport/bridge layer (``icikit/fleet/transport.py``,
``coordinator.py``, ``kvbridge.py``) must keep working while a
defective engine's device schedules are exactly what is under
suspicion, and must never stall a claim RPC behind an XLA dispatch —
so it performs NO jax device dispatch and NO jnp allocation: control
frames and KV bytes move over host sockets only (numpy views are
fine; they are host memory). Since r19 the telemetry/collector path
(``fleet/telemetry.py``, ``obs/aggregate.py``) is held to the same
contract: observability must keep flowing — and the collector must
keep answering inside the coordinator — while engine device
schedules are suspect. Same for the r20 autoscale supervisor
(``fleet/supervisor.py``): the scale policy must keep deciding while
engines' devices are the thing under load. The data plane
(``roles.py``/``worker.py`` — the engine lives there) is explicitly
out of scope.

Mechanically: flag any ``import jax``/``from jax ...`` and any
``jax.``/``jnp.`` attribute use in the control-plane modules,
comments stripped (the serve-key rule's discipline)."""

from __future__ import annotations

import re

from icikit.analysis.core import Finding, rule

CONTROL_PLANE = ("icikit/fleet/transport.py",
                 "icikit/fleet/coordinator.py",
                 "icikit/fleet/kvbridge.py",
                 "icikit/fleet/journal.py",
                 "icikit/fleet/ha.py",
                 "icikit/fleet/telemetry.py",
                 "icikit/fleet/supervisor.py",
                 "icikit/obs/aggregate.py")

BANNED = [
    (re.compile(r"^\s*(?:import|from)\s+jax\b"),
     "jax import in fleet control-plane code — the coordinator/"
     "transport/bridge layer is host-only by contract"),
    (re.compile(r"\bjnp\s*\."),
     "jnp allocation in fleet control-plane code — device arrays "
     "have no business on the claim/lease/bridge path"),
    (re.compile(r"\bjax\s*\."),
     "jax device dispatch in fleet control-plane code — the control "
     "plane must keep flowing while device schedules are suspect"),
]


@rule("fleet-control-plane",
      "no jax device dispatch / jnp allocation in the fleet "
      "coordinator/transport/bridge (control plane stays host-only)")
def check_fleet_control_plane(project) -> list:
    out = []
    for rel in CONTROL_PLANE:
        sf = project.file(rel)
        if sf is None:
            continue
        for ln, text in enumerate(sf.lines, 1):
            stripped = text.split("#", 1)[0]
            for pat, why in BANNED:
                if pat.search(stripped):
                    out.append(Finding("fleet-control-plane",
                                       sf.rel, ln, why))
    return out
