"""``lock-discipline`` — nothing slow or reentrant runs under a lock
in ``icikit/serve/`` and ``icikit/obs/``.

The two review-era incidents this rule mechanizes: the PR 2
lease-queue stall (bus emission inside ``with self._lock`` — one slow
sink stalled every admission; the fix moved every emit outside the
lock, the ``mark_dead`` discipline) and the PR 12 torn histogram read
(whose fix is the OPPOSITE shape — a single lock-scoped snapshot — so
the rule flags work under locks, never lock-scoped copying of plain
state).

Flags, lexically inside any ``with <something lock-ish>:`` block:

- bus/metric emission (``obs.emit/count/observe/gauge``) — a slow
  sink must never stall the lock's other waiters;
- device dispatch (``jnp.*``/``jax.*``, jitted ``*_fn`` programs,
  ``block_until_ready``/``device_put``/``device_get``, and the pool's
  ``*_cb`` capture callbacks) — dispatch latency is unbounded under
  contention;
- file I/O (``open``, ``json.dump``, ``os.replace``/``fsync``/...,
  ``.flush()``) — the ChunkCheckpoint retry ladder can hold a lock
  for three backoff rounds;
- ``time.*`` calls — clock reads belong on the caller's side of the
  critical section (and ``time.sleep`` under a lock is a stall by
  definition);
- with TWO locks held (lexically nested lock blocks), additionally
  any blocking call (``sleep``/``join``/``wait``/``acquire``/
  ``.result()``/``.get()``) — the deadlock-adjacent shape.

One level of helper propagation: a method called under the lock
(``self._take(...)`` from ``alloc``) is scanned for the same
patterns, because "lock held" is that helper's documented contract —
findings land at the helper's line. Deliberate exceptions (the
FileSink whose per-sink lock exists to serialize exactly that write)
are baselined with a note, not silenced in code.
"""

from __future__ import annotations

import ast
import re

from icikit.analysis.core import Finding, rule

SCOPE_PREFIXES = ("icikit/serve/", "icikit/obs/")

_LOCKISH = re.compile(r"lock", re.IGNORECASE)

# callee-text pattern -> what it is (the finding's noun phrase)
_BANNED = [
    (re.compile(r"^obs\.(emit|count|observe|gauge)$"),
     "bus/metric emission"),
    (re.compile(r"(^|\.)(jnp|jax)\.|_fns?\[|\b\w+_fn$"
                r"|block_until_ready$|device_(put|get)$|\w+_cb$"),
     "device dispatch"),
    (re.compile(r"^open$|^json\.dump(s)?$|^os\.(replace|rename|fsync"
                r"|remove|unlink|makedirs)$|\.flush$|\.write_text$"
                r"|\.read_text$|^shutil\."),
     "file I/O"),
    (re.compile(r"^time\.\w+$"), "a clock/time call"),
]

_BLOCKING = re.compile(
    r"sleep$|\.join$|\.wait$|\.acquire$|\.result$|\.recv$")


def _unparse(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _is_lock_with(node: ast.With) -> bool:
    return any(_LOCKISH.search(_unparse(item.context_expr))
               for item in node.items)


def _method_index(tree) -> dict:
    """qualname-free helper map: class name -> {method name: node}
    (module-level defs under class "")."""
    index: dict = {"": {}}
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            index[""][node.name] = node
        elif isinstance(node, ast.ClassDef):
            index[node.name] = {
                m.name: m for m in node.body
                if isinstance(m, ast.FunctionDef)}
    return index


def _banned_calls(body_nodes, *, two_locks: bool):
    """Yield (node, label) for flagged calls lexically in
    ``body_nodes`` — NOT descending into nested function defs (a def
    under a lock runs later, without it) or nested lock blocks
    (handled by the caller at the deeper lock count)."""
    stack = list(body_nodes)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.With) and _is_lock_with(node):
            continue      # inner lock block: scanned at two-lock level
        if isinstance(node, ast.Call):
            src = _unparse(node.func)
            for pat, label in _BANNED:
                if pat.search(src):
                    yield node, f"{label} ({src})"
                    break
            else:
                if two_locks and _BLOCKING.search(src):
                    yield node, f"a blocking call ({src})"
        stack.extend(ast.iter_child_nodes(node))


def _self_calls(body_nodes):
    """Method names called as ``self.X(...)`` lexically in the block
    (the one-level lock-held-helper propagation)."""
    out = []
    stack = list(body_nodes)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            out.append((node.func.attr, node.lineno))
        stack.extend(ast.iter_child_nodes(node))
    return out


@rule("lock-discipline",
      "no bus emission, device dispatch, file I/O, or time.* under "
      "with-lock blocks in icikit/serve/ + icikit/obs/")
def check_lock_discipline(project) -> list:
    out = []
    for prefix in SCOPE_PREFIXES:
        for sf in project.iter_py(prefix.rstrip("/")):
            if sf.tree is None:
                continue
            methods = _method_index(sf.tree)
            # locate every lock-with and its enclosing class + depth
            def walk(node, cls: str, locks: int):
                for child in ast.iter_child_nodes(node):
                    c_cls = (child.name
                             if isinstance(child, ast.ClassDef)
                             else cls)
                    if (isinstance(child, ast.With)
                            and _is_lock_with(child)):
                        held = locks + 1
                        lock_src = _unparse(
                            child.items[0].context_expr)
                        for call, label in _banned_calls(
                                child.body, two_locks=held >= 2):
                            out.append(Finding(
                                "lock-discipline", sf.rel,
                                call.lineno,
                                f"{label} while holding "
                                f"{'two locks' if held >= 2 else repr(lock_src)}"
                                " — run it outside the critical "
                                "section (the mark_dead discipline)"))
                        # one-level helper propagation: lock-held
                        # methods inherit the ban (the message omits
                        # the caller line so one helper violation is
                        # ONE finding however many locked callers it
                        # has — baseline entries key on the message)
                        for name, _at in _self_calls(child.body):
                            helper = methods.get(cls, {}).get(name)
                            if helper is None:
                                continue
                            for call, label in _banned_calls(
                                    helper.body, two_locks=held >= 2):
                                out.append(Finding(
                                    "lock-discipline", sf.rel,
                                    call.lineno,
                                    f"{label} in lock-held helper "
                                    f"{name}() (called under "
                                    f"{lock_src!r}) — defer it past "
                                    "the lock release"))
                        walk(child, c_cls, held)
                    else:
                        walk(child, c_cls, locks)
            walk(sf.tree, "", 0)
    return out
