"""Rule modules — importing this package registers every rule."""

from icikit.analysis.rules import (  # noqa: F401
    chaos_site,
    fleet_control_plane,
    host_sync,
    journal_discipline,
    lock_discipline,
    obs_catalog,
    quant,
    serve_key,
    telemetry,
    tree_accept,
)
