"""The two former Makefile grep lints, as framework rules.

``obs-print`` — telemetry goes through the ``icikit.obs`` event bus,
not bare prints: a bare print of a ``json.dumps`` payload outside
``icikit/obs/`` is a telemetry line no sink, filter, or metrics
registry will ever see. (The grep ancestor piped the print-of-dumps
pattern through ``grep -v '^icikit/obs/'``.)

``serve-clock`` — SLO math in ``icikit/serve/`` must use
``time.monotonic``: ``time.time()`` steps under NTP adjustment and a
stepped clock turns one TTFT sample negative and every percentile
after it garbage. (The grep ancestor: ``grep -rn "time\\.time("
icikit/serve``.)

Both keep the ancestors' raw line-match semantics (comments count —
the greps never stripped them); the framework's suppression comment
is the one new escape hatch.
"""

from __future__ import annotations

import re

from icikit.analysis.core import Finding, rule

_PRINT_DUMPS = re.compile(r"print\(json\.dumps")
_WALL_CLOCK = re.compile(r"time\.time\(")


@rule("obs-print",
      "no bare print of json.dumps telemetry outside icikit/obs/")
def check_obs_print(project) -> list:
    out = []
    for sf in project.iter_py("icikit"):
        # icikit/obs/ is the one legitimate home — everything else
        # (the analysis package included) answers to the rule; the
        # few self-matching literal sites carry per-line suppressions
        if sf.rel.startswith("icikit/obs/"):
            continue
        for ln, text in enumerate(sf.lines, 1):
            if _PRINT_DUMPS.search(text):
                # msg deliberately avoids quoting the matched pattern
                # (the rule would flag its own message otherwise)
                out.append(Finding(
                    "obs-print", sf.rel, ln,
                    "bare print of json.dumps telemetry — route it "
                    "through the icikit.obs event bus"))
    return out


@rule("serve-clock",
      "icikit/serve SLO clocks are monotonic (no time.time)")
def check_serve_clock(project) -> list:
    out = []
    for sf in project.iter_py("icikit/serve"):
        for ln, text in enumerate(sf.lines, 1):
            if _WALL_CLOCK.search(text):
                out.append(Finding(
                    "serve-clock", sf.rel, ln,
                    "wall clock in icikit/serve — SLO math must use "
                    "time.monotonic"))
    return out
