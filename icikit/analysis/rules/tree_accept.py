"""``tree-accept`` — ONE speculative accept implementation.

Port of ``tools/tree_accept_lint.py`` (round 14; semantics pinned by
tests/test_analysis.py). The token-tree verify path's exactness
argument leans on the primary chain being accepted by the *existing*
chain rule:

1. ``_accept_window`` and ``_accept_tree`` are each defined exactly
   once, in ``icikit/models/transformer/speculative.py``;
2. ``_accept_tree``'s body CALLS ``_accept_window`` (the primary
   chain goes through the one rule, not a fork of its semantics);
3. nothing else in ``icikit/`` defines its own accept, and the
   serving engine references both names (it imports the shared rule —
   the engine-vs-generate identity contract hangs on it).
"""

from __future__ import annotations

import ast

from icikit.analysis.core import Finding, rule

SPEC = "icikit/models/transformer/speculative.py"
ENGINE = "icikit/serve/engine.py"
ACCEPT_NAMES = ("_accept_window", "_accept_tree")


def _called_names(fn: ast.FunctionDef) -> set:
    names = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                names.add(f.id)
            elif isinstance(f, ast.Attribute):
                names.add(f.attr)
    return names


@rule("tree-accept",
      "one accept implementation (_accept_tree wraps _accept_window)")
def check_tree_accept(project) -> list:
    out = []
    spec = project.file(SPEC)
    if spec is None or spec.tree is None:
        return [Finding("tree-accept", SPEC, 0,
                        f"{SPEC} missing or unparsable — the shared "
                        "accept rule has no home")]
    defs: dict = {}
    for node in ast.walk(spec.tree):
        if (isinstance(node, ast.FunctionDef)
                and node.name in ACCEPT_NAMES):
            if node.name in defs:
                out.append(Finding(
                    "tree-accept", SPEC, node.lineno,
                    f"{node.name} defined more than once"))
            defs[node.name] = node
    for name in ACCEPT_NAMES:
        if name not in defs:
            out.append(Finding("tree-accept", SPEC, 0,
                               f"{name} not defined"))
    if ("_accept_tree" in defs
            and "_accept_window" not in _called_names(
                defs["_accept_tree"])):
        out.append(Finding(
            "tree-accept", SPEC, defs["_accept_tree"].lineno,
            "_accept_tree does not call _accept_window — the primary "
            "chain must run the ONE chain accept rule, not a "
            "re-implementation"))
    # no second definition anywhere else in the package (the few
    # sites quoting the sentinel text — this scan, the self-check
    # seeds — carry per-line suppressions, not a blanket pass)
    for sf in project.iter_py("icikit"):
        if sf.rel == SPEC:
            continue
        for ln, text in enumerate(sf.lines, 1):
            if ("def _accept_window" in text  # icikit-lint: off[tree-accept]
                    or "def _accept_tree" in text):  # icikit-lint: off[tree-accept]
                out.append(Finding(
                    "tree-accept", sf.rel, ln,
                    "defines its own accept — import the shared rule "
                    "from speculative.py instead"))
    # the engine consumes the shared rule, not a local fork
    eng = project.file(ENGINE)
    if eng is None:
        out.append(Finding("tree-accept", ENGINE, 0,
                           "engine missing — nothing imports the "
                           "shared accept"))
    else:
        for name in ACCEPT_NAMES:
            if name not in eng.text:
                out.append(Finding(
                    "tree-accept", ENGINE, 0,
                    f"does not reference {name} — the engine's "
                    "verify windows must run the shared accept"))
    return out
