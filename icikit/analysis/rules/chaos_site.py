"""``chaos-site`` — every chaos plan entry names a registered probe
site.

Port of ``tools/chaos_site_lint.py`` (semantics pinned by
tests/test_analysis.py). Probe sites used to be bare strings: a typo
in an ``ICIKIT_CHAOS`` spec or a drill's ``FaultPlan`` key silently
never fired — the drill "passed" while exercising nothing. Modules
register their sites at definition (``chaos.register_site``); this
rule imports every instrumented module, then scans the TOP-LEVEL test
and tool trees (the historical non-recursive walk — fixture subdirs
are data) plus the Makefile for ``kind:site-glob`` literals and fails
on any glob that cannot reach a registered site.

Review-hardened twice with no direct coverage before the port — the
helpers (:func:`collapse_holes`, the :data:`ENV_ENTRY` scanner) now
carry their own unit tests in tests/test_analysis.py:

- f-string holes collapse to a glob star BEFORE judging
  (``f"die:solitaire.worker.{w}"`` drills the registered
  ``solitaire.worker.*`` family);
- ``ENV_ENTRY`` matches the env-spec form ``corrupt:site=@0`` where
  the glob is followed by ``=value`` rather than a closing quote —
  the PR 10 regex required a closing quote and matched the Makefile's
  own spec form *never*.
"""

from __future__ import annotations

import fnmatch
import re

from icikit.analysis.core import Finding, rule

# A plan entry literal: "kind:site-glob" in quotes, f-string holes
# collapsed to a glob star before judging.
ENTRY = re.compile(
    r"""["'](delay|die|corrupt|io):([A-Za-z0-9_.*?{}\[\]-]+)["']""")

# An ICIKIT_CHAOS env-spec entry: the spec is one quoted semicolon-
# separated string ('seed=0;corrupt:serve.kv.page=@0'), so the glob is
# followed by '=value' rather than a closing quote — the Makefile's
# drills (and any subprocess env strings in tests) are written this way.
ENV_ENTRY = re.compile(
    r"""(delay|die|corrupt|io):([A-Za-z0-9_.*?{}\[\]-]+)=""")

# A direct probe call in the scanned file: the chaos-machinery unit
# tests drill synthetic sites ("w.1", "x") they probe themselves —
# those are defined, just locally. Same register-at-definition rule,
# applied to the file under scan.
LOCAL_PROBE = re.compile(
    r"""(?:maybe_delay|maybe_die|maybe_corrupt|maybe_io_fail|io_retry|"""
    r"""fires)\(\s*(?:["'][a-z]+["']\s*,\s*)?f?["']"""
    r"""([A-Za-z0-9_.{}-]+)["']""")

_HOLE = re.compile(r"\{[^}]*\}")


def collapse_holes(glob: str) -> str:
    """Collapse f-string holes to glob stars:
    ``solitaire.worker.{w}`` -> ``solitaire.worker.*``."""
    return _HOLE.sub("*", glob)


def scan_entries(text: str):
    """Every ``(lineno, kind, glob)`` plan entry in ``text`` (both
    quoted-literal and env-spec forms), holes already collapsed;
    lines carrying the legacy ``chaos-site-lint: off`` marker are
    deliberate negatives (the warn-path tests) and skipped."""
    out = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if "chaos-site-lint: off" in line:
            continue
        for kind, glob in ENTRY.findall(line) + ENV_ENTRY.findall(line):
            out.append((lineno, kind, collapse_holes(glob)))
    return out


def local_probes(text: str) -> set:
    """Sites the scanned file probes itself (hole-collapsed)."""
    return {collapse_holes(s) for s in LOCAL_PROBE.findall(text)}


def _register_everything() -> None:
    """Import every module that owns probe sites, so registration-at-
    definition has happened before we judge the globs."""
    import icikit.bench.harness  # noqa: F401
    import icikit.fleet.ha  # noqa: F401 - fleet.ha.*
    import icikit.fleet.journal  # noqa: F401 - fleet.journal/leader
    import icikit.fleet.roles  # noqa: F401 - fleet.engine.die
    import icikit.fleet.transport  # noqa: F401 - fleet.rpc.*
    import icikit.models.solitaire.scheduler  # noqa: F401
    import icikit.models.sort  # noqa: F401
    import icikit.models.transformer.decode  # noqa: F401
    import icikit.models.transformer.model  # noqa: F401
    import icikit.models.transformer.speculative  # noqa: F401
    import icikit.models.transformer.train  # noqa: F401
    import icikit.parallel.integrity  # noqa: F401
    import icikit.parallel.multihost  # noqa: F401
    import icikit.serve.engine  # noqa: F401
    import icikit.utils.checkpoint  # noqa: F401


@rule("chaos-site",
      "every tests/tools/Makefile chaos plan entry reaches a "
      "registered probe site", runtime=True)
def check_chaos_site(project) -> list:
    _register_everything()
    from icikit import chaos

    out = []

    def judge(rel, text, local):
        for lineno, kind, glob in scan_entries(text):
            if chaos.site_known(glob):
                continue
            if any(fnmatch.fnmatchcase(s, glob)
                   or fnmatch.fnmatchcase(glob, s) for s in local):
                continue  # the file probes that site itself
            # msg names ONLY the offending entry: it is the baseline
            # identity, and interpolating the (global, ever-growing)
            # registered-site list here would turn every new
            # register_site into baseline churn — list the registry
            # with `python -m icikit.analysis --list` / chaos docs
            out.append(Finding(
                "chaos-site", rel, lineno,
                f"chaos plan entry {kind}:{glob} names no registered "
                "probe site (typo, or the owning module forgot "
                "chaos.register_site)"))

    for sub in ("tests", "tools"):
        for sf in project.iter_py(sub, top_only=True):
            judge(sf.rel, sf.text, local_probes(sf.text))
    mk = project.makefile_text()
    if mk:
        judge("Makefile", mk, set())
    return out
