"""``obs-catalog`` — every emitted telemetry name is documented.

Port of ``tools/obs_catalog_lint.py`` (semantics pinned by
tests/test_analysis.py). The watch layer and the bench regression
gate both key on metric NAMES; a counter that exists in code but not
in docs/OBSERVABILITY.md is telemetry nobody can alarm on, and a
renamed counter silently orphans its alert rule. Walks ``icikit/``
for literal ``obs.count/observe/gauge/emit`` names under the
``serve.*`` / ``decode.spec.*`` / ``fleet.*`` prefixes, plus the
async request-span names the trace_ctx layer opens, and fails on any
name the catalog does not mention. The doc may document MORE than code emits — planned
names are fine; the failure mode is only code the doc lost track of.
"""

from __future__ import annotations

import re

from icikit.analysis.core import Finding, rule

DOC = "docs/OBSERVABILITY.md"

EMIT_RE = re.compile(
    r'obs\.(?:count|observe|gauge|emit)\(\s*"'
    r'((?:serve|decode\.spec|fleet)\.[^"]+)"')
# request-scoped async span/instant names (trace_ctx call sites in
# serve/: self-opens inside trace_ctx.py itself count too)
CTX_RE = re.compile(
    r'\.(?:open|close|instant|span)\(\s*"(serve\.req[^"]*)"')


def emitted_names(project) -> dict:
    """name -> (path, line) of its first emitting site."""
    names: dict = {}
    for sf in project.iter_py("icikit"):
        for ln, text in enumerate(sf.lines, 1):
            for pat in (EMIT_RE, CTX_RE):
                for name in pat.findall(text):
                    names.setdefault(name, (sf.rel, ln))
    return names


@rule("obs-catalog",
      "every serve.*/decode.spec.* telemetry name is in "
      "docs/OBSERVABILITY.md")
def check_obs_catalog(project) -> list:
    import os
    doc_path = os.path.join(project.root, DOC)
    if not os.path.isfile(doc_path):
        return [Finding("obs-catalog", DOC, 0,
                        "docs/OBSERVABILITY.md missing — the "
                        "telemetry catalog has no home")]
    with open(doc_path, encoding="utf-8") as f:
        doc = f.read()
    out = []
    for name, (rel, ln) in sorted(emitted_names(project).items()):
        if name not in doc:
            out.append(Finding(
                "obs-catalog", rel, ln,
                f"telemetry name {name!r} emitted in code but absent "
                "from docs/OBSERVABILITY.md's catalog"))
    return out
