"""Round-9 serving study: continuous batching vs static at matched
Poisson load — the reproducible command behind serve_r9.jsonl.

Runs the ``icikit.bench.serve`` workload at saturating and moderate
offered loads with high output-length variance (the regime continuous
batching exists for: short rows idle behind long rows in a static
batch), appends every record to ``serve_r9.jsonl``, and prints the
continuous/static comparison. Also appends the batch-aware speculative
break-even table (ROADMAP 3c) so the round's records are
self-contained.

Usage::

    JAX_PLATFORMS=cpu python tools/serve_study.py [--out serve_r9.jsonl]

Every row is backend-stamped; a CPU session prices the
continuous-vs-static *ratio* (occupancy accounting) — absolute
tokens/s waits on a v5e session, like every other decode-side number
in this repo (DECODE.md protocol).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

try:
    import icikit  # noqa: F401
except ModuleNotFoundError:  # `python tools/serve_study.py` from root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

from icikit.bench.decode import spec_breakeven_rows
from icikit.bench.serve import run_bench

# The committed study points. compute_dtype float32 is the CPU
# protocol (XLA:CPU re-packs bf16 weight operands per program call,
# which generate's scanned loop hoists but a per-call engine step
# cannot — an artifact a native-bf16 TPU never pays; see the note in
# icikit.bench.serve.run_bench). Rate 1000 is effectively all-at-once
# (saturated queue, the throughput comparison); rate 2.5 sits near
# ~60% of this CPU's measured ~4 req/s service rate (the latency
# comparison).
POINTS = (
    {"rows": 4, "n_requests": 16, "rate_rps": 1000.0,
     "new_min": 4, "new_max": 64, "label": "saturated",
     "mode": "both", "speculate": 1},
    {"rows": 4, "n_requests": 12, "rate_rps": 2.5,
     "new_min": 4, "new_max": 64, "label": "moderate",
     "mode": "both", "speculate": 1},
    # bonus: the zero-cost ngram drafter under the same saturated
    # trace — continuous-only (static generate has no drafter swap);
    # acceptance is workload-dependent by contract
    {"rows": 4, "n_requests": 16, "rate_rps": 1000.0,
     "new_min": 4, "new_max": 64, "label": "saturated-ngram",
     "mode": "continuous", "speculate": 3},
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="serve_r9.jsonl")
    ap.add_argument("--preset", default="small")
    ap.add_argument("--prompt", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--compute-dtype", default="float32")
    args = ap.parse_args(argv)

    rows_out = []
    for pt in POINTS:
        recs = run_bench(args.preset, pt["rows"], pt["n_requests"],
                         pt["rate_rps"], args.prompt, pt["new_min"],
                         pt["new_max"], speculate=pt["speculate"],
                         seed=args.seed, mode=pt["mode"],
                         compute_dtype=args.compute_dtype)
        for r in recs:
            r["study"] = "r9"
            r["load_label"] = pt["label"]
        rows_out.extend(recs)
        cont = next(r for r in recs if r["mode"] == "continuous")
        stat = next((r for r in recs if r["mode"] == "static"), None)
        if stat is None:
            print(f"[{pt['label']}] continuous "
                  f"{cont['tokens_per_s']} tok/s "
                  f"(occ {cont['occupancy_mean']}, "
                  f"p99 TTFT {cont['ttft_ms']['p99']} ms)")
            continue
        print(f"[{pt['label']}] continuous {cont['tokens_per_s']} tok/s "
              f"(occ {cont['occupancy_mean']}, "
              f"p99 TTFT {cont['ttft_ms']['p99']} ms)  vs  static "
              f"{stat['tokens_per_s']} tok/s "
              f"(occ {stat['occupancy_mean']}, "
              f"p99 TTFT {stat['ttft_ms']['p99']} ms)  -> "
              f"x{cont['tokens_per_s'] / stat['tokens_per_s']:.2f}")
    be = spec_breakeven_rows(preset="base")
    for r in be:
        r["study"] = "r9"
    rows_out.extend(be)
    with open(args.out, "a") as f:
        for r in rows_out:
            f.write(json.dumps(r) + "\n")
    print(f"appended {len(rows_out)} records to {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
