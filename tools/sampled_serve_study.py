"""Round-12 serving study: sampled speculation + in-flight prefill
dedup A/Bs — the reproducible command behind serve_r12.jsonl.

Two questions, each answered by paired arms over the SAME seeded
workload (matched offered load; EVERY arm re-decodes every completed
request through single-request ``generate``/``sample_generate`` with
the per-request stream seeds and asserts token identity — the r12
identity audit is what makes the sampled arms trustworthy at all):

1. **Rejection-sampled speculation** (``--speculate`` 3/4 with the
   suffix-automaton drafter vs 1, all arms sampled): served on the
   repo's standard trained toy (the decode_spec_r7/r8 protocol —
   Markov corpus, here ``branch=1`` so the chain is deterministic:
   the extractive/repetitive traffic shape where suffix-match
   drafting earns its keep, and the model trains to confident
   near-one-hot distributions, the regime real serving lives in).
   Sampled at temperature 0.3/0.7 with per-request seeds — honest
   sampled traffic, audited bitwise against ``sample_generate``.
   A RANDOM-INIT model is the wrong instrument here twice over: its
   flat distributions give the drafter nothing to match unless the
   temperature is so low that the draw is numerically knife-edged
   (fp32 reassociation between the window and single-token programs
   is amplified by 1/T — measured flips at T<=0.1), and give the
   accept rule no margin. The trained toy has both margin and
   structure; scoped probes on the random-init small preset at
   T 0.15-0.3 measured spec-sampling at/below break-even for
   exactly those reasons (identity clean, acceptance ~0.1-0.25 —
   rows not committed).
2. **In-flight prefill dedup** (``inflight_dedup`` on vs off,
   prefix cache on in both): on the duplicate-prompt Poisson
   workload (one hot prompt, concurrent arrivals, long prompt /
   short outputs — prefill-dominated), how much duplicate prefill
   compute does the waiter mechanism remove, and what does that buy
   the second arrival's TTFT? The compute ledger
   (``prefill_tokens_computed``) is exact; the wall-clock side is
   CPU-honest (dispatch-bound regimes dilute it — noted per row).

CPU wall clocks on this container drift (warm-up, shared cores), so
the speculation A/B runs INTERLEAVED repeats and commits the median
of adjacent-pair ratios — the train_ab_r6 discipline. Acceptance
(tokens/row-step) is deterministic given the seed and carries no
such noise.

Usage::

    JAX_PLATFORMS=cpu python tools/sampled_serve_study.py \
        [--out serve_r12.jsonl] [--seeds 0 1] [--reps 3]

CPU-fp32 protocol throughout (the r9 rule: XLA:CPU re-packs bf16
weight operands per program call, and the identity audit requires
matched arithmetic between the engine's per-call programs and
generate's scanned loop). Every row is backend-stamped; absolute
tokens/s waits on a v5e session like every decode-side number in
this repo.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

import numpy as np

try:
    import icikit  # noqa: F401
except ModuleNotFoundError:  # `python tools/sampled_serve_study.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

from icikit.bench.serve import run_bench

COMMON = dict(rows=4, compute_dtype="float32", mode="continuous",
              verify=True, block_size=8)

# Q1 workload shape: short prompts, long continuations (the loop
# regime), saturated arrivals, per-request sampling streams.
Q1 = dict(n_requests=12, rate_rps=1000.0, prompt_len=16, new_min=32,
          new_max=128, seed_per_request=True)
TOY_STEPS = 1500

# Q2: duplicate-prompt traffic, prefill-dominated (one hot 224-token
# prompt, 2-4 token outputs, saturated arrivals over 4 rows) — the
# in-flight window the dedup exists to close: concurrent identical
# admissions used to both pay full prefill. Small preset (the r9/r11
# serving protocol preset).
Q2 = dict(preset="small", n_requests=8, rate_rps=1000.0,
          prompt_len=224, new_min=2, new_max=4, prefill_chunk=32,
          distinct=1)


def train_toy(steps: int = TOY_STEPS):
    """The decode_spec_r7 trained-toy recipe at ``branch=1``: a
    deterministic order-2 chain over a small vocab (contexts recur
    within a request's window, so suffix-match drafting has material
    to match) learned to near-zero loss — confident distributions
    with wide argmax margins."""
    import jax
    import jax.numpy as jnp
    import optax

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import (make_model_mesh,
                                                 make_train_step)
    from icikit.models.transformer.train import make_markov_sampler

    cfg = TransformerConfig(vocab=12, d_model=64, n_heads=2, d_head=32,
                            d_ff=256, n_layers=4, max_seq=160,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    sampler = make_markov_sampler(cfg.vocab, seed=0, branch=1)
    _, step = make_train_step(mesh, cfg, optax.adam(3e-3))
    opt_state = optax.adam(3e-3).init(params)
    loss = None
    for s in range(steps):
        chunk = sampler(s, 16, 64)
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(chunk[:, :-1]),
            jnp.asarray(chunk[:, 1:]))
    final = float(np.asarray(loss))
    print(f"toy model trained: {steps} steps (branch=1), final loss "
          f"{final:.3f}", flush=True)
    return cfg, mesh, params, sampler, final


def chain_workload(sampler, seed: int, q1: dict) -> list:
    """In-distribution prompts: each request starts somewhere on the
    chain (fresh stream per workload seed), Poisson offsets, per-
    request sampling seeds."""
    rng = np.random.default_rng(seed)
    n = q1["n_requests"]
    offs = np.cumsum(rng.exponential(1.0 / q1["rate_rps"], size=n))
    chunk = sampler(10_000 + seed, n, q1["prompt_len"] + 1)
    return [(float(offs[i]),
             np.asarray(chunk[i, :q1["prompt_len"]], np.int32),
             int(rng.integers(q1["new_min"], q1["new_max"] + 1)), i)
            for i in range(n)]


def _arm(seed: int, label: str, preset: str = "toy",
         model=None, workload=None, **over) -> dict:
    kw = {**COMMON, **Q1, **over}
    [rec] = run_bench(
        preset, kw["rows"], kw["n_requests"], kw["rate_rps"],
        kw["prompt_len"], kw["new_min"], kw["new_max"],
        kw["block_size"], seed=seed, mode=kw["mode"],
        compute_dtype=kw["compute_dtype"],
        speculate=kw.get("speculate", 1),
        drafter=kw.get("drafter", "ngram"),
        temperature=kw.get("temperature", 0.0),
        top_k=kw.get("top_k", 0), top_p=kw.get("top_p", 1.0),
        seed_per_request=kw.get("seed_per_request", False),
        distinct=kw.get("distinct", 0),
        inflight_dedup=kw.get("inflight_dedup", True),
        prefill_chunk=kw.get("prefill_chunk", 64),
        verify=kw["verify"], model=model, workload=workload)
    rec["study"] = "r12"
    rec["arm"] = label
    assert rec["identity_ok"], (
        f"arm {label} seed {seed}: served tokens diverged from "
        "single-request generate — the A/B is void")
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="serve_r12.jsonl")
    ap.add_argument("--seeds", type=int, nargs="+", default=[0, 1])
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repeats per spec A/B arm; the "
                         "committed figure is the median adjacent-"
                         "pair ratio")
    args = ap.parse_args(argv)

    cfg, mesh, params, sampler, toy_loss = train_toy()
    model = (params, mesh, cfg)
    rows = []
    for seed in args.seeds:
        wl = chain_workload(sampler, seed, Q1)
        stamp = {"corpus": "markov-order2-branch1",
                 "train_steps": TOY_STEPS,
                 "toy_loss": round(toy_loss, 4)}
        _arm(seed, "warmup", model=model, workload=wl,
             temperature=0.3, speculate=1)
        for temp in (0.3, 0.7):
            reps: dict = {1: [], 3: []}
            for _ in range(args.reps):
                for spec_k in (1, 3):
                    reps[spec_k].append(_arm(
                        seed, f"sampled-t{temp}-spec{spec_k}",
                        model=model, workload=wl, temperature=temp,
                        speculate=spec_k, drafter="suffix"))
            ratios = [s["tokens_per_s"] / b["tokens_per_s"]
                      for b, s in zip(reps[1], reps[3])]
            ratio = statistics.median(ratios)
            med = {k: statistics.median(
                r["tokens_per_s"] for r in v) for k, v in reps.items()}
            for k, v in reps.items():
                pick = dict(min(v, key=lambda r: abs(
                    r["tokens_per_s"] - med[k])))
                pick.update(stamp)
                pick["tokens_per_s_reps"] = [r["tokens_per_s"]
                                             for r in v]
                pick["tokens_per_s_median"] = med[k]
                if k == 3:
                    pick["spec_ratio_reps"] = [round(x, 4)
                                               for x in ratios]
                    pick["spec_ratio_median"] = round(ratio, 4)
                rows.append(pick)
            spec_t = reps[3][0]
            print(f"[seed {seed}] spec-sampling @ T={temp}: median "
                  f"pair ratio x{ratio:.2f} "
                  f"(reps {[round(x, 2) for x in ratios]}; medians "
                  f"{med[3]} vs {med[1]} tok/s), tokens/row-step "
                  f"{spec_t['tokens_per_step_row']}; identity "
                  f"{args.reps}x(12+12) OK", flush=True)
        # bonus depth point: k=4 at T=0.3, one rep (the trend row)
        k4 = _arm(seed, "sampled-t0.3-spec4", model=model, workload=wl,
                  temperature=0.3, speculate=4, drafter="suffix")
        k4.update(stamp)
        rows.append(k4)
        print(f"[seed {seed}] k=4 @ T=0.3: {k4['tokens_per_s']} tok/s "
              f"(tokens/row-step {k4['tokens_per_step_row']})",
              flush=True)

        on = _arm(seed, "inflight-dedup-on", **Q2, inflight_dedup=True)
        off = _arm(seed, "inflight-dedup-off", **Q2,
                   inflight_dedup=False)
        rows += [on, off]
        t_on, t_off = on["dup_ttft_ms"]["p50"], off["dup_ttft_ms"]["p50"]
        ttft = (f"{t_on} vs {t_off} ms (x{t_off / t_on:.2f} lower)"
                if t_on and t_off else f"{t_on} vs {t_off} ms")
        print(f"[seed {seed}] in-flight dedup: prefill tokens "
              f"{on['prefill_tokens_computed']} vs "
              f"{off['prefill_tokens_computed']} "
              f"(x{off['prefill_tokens_computed'] / on['prefill_tokens_computed']:.2f} less compute), "
              f"second-arrival p50 TTFT {ttft}, "
              f"tok/s {on['tokens_per_s']} vs {off['tokens_per_s']}; "
              f"waiters {on['prefix']['inflight_hits']}; identity "
              f"{on['identity_checked']}+{off['identity_checked']} OK",
              flush=True)
        # append per seed so a late-arm failure can't discard the
        # already-measured records
        with open(args.out, "a") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        print(f"[seed {seed}] appended {len(rows)} records to "
              f"{args.out}", flush=True)
        rows = []
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
