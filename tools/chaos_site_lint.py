"""Lint: every chaos plan entry in tests/tools must name a registered
probe site.

Probe sites used to be bare strings: a typo in an ``ICIKIT_CHAOS``
spec or a drill's ``FaultPlan`` key silently never fired — the drill
"passed" while exercising nothing. Modules now register their sites at
definition (``chaos.register_site``, next to the probes themselves);
this lint imports every instrumented module, then scans the test and
tool trees (plus the Makefile's ``ICIKIT_CHAOS`` specs) for
``kind:site-glob`` literals and fails on any glob that cannot reach a
registered site (``chaos.site_known``). ``inject()`` gives the same
feedback at runtime as a ``RuntimeWarning``; this makes it a CI
failure (wired into ``make check``).

Run: ``python tools/chaos_site_lint.py`` — exits nonzero with the
offending entries on a hit.
"""

from __future__ import annotations

import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:  # runnable as `python tools/chaos_site_lint.py`
    sys.path.insert(0, ROOT)

# A plan entry literal: "kind:site-glob" in quotes, f-string holes
# collapsed to a glob star (f"die:solitaire.worker.{w}" drills the
# registered solitaire.worker.* family).
ENTRY = re.compile(
    r"""["'](delay|die|corrupt|io):([A-Za-z0-9_.*?{}\[\]-]+)["']""")

# An ICIKIT_CHAOS env-spec entry: the spec is one quoted semicolon-
# separated string ('seed=0;corrupt:serve.kv.page=@0'), so the glob is
# followed by '=value' rather than a closing quote — the Makefile's
# drills (and any subprocess env strings in tests) are written this way.
ENV_ENTRY = re.compile(
    r"""(delay|die|corrupt|io):([A-Za-z0-9_.*?{}\[\]-]+)=""")

# A direct probe call in the scanned file: the chaos-machinery unit
# tests drill synthetic sites ("w.1", "x") they probe themselves —
# those are defined, just locally. Same register-at-definition rule,
# applied to the file under scan.
LOCAL_PROBE = re.compile(
    r"""(?:maybe_delay|maybe_die|maybe_corrupt|maybe_io_fail|io_retry|"""
    r"""fires)\(\s*(?:["'][a-z]+["']\s*,\s*)?f?["']"""
    r"""([A-Za-z0-9_.{}-]+)["']""")


def _register_everything() -> None:
    """Import every module that owns probe sites, so registration-at-
    definition has happened before we judge the globs."""
    import icikit.bench.harness  # noqa: F401
    import icikit.models.solitaire.scheduler  # noqa: F401
    import icikit.models.sort  # noqa: F401
    import icikit.models.transformer.decode  # noqa: F401
    import icikit.models.transformer.model  # noqa: F401
    import icikit.models.transformer.speculative  # noqa: F401
    import icikit.models.transformer.train  # noqa: F401
    import icikit.parallel.integrity  # noqa: F401
    import icikit.parallel.multihost  # noqa: F401
    import icikit.serve.engine  # noqa: F401
    import icikit.utils.checkpoint  # noqa: F401


def _scan_paths():
    for sub in ("tests", "tools"):
        d = os.path.join(ROOT, sub)
        for name in sorted(os.listdir(d)):
            if name.endswith(".py"):
                yield os.path.join(d, name)
    yield os.path.join(ROOT, "Makefile")


def main() -> int:
    _register_everything()
    from icikit import chaos

    import fnmatch

    bad = []
    for path in _scan_paths():
        with open(path) as f:
            text = f.read()
        local = {re.sub(r"\{[^}]*\}", "*", s)
                 for s in LOCAL_PROBE.findall(text)}
        for lineno, line in enumerate(text.splitlines(), 1):
            if "chaos-site-lint: off" in line:
                continue  # deliberate negative (the warn-path tests)
            entries = ENTRY.findall(line) + ENV_ENTRY.findall(line)
            for kind, glob in entries:
                # collapse f-string holes to globs before judging
                glob = re.sub(r"\{[^}]*\}", "*", glob)
                if chaos.site_known(glob):
                    continue
                if any(fnmatch.fnmatchcase(s, glob)
                       or fnmatch.fnmatchcase(glob, s)
                       for s in local):
                    continue  # the file probes that site itself
                rel = os.path.relpath(path, ROOT)
                bad.append(f"{rel}:{lineno}: {kind}:{glob}")
    if bad:
        print("chaos plan entries naming no registered probe site "
              "(typo, or the owning module forgot "
              "chaos.register_site):")
        print("\n".join("  " + b for b in bad))
        print(f"registered sites: "
              f"{sorted(chaos.registered_sites())}")
        return 1
    n = len(chaos.registered_sites())
    print(f"chaos-site lint OK: every tests/tools plan entry reaches "
          f"one of the {n} registered sites")
    return 0


if __name__ == "__main__":
    sys.exit(main())
