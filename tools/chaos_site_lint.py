"""Thin shim: this lint is now the ``chaos-site`` rule of the unified
analysis framework (``icikit.analysis``, docs/ANALYSIS.md) — every
chaos plan entry in tests/tools/Makefile must name a registered probe
site. The scanners (``ENTRY``/``ENV_ENTRY``/``LOCAL_PROBE``, plus the
``collapse_holes`` f-string-glob helper — both now unit-tested in
tests/test_analysis.py) live in ``icikit.analysis.rules.chaos_site``;
``make check`` runs the whole suite as
``python -m icikit.analysis --gate``.

Run standalone: ``JAX_PLATFORMS=cpu python tools/chaos_site_lint.py``.
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from icikit.analysis.rules.chaos_site import (  # noqa: E402,F401
    ENTRY,
    ENV_ENTRY,
    LOCAL_PROBE,
    check_chaos_site,
    collapse_holes,
    local_probes,
    scan_entries,
)

RULE = "chaos-site"


def main() -> int:
    from icikit.analysis import shim_main
    return shim_main(RULE, "chaos-site lint OK (via icikit.analysis):"
                           " every tests/tools plan entry reaches a "
                           "registered site")


if __name__ == "__main__":
    sys.exit(main())
