#!/usr/bin/env python
"""Fleet round-20 study: pricing cache-aware dispatch, the host-RAM
bridge tier, and the autoscale supervisor.

Paired-per-seed protocol on the SAME seeded Zipf multi-tenant
shared-prefix workload (``make_workload(tenants=, zipf=)``), every
arm ``--verify-identity`` audited — routing changes WHERE a claim
lands, never what it computes, and the audit is what makes that a
measurement instead of a hope. Arms, interleaved per seed, appending
to ``serve_fleet_route_r20.jsonl``:

- **routed vs blind, homogeneous** (2 engines, ``both``): the win is
  locality — steering a tenant's requests to the engine already
  holding its prefix chain raises the local radix-cache hit ratio
  (``prefix_hit_ratio``) instead of re-prefilling the same blocks on
  every engine. Bar: mean hit-ratio strictly up, tokens/s within
  ``tps_tolerance_pct`` of blind.
- **routed vs blind, disaggregated** (3 engines: 1 prefill +
  2 decode): the win is traffic — a tenant's decode claims stick to
  the decode engine that already pulled its shared prefix, so the
  bridge moves fewer migrated bytes. Bar: mean ``migration_bytes``
  strictly down, tokens/s within tolerance.
- **host-RAM bridge tier vs disk-only** (2-engine disagg,
  ``bridge_ram`` 256 vs 0): same pulls, different tier — the record
  compares per-fetch wall time (``ram_hit_us_mean`` vs
  ``disk_hit_us_mean``). Bar: RAM tier strictly faster, identity
  holds on both.
- **autoscale supervisor** (1 base engine, hot Poisson burst): the
  watch's ``fleet.pending`` watermark spawns a joiner, sustained
  post-drain idle retires it — the decision timeline and the
  spawn->first-commit scale-up TTFT land in the record, with the
  cross-process weight cache ON vs OFF (the r18 3.4 s scale-up was
  weight-rebuild dominated).
- **weight-rebuild microbench** (fresh subprocesses, ``small``
  preset): ``build_model`` cold (no cache) vs cache-write vs
  cache-warm — the component cost the supervisor arm's TTFT delta
  comes from.

CPU protocol note: engines share this host's physical cores, so
absolute tokens/s under-reports separate-host scaling; the portable
claims are the paired ratios. The TPU/multi-host session re-prices
absolutes (ROADMAP item 5 ledger).

Reproduce::

    python tools/fleet_route_study.py --json serve_fleet_route_r20.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from icikit.bench.fleet import run_fleet, worker_env  # noqa: E402

# shared-prefix-dominated prompts: 5 of 7 blocks (block_size 4) are
# the tenant's — the regime cache-aware routing exists for
ARM_KW = dict(
    prompt_len=24, new_min=4, new_max=8, prefix_len=20,
    tenants=4, zipf=1.2, verify=True, timeout_s=900.0)

SUP_KW = dict(
    prompt_len=16, new_min=4, new_max=8, supervise=True,
    pending_high=3.0,
    supervise_kw=dict(spawn_cooldown_s=2.0, retire_cooldown_s=1.0,
                      scale_down_idle_s=0.5),
    verify=True, timeout_s=900.0)

# the weight-rebuild microbench runs a REAL-sized recipe: tiny's
# init is milliseconds either way, small's is the visible cost
BUILD_SPEC = {"preset": "small", "overrides": {"max_seq": 64},
              "compute_dtype": "float32", "dp": 1, "tp": 1,
              "init_seed": 0}

_BUILD_PROBE = """\
import json, sys, time
spec = json.loads(sys.argv[1])
cache = sys.argv[2] or None
from icikit.fleet.worker import build_model
t0 = time.perf_counter()
build_model(spec, weight_cache=cache)
print("BUILD_S", time.perf_counter() - t0)
"""


def _build_time(cache_dir: str | None) -> float:
    """``build_model`` wall time in a FRESH subprocess (the in-process
    memo must not flatter the numbers)."""
    probe = os.path.join(tempfile.gettempdir(),
                         "icikit_build_probe.py")
    with open(probe, "w") as f:
        f.write(_BUILD_PROBE)
    out = subprocess.run(
        [sys.executable, probe, json.dumps(BUILD_SPEC),
         cache_dir or ""],
        capture_output=True, text=True, timeout=300,
        env=worker_env())
    for line in out.stdout.splitlines():
        if line.startswith("BUILD_S "):
            return float(line.split()[1])
    raise RuntimeError(f"build probe failed: {out.stdout[-500:]} "
                       f"{out.stderr[-500:]}")


def _route_pair(rec: dict) -> dict:
    b = rec["bridge"]
    return {"tokens_per_s": rec["tokens_per_s"],
            "prefix_hit_ratio": rec["prefix_hit_ratio"],
            "migration_bytes": b["migration_bytes"],
            "migrations": b["migrations"],
            "route": rec["route"],
            "identity_ok": rec["identity_ok"]}


def study(json_path: str | None, seeds=(0, 1), requests: int = 24,
          rate: float = 12.0,
          tps_tolerance_pct: float = 10.0) -> list:
    recs = []
    for seed in seeds:
        # -- routed vs blind, homogeneous locality arm ---------------
        homog = {}
        for arm, route in (("blind", False), ("routed", True)):
            r = run_fleet(2, requests, rate, seed=seed, route=route,
                          **ARM_KW)
            assert r["identity_ok"] and not r["failed"], r
            homog[arm] = _route_pair(r)
        # -- routed vs blind, disagg migration-traffic arm -----------
        disagg = {}
        for arm, route in (("blind", False), ("routed", True)):
            r = run_fleet(3, requests, rate, seed=seed, route=route,
                          roles="disagg", **ARM_KW)
            assert r["identity_ok"] and not r["failed"], r
            disagg[arm] = _route_pair(r)
        # -- host-RAM bridge tier vs disk-only -----------------------
        bridge = {}
        for arm, ram in (("ram", 256), ("disk", 0)):
            r = run_fleet(2, requests, rate, seed=seed, route=False,
                          roles="disagg", bridge_ram=ram, **ARM_KW)
            assert r["identity_ok"] and not r["failed"], r
            b = r["bridge"]
            bridge[arm] = {
                "pulled": b["pulled"],
                "ram_hits": b["ram_hits"],
                "disk_hits": b["disk_hits"],
                "ram_hit_us_mean": b["ram_hit_us_mean"],
                "disk_hit_us_mean": b["disk_hit_us_mean"],
                "tokens_per_s": r["tokens_per_s"],
                "identity_ok": r["identity_ok"]}
        assert bridge["ram"]["ram_hits"] >= 1, bridge
        assert bridge["disk"]["disk_hits"] >= 1, bridge
        # -- autoscale supervisor, weight cache on vs off ------------
        autoscale = {}
        for arm, wc in (("cache", None), ("no_cache", "off")):
            r = run_fleet(1, 16, 16.0, seed=seed, weight_cache=wc,
                          **SUP_KW)
            assert r["identity_ok"] and not r["failed"], r
            a = r["autoscale"]
            assert a["spawns"] >= 1 and a["retires"] >= 1, a
            autoscale[arm] = a
        rec = {
            "kind": "serve_fleet_route",
            "n_requests": requests,
            "rate_rps": rate,
            "seed": seed,
            **{k: ARM_KW[k] for k in
               ("prompt_len", "prefix_len", "tenants", "zipf")},
            "homog": homog,
            "disagg": disagg,
            "bridge_tier": bridge,
            "autoscale": autoscale,
            "note": "paired per-seed arms on one Zipf multi-tenant "
                    "workload; every arm identity-audited; CPU "
                    "co-located engines, ratios are the portable "
                    "claim",
        }
        recs.append(rec)
        if json_path:
            with open(json_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        print(json.dumps({  # icikit-lint: off[obs-print]
            "seed": seed,
            "homog_hit": [homog["blind"]["prefix_hit_ratio"],
                          homog["routed"]["prefix_hit_ratio"]],
            "disagg_mig_bytes": [disagg["blind"]["migration_bytes"],
                                 disagg["routed"]["migration_bytes"]],
            "tier_us": [bridge["ram"]["ram_hit_us_mean"],
                        bridge["disk"]["disk_hit_us_mean"]],
            "scaleup_ms": {
                arm: [s["ttft_ms"] for s in a["scaleup_ttft_ms"]]
                for arm, a in autoscale.items()}}))

    # -- weight-rebuild microbench (once; deterministic recipe) ------
    wc_dir = tempfile.mkdtemp(prefix="icikit_wc_study_")
    try:
        t_none = _build_time(None)
        t_write = _build_time(wc_dir)       # cold: build + save
        t_warm = _build_time(wc_dir)        # warm: load + verify
    finally:
        shutil.rmtree(wc_dir, ignore_errors=True)
    build_rec = {
        "kind": "serve_fleet_route_build",
        "preset": BUILD_SPEC["preset"],
        "build_s_no_cache": round(t_none, 3),
        "build_s_cache_write": round(t_write, 3),
        "build_s_cache_warm": round(t_warm, 3),
        "speedup": round(t_none / t_warm, 2),
        "note": "build_model in fresh subprocesses: the weight-"
                "rebuild component of scale-up TTFT, before "
                "(no cache) vs after (warm cross-process cache)",
    }
    recs.append(build_rec)
    if json_path:
        with open(json_path, "a") as f:
            f.write(json.dumps(build_rec) + "\n")
    print(json.dumps({  # icikit-lint: off[obs-print]
        k: build_rec[k] for k in
        ("build_s_no_cache", "build_s_cache_warm", "speedup")}))

    # -- acceptance bars (means across seeds: single-seed CPU noise
    # must not flip a verdict the pairing was designed to settle) ----
    arms = [r for r in recs if r["kind"] == "serve_fleet_route"]
    n = len(arms)

    def mean(path_a, path_b, key):
        return sum(r[path_a][path_b][key] for r in arms) / n

    hit_blind = mean("homog", "blind", "prefix_hit_ratio")
    hit_routed = mean("homog", "routed", "prefix_hit_ratio")
    assert hit_routed > hit_blind, \
        f"routing did not raise prefix hit-ratio: " \
        f"{hit_routed:.4f} vs {hit_blind:.4f}"
    mig_blind = mean("disagg", "blind", "migration_bytes")
    mig_routed = mean("disagg", "routed", "migration_bytes")
    assert mig_routed < mig_blind, \
        f"routing did not cut migration bytes: " \
        f"{mig_routed:.0f} vs {mig_blind:.0f}"
    for arm_name in ("homog", "disagg"):
        tb = mean(arm_name, "blind", "tokens_per_s")
        tr = mean(arm_name, "routed", "tokens_per_s")
        assert tr >= tb * (1 - tps_tolerance_pct / 100), \
            f"{arm_name}: routed tokens/s {tr:.2f} degraded past " \
            f"{tps_tolerance_pct}% of blind {tb:.2f}"
    ram_us = mean("bridge_tier", "ram", "ram_hit_us_mean")
    disk_us = mean("bridge_tier", "disk", "disk_hit_us_mean")
    assert ram_us < disk_us, \
        f"RAM tier not faster than disk: {ram_us} vs {disk_us}"
    assert build_rec["build_s_cache_warm"] \
        < build_rec["build_s_no_cache"], build_rec
    print(json.dumps({  # icikit-lint: off[obs-print]
        "hit_ratio": [round(hit_blind, 4), round(hit_routed, 4)],
        "migration_bytes": [round(mig_blind), round(mig_routed)],
        "tier_us": [round(ram_us, 1), round(disk_us, 1)],
        "all_bars_pass": True}))
    return recs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", dest="json_path",
                    default="serve_fleet_route_r20.jsonl")
    ap.add_argument("--seeds", type=int, nargs="+", default=[0, 1])
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--rate", type=float, default=12.0)
    args = ap.parse_args(argv)
    t0 = time.monotonic()
    study(args.json_path, seeds=tuple(args.seeds),
          requests=args.requests, rate=args.rate)
    print(json.dumps({  # icikit-lint: off[obs-print]
        "study_s": round(time.monotonic() - t0, 1)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
