"""Round-16 serving study: tiered KV cache A/B — the reproducible
command behind serve_r16.jsonl.

Three questions, each answered by paired arms over the SAME seeded
workload (matched offered load; every serve arm per-request
token-identity audited against single-request ``generate``, so
spill/restore is proven bitwise-invisible to committed tokens):

1. **Spill tier vs no tier** on the Zipf multi-tenant shared-prefix
   workload with a device pool sized to force eviction (8 tenants x
   8-block prefixes + decode-block churn against a 32-block pool
   that cannot cache them all): does the host spill tier beat the
   no-tier baseline on prefix hit tokens AND p50 TTFT? The no-tier
   arm is exactly the r11 cache (evicted refcount-0 blocks vanish
   and their tenants recompute); the spill arms swap them back in,
   digest-verified.
2. **Hit-rate x swap-latency curve**: host tier capacity swept
   (0 / 16 / 96 blocks) at fixed workload — each row carries the hit
   tokens its capacity bought and the measured per-restore latency
   (``prefix.restore_ms_total / prefix.restores``), the curve
   docs/SERVING.md tabulates.
3. **Cold restart vs rewarm-from-store** (kind ``serve_rewarm``): an
   engine that persisted its sealed blocks is restarted; the rewarm
   arm restores the pending prompts' chains from disk
   (``Engine.rewarm`` over ``RequestQueue.pending_prompts``) while
   the cold arm recomputes prefill from nothing. Compared on
   time-to-first-completion (TTFC), compile-warmed in both arms so
   the delta is prefill-compute vs restore-I/O, not XLA.

Usage::

    JAX_PLATFORMS=cpu python tools/tiered_kv_study.py \
        [--out serve_r16.jsonl] [--seeds 0 1]

CPU-fp32 protocol throughout (the r9 rule: the identity audit needs
matched arithmetic between the engine's per-call programs and
generate's scanned loop, which on XLA:CPU only fp32 provides). Every
row is backend-stamped; absolute numbers are CPU-measured, the
tier-vs-no-tier RATIOS are the portable claim.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

try:
    import icikit  # noqa: F401
except ModuleNotFoundError:  # `python tools/tiered_kv_study.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

from icikit.bench.serve import run_bench

COMMON = dict(preset="small", rows=2, compute_dtype="float32",
              mode="continuous", verify=True)

# The Zipf multi-tenant pressure workload: 8 tenants x 32-token
# prefixes (8 blocks each at bs=4, 64 prefix blocks of cacheable
# content) against a 32-block pool whose worst-case live demand is
# ~24 — the cold tenants' cached prefixes are forced out constantly,
# which is the population the spill tier re-serves. The SMALL preset
# is deliberate: the tier trades a host-memory round trip for prefill
# recompute, so the honest venue is a model whose prefill costs more
# than a memcpy — on the tiny toy, recompute is near-free and the
# tier (correctly) cannot pay for itself (measured while scoping this
# study; the no-tier arm rows pin that baseline too).
WORK = dict(n_requests=24, rate_rps=20.0, prompt_len=48,
            prefix_len=40, new_min=4, new_max=6, block_size=4,
            n_blocks=36, prefill_chunk=16, tenants=6, zipf=0.7)


def _arm(seed: int, label: str, **over) -> dict:
    kw = {**COMMON, **WORK, **over}
    [rec] = run_bench(
        kw["preset"], kw["rows"], kw["n_requests"], kw["rate_rps"],
        kw["prompt_len"], kw["new_min"], kw["new_max"],
        kw["block_size"], kw["n_blocks"], seed=seed, mode=kw["mode"],
        compute_dtype=kw["compute_dtype"],
        prefix_len=kw["prefix_len"],
        prefill_chunk=kw["prefill_chunk"], verify=kw["verify"],
        tenants=kw["tenants"], zipf=kw["zipf"],
        host_blocks=kw.get("host_blocks", 0),
        store_dir=kw.get("store_dir"))
    rec["study"] = "r16"
    rec["arm"] = label
    assert rec["identity_ok"], (
        f"arm {label} seed {seed}: served tokens diverged from "
        "single-request generate — spill/restore is NOT bitwise "
        "invisible, the A/B is void")
    return rec


def _restore_ms(rec: dict) -> float | None:
    p = rec["prefix"]
    if not p.get("restores"):
        return None
    return round(p["restore_ms_total"] / p["restores"], 3)


def _rewarm_ab(seed: int, out_rows: list) -> None:
    """Q3: cold restart vs rewarm-from-store on TTFC. Self-contained:
    primes its own store over 8 long prompts, then restarts twice —
    once blind, once rewarming from disk. The model is a wide-FFN
    geometry (d_model 1024, d_ff 8192, 4 layers, small vocab): the
    rewarm trade is disk-read bytes vs prefill FLOPs, and the honest
    venue is a model whose compute-per-KV-byte ratio resembles
    production (on the narrow presets this CPU recomputes a 64-token
    prefill faster than it can load+verify the same KV from disk —
    measured while scoping this study; the narrower the model, the
    more the verdict belongs to a TPU session)."""
    import jax
    import jax.numpy as jnp

    from icikit.models.transformer import (
        TransformerConfig,
        greedy_generate,
        init_params,
    )
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve import Engine, ServeConfig

    cfg = TransformerConfig(vocab=1024, d_model=1024, n_heads=8,
                            d_head=128, d_ff=8192, n_layers=4,
                            max_seq=256, compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    rng = np.random.default_rng(seed)
    s_prompt = 128
    prompts = [rng.integers(0, cfg.vocab, (s_prompt,))
               .astype(np.int32) for _ in range(8)]
    warm_p = rng.integers(0, cfg.vocab, (s_prompt,)).astype(np.int32)
    n_new = 2
    bases = [np.asarray(greedy_generate(
        params, jnp.asarray(p)[None], mesh, cfg, n_new))[0, s_prompt:]
        for p in prompts]
    store = tempfile.mkdtemp(prefix="icikit_r16_store_")

    def serve_cfg(store_dir):
        return ServeConfig(max_rows=4, block_size=8, n_blocks=80,
                           max_prompt=s_prompt, max_new=8,
                           prefill_chunk=64, host_cache_blocks=16,
                           store_dir=store_dir)

    def ttfc_arm(store_dir, rewarm: bool) -> dict:
        eng = Engine(params, mesh, cfg, serve_cfg(store_dir))
        eng.submit(warm_p, 2)
        eng.run()                     # compile warm, outside the clock
        # tier programs re-warm at POST-STEP arena shardings (the
        # bench.serve warm protocol): without this the rewarm arm
        # pays the restore-write recompile inside its TTFC
        eng.pool.warm_restore(8, max_evict=eng.nb_per_row)
        eng.submit(warm_p, 2)
        eng.run()
        t0 = time.monotonic()
        rids = [eng.submit(p, n_new) for p in prompts]
        nblocks = eng.rewarm() if rewarm else 0
        eng.run()
        ttfc = min(eng.queue.request(r).done_t for r in rids) - t0
        ok = all(
            list(eng.queue.request(r).tokens) == list(b)
            for r, b in zip(rids, bases))
        return {"ttfc_ms": round(ttfc * 1e3, 3),
                "rewarm_blocks": nblocks, "identity_ok": ok,
                "restores": eng.prefix_stats().get("restores", 0)}

    try:
        # prime: one engine serves the prompts with the store armed;
        # its drain flush persists every sealed block
        prime = Engine(params, mesh, cfg, serve_cfg(store))
        for p in prompts:
            prime.submit(p, n_new)
        prime.run()
        import jax as _jax
        common = {"kind": "serve_rewarm", "study": "r16",
                  "seed": seed, "preset": "wide-ffn-4L",
                  "d_model": 1024, "d_ff": 8192, "n_layers": 4,
                  "vocab": 1024,
                  "backend": _jax.default_backend(),
                  "compute_dtype": "float32",
                  "prompt_len": s_prompt,
                  "n_new": n_new, "n_prompts": len(prompts),
                  "note": ("CPU-measured"
                           if _jax.default_backend() == "cpu"
                           else "device-measured")}
        cold = ttfc_arm(None, rewarm=False)
        warm = ttfc_arm(store, rewarm=True)
        assert cold["identity_ok"] and warm["identity_ok"], (
            f"seed {seed}: rewarm A/B tokens diverged from generate")
        out_rows.append({**common, "arm": "cold-restart", **cold})
        out_rows.append({**common, "arm": "rewarm-from-store",
                         **warm})
        print(f"[seed {seed}] cold vs rewarm TTFC: "
              f"{cold['ttfc_ms']} vs {warm['ttfc_ms']} ms "
              f"(x{cold['ttfc_ms'] / warm['ttfc_ms']:.2f}); rewarm "
              f"restored {warm['rewarm_blocks']} blocks from disk, "
              f"identity OK both arms")
    finally:
        shutil.rmtree(store, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="serve_r16.jsonl")
    ap.add_argument("--seeds", type=int, nargs="+", default=[0, 1])
    args = ap.parse_args(argv)

    rows = []
    for seed in args.seeds:
        base = _arm(seed, "no-tier", host_blocks=0)
        spill16 = _arm(seed, "spill-16", host_blocks=16)
        spill96 = _arm(seed, "spill-96", host_blocks=96)
        store_dir = tempfile.mkdtemp(prefix="icikit_r16_tier_")
        try:
            tiered = _arm(seed, "spill-96+store", host_blocks=96,
                          store_dir=store_dir)
        finally:
            shutil.rmtree(store_dir, ignore_errors=True)
        rows += [base, spill16, spill96, tiered]
        for rec in (spill16, spill96, tiered):
            ht = rec["prefix"]["hit_tokens"]
            bt = base["prefix"]["hit_tokens"]
            ttft = (base["ttft_ms"]["p50"] or 1.0) / \
                (rec["ttft_ms"]["p50"] or 1.0)
            print(f"[seed {seed}] {rec['arm']} vs no-tier: "
                  f"hit_tokens {ht} vs {bt} "
                  f"(x{ht / max(1, bt):.2f}); p50 TTFT "
                  f"{rec['ttft_ms']['p50']} vs "
                  f"{base['ttft_ms']['p50']} ms (x{ttft:.2f} lower); "
                  f"restores {rec['prefix']['restores']} "
                  f"({_restore_ms(rec)} ms/block), spills "
                  f"{rec['prefix'].get('spills', 0)}, identity "
                  f"{rec['identity_checked']} OK")
        _rewarm_ab(seed, rows)

    with open(args.out, "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    print(f"appended {len(rows)} records to {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
