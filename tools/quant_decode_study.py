"""Round-10 quantized-decode study driver (DECODE.md "Quantized
decode"): measure the relaxed parity bar and re-price the route.

Protocol — two measurement regimes plus the one-command re-pricing:

1. **Confident regime** (the bar): train a deterministic-corpus toy
   (order-2 Markov, branch=1, vocab 16 — greedy decode's home turf:
   the trained model's predictions are near-one-hot). Measure
   teacher-forced top-1 agreement between the int8 and fp decode
   paths at GENERATE level (``quant.measure_top1_agreement`` — a
   full-width verify window, i.e. the decode path's next-token argmax
   at every committed prefix) and at ENGINE level (fp engine vs int8
   engine over a request workload; the int8 engine is additionally
   token-identical to int8 generate by the pinned identity contract).
   Validated this round: **1.0 over 3040 generate positions** and
   1.0 over the engine workload, with max logit deviation ~0.22 —
   the comparison is real, the bar (>= 0.999) clears.
2. **Entropy-limited regime** (the caveat row): the r8 branch-4
   teacher (loss 1.67 — within ~0.3 of the corpus entropy floor)
   measures ~0.97, and EVERY disagreement sits at an fp top-2 margin
   < 0.22 (median 0.03): near-ties where the fp32 path itself is one
   rounding away from flipping. Both rows are recorded so the bar is
   honest about where it holds.
3. **Re-pricing**: ``bench.decode.cost_model_rows(bytes_dtype="int8")``
   re-verdicts the r8 measured α=0.377 row against the int8 floor,
   and ``spec_breakeven_rows(bytes_dtype="int8")`` re-prices the
   batch-aware break-even table — the same rows
   ``python -m icikit.bench.decode --cost-model --bytes-dtype int8
   --alpha-from decode_spec_r8.jsonl`` reproduces from records alone.

Usage::

    JAX_PLATFORMS=cpu python tools/quant_decode_study.py \
        --json decode_spec_r10.jsonl [--quick]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# confident-regime toy: deterministic order-2 chain over a small state
# space the capacity fully memorizes (loss ~0.04 at 1500 steps)
DET_TOY = dict(vocab=16, d_model=64, n_heads=2, d_head=32, d_ff=256,
               n_layers=4, max_seq=160, compute_dtype="float32")
# the r7/r8 pricing toy (branch-4, entropy-limited)
R8_TOY = dict(vocab=64, d_model=64, n_heads=2, d_head=32, d_ff=256,
              n_layers=4, max_seq=160, compute_dtype="float32")


def _train(toy: dict, branch: int, steps: int, lr: float = 3e-3):
    import jax
    import jax.numpy as jnp
    import optax

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import (make_model_mesh,
                                                 make_train_step)
    from icikit.models.transformer.train import make_markov_sampler

    cfg = TransformerConfig(**toy)
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    sampler = make_markov_sampler(cfg.vocab, seed=0, branch=branch)
    _, step = make_train_step(mesh, cfg, optax.adam(lr))
    st = optax.adam(lr).init(params)
    loss = None
    for s in range(steps):
        chunk = sampler(s, 16, 64)
        params, st, loss = step(params, st,
                                jnp.asarray(chunk[:, :-1]),
                                jnp.asarray(chunk[:, 1:]))
    final = float(np.asarray(loss))
    print(f"toy trained: vocab={cfg.vocab} branch={branch} "
          f"{steps} steps, loss {final:.4f}", flush=True)
    return cfg, mesh, params, sampler, final


def _generate_level(cfg, mesh, params, sampler, n_prompts: int,
                    n_new: int) -> dict:
    import jax.numpy as jnp

    from icikit.models.transformer import greedy_generate
    from icikit.models.transformer.quant import measure_top1_agreement

    qcfg = dataclasses.replace(cfg, decode_quant="int8")
    prompts = jnp.asarray(sampler(9, n_prompts, 64)[:, :32], jnp.int32)
    y = greedy_generate(params, prompts, mesh, cfg, n_new)
    return measure_top1_agreement(params, y, mesh, qcfg, 32)


def _engine_level(cfg, mesh, params, sampler, n_requests: int,
                  n_new: int) -> dict:
    """fp engine vs int8 engine over the same workload: token-level
    agreement per position (free-running — on the confident toy the
    paths agree at every prefix, so no divergence ever starts)."""
    from icikit.serve import Engine, ServeConfig

    qcfg = dataclasses.replace(cfg, decode_quant="int8")
    rng = np.random.default_rng(5)
    chunks = sampler(11, n_requests, 64)
    prompts = [chunks[i, :int(rng.integers(6, 24))].astype(np.int32)
               for i in range(n_requests)]
    sv = ServeConfig(max_rows=4, block_size=8,
                     n_blocks=max(64, 8 * n_requests),
                     max_prompt=32, max_new=n_new)

    def serve(c):
        eng = Engine(params, mesh, c, sv)
        rids = [eng.submit(p, n_new) for p in prompts]
        eng.run()
        return [eng.queue.done[r].tokens for r in rids]

    fp = serve(cfg)
    q8 = serve(qcfg)
    total = agree = 0
    for a, b in zip(fp, q8):
        n = min(len(a), len(b))
        total += n
        agree += sum(1 for x, y in zip(a[:n], b[:n]) if x == y)
    return {"n_positions": total, "n_agree": agree,
            "top1_agreement": agree / total if total else 0.0}


def parity_rows(quick: bool) -> list:
    rows = []
    # 1. confident regime — the bar
    steps = 150 if quick else 1500
    cfg, mesh, params, sampler, loss = _train(DET_TOY, branch=1,
                                              steps=steps)
    gen = _generate_level(cfg, mesh, params, sampler,
                          8 if quick else 32, 32 if quick else 96)
    eng = _engine_level(cfg, mesh, params, sampler,
                        4 if quick else 12, 8 if quick else 24)
    for level, m in (("generate", gen), ("engine", eng)):
        rows.append({
            "kind": "quant_parity", "level": level,
            "regime": "confident", "corpus": "markov-det-branch1",
            "train_steps": steps, "train_loss": round(loss, 4),
            "bar": 0.999, **{k: (round(v, 6)
                                 if isinstance(v, float) else v)
                             for k, v in m.items()},
            "clears_bar": m["top1_agreement"] >= 0.999,
        })
        print(f"confident/{level}: agreement "
              f"{m['top1_agreement']:.6f} over {m['n_positions']} "
              f"positions", flush=True)
    # 2. entropy-limited regime — the caveat row
    steps = 150 if quick else 3000
    cfg4, mesh4, p4, smp4, loss4 = _train(R8_TOY, branch=4,
                                          steps=steps)
    gen4 = _generate_level(cfg4, mesh4, p4, smp4,
                           8 if quick else 16, 32 if quick else 96)
    rows.append({
        "kind": "quant_parity", "level": "generate",
        "regime": "entropy-limited", "corpus": "markov-order2",
        "train_steps": steps, "train_loss": round(loss4, 4),
        "bar": 0.999, **{k: (round(v, 6) if isinstance(v, float)
                             else v) for k, v in gen4.items()},
        "clears_bar": gen4["top1_agreement"] >= 0.999,
        "note": ("disagreements sit at fp top-2 margins below the "
                 "logit quant noise (near-ties; r10 margin diagnosis: "
                 "max 0.22, median 0.03)"),
    })
    print(f"entropy-limited/generate: agreement "
          f"{gen4['top1_agreement']:.6f}", flush=True)
    return rows


def pricing_rows(alpha_from: str) -> list:
    from icikit.bench.decode import cost_model_rows, spec_breakeven_rows
    rows = []
    for dt in ("bf16", "int8"):
        rows.extend(cost_model_rows(alpha_from, preset="base", batch=1,
                                    bytes_dtype=dt))
        rows.extend(spec_breakeven_rows(preset="base", bytes_dtype=dt))
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", dest="json_path",
                    default="decode_spec_r10.jsonl")
    ap.add_argument("--alpha-from", default="decode_spec_r8.jsonl",
                    help="measured-acceptance records the re-pricing "
                         "re-verdicts (the r8 α=0.377 rows)")
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized run (fewer steps/tokens; the "
                         "confident toy does not converge, so the "
                         "bar row is machinery-only)")
    args = ap.parse_args(argv)
    rows = parity_rows(args.quick)
    if os.path.exists(args.alpha_from):
        rows.extend(pricing_rows(args.alpha_from))
    else:
        print(f"no {args.alpha_from}: skipping re-pricing rows",
              flush=True)
    with open(args.json_path, "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    print(f"{len(rows)} rows appended to {args.json_path}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
