"""b=1 decode scaffolding floor study (VERDICT r4 #5).

DECODE.md's profile attributes ~142 µs/token of the base b=1 step to
218 serialized sub-µs fusions. This script measures, by in-structure
ablation (same dataflow, one op class stubbed at a time — the
tile_floor discipline; an isolated microbench would let Mosaic/XLA
reschedule everything), what each scaffolding class actually costs
end-to-end, i.e. what a perfect fused replacement could reclaim:

  shipped     — as measured by bench.decode
  no-norm     — every _rms_norm is identity (removes 2 norm chains/layer)
  no-softmax  — attention keeps both dots but drops mask+softmax
  no-attn-vpu — both of the above

Timing-only: the ablated programs compute wrong tokens by design.
Run on the real chip: PYTHONPATH=/root/repo:/root/.axon_site.
"""

import json
import sys

import jax.numpy as jnp


def main():
    import icikit.models.transformer.decode as D
    from icikit.bench.decode import run_bench

    real_norm = D._rms_norm
    real_attn = D._masked_attention

    def no_norm(x, w):
        return x

    def no_vpu_attn(q, ks, vs, mask, scale, n_rep):
        b, one, h, dh = q.shape
        from icikit.models.transformer.model import repeat_kv
        ks, vs = repeat_kv(ks, n_rep), repeat_kv(vs, n_rep)
        w = jnp.einsum("bqhd,bkhd->bhqk", q, ks,
                       preferred_element_type=jnp.float32) * scale
        out = jnp.einsum("bhqk,bkhd->bqhd", w.astype(vs.dtype), vs,
                         preferred_element_type=jnp.float32)
        return out.astype(q.dtype)

    variants = [
        ("shipped", real_norm, real_attn),
        ("no-norm", no_norm, real_attn),
        ("no-softmax", real_norm, no_vpu_attn),
        ("no-attn-vpu", no_norm, no_vpu_attn),
    ]
    for name, norm, attn in variants:
        D._rms_norm = norm
        D._masked_attention = attn
        D._build_generate.cache_clear()
        rec = run_bench("base", 1, 1, 1, 64, 256, runs=3, windows=3)
        rec["ablation"] = name
        print(json.dumps(rec), flush=True)
        with open("decode_floor_r5.jsonl", "a") as f:
            f.write(json.dumps(rec) + "\n")
    D._rms_norm = real_norm
    D._masked_attention = real_attn
    return 0


if __name__ == "__main__":
    sys.exit(main())
