"""Thin shim: this lint is now the ``quant-arena`` rule of the
unified analysis framework (``icikit.analysis``, docs/ANALYSIS.md) —
no high-precision KV tensor is ALLOCATED on the int8 decode path, and
sealed-block digests cover the int8 scale pages. Unlike the AST rules
it is a RUNTIME check. Backward compatible as an ENTRY POINT (same
exit codes); the re-exported check bodies are the framework forms —
they RETURN ``Finding`` lists now instead of asserting, so call sites
must check the return value, not rely on an exception. ``make check``
runs the whole suite as ``python -m icikit.analysis --gate``.

Run standalone: ``JAX_PLATFORMS=cpu python tools/quant_lint.py``.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from icikit.analysis.rules.quant import (  # noqa: E402,F401
    check_block_hash_covers_scales,
    check_engine,
    check_generate,
    check_pool,
    check_quant,
)

RULE = "quant-arena"


def main() -> int:
    from icikit.analysis import shim_main
    return shim_main(RULE, "quant-lint OK (via icikit.analysis): no "
                           "high-precision KV allocated on the int8 "
                           "path; block digests cover scale pages")


if __name__ == "__main__":
    raise SystemExit(main())
