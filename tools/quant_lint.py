"""``make check`` lint: no high-precision KV tensor is ALLOCATED on
the int8 decode path.

"Allocated" means the persistent cache stores — pool arenas and the
loop-carried cache buffers — not transient fused values (an int8
operand upcast inside a matmul never owns HBM). Three mechanical
checks, each failing loudly:

1. ``KVPool(quant="int8")`` holds ONLY int8 arenas + fp32 scale pages
   (no compute-dtype KV arena attribute exists at all);
2. the int8 generate program's decode loop carries int8 caches: the
   jaxpr's scan/while carry avals contain NO floating-point tensor of
   the cache shape;
3. the int8 engine's step-program buffer pytree round-trips int8.

Run: ``JAX_PLATFORMS=cpu python tools/quant_lint.py``
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def check_pool() -> None:
    import jax.numpy as jnp

    from icikit.models.transformer import TransformerConfig
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    cfg = TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=2, max_seq=32,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(cfg, mesh, n_blocks=4, block_size=4, quant="int8")
    assert pool.kc is None and pool.vc is None, \
        "int8 pool allocated a high-precision KV arena"
    for name in ("qkc", "qvc"):
        for buf in getattr(pool, name):
            assert buf.dtype == jnp.int8, (name, buf.dtype)
    for name in ("ksc", "vsc"):
        for buf in getattr(pool, name):
            assert buf.dtype == jnp.float32, (name, buf.dtype)
    bufs = pool.buffers()
    assert set(bufs) == {"qkc", "qvc", "ksc", "vsc"}, set(bufs)
    print("quant-lint: KVPool int8 arenas OK (no fp KV allocated)")


def _float_cache_avals(jaxpr, cache_shape_tail):
    """Recursively collect scan/while carry avals that are floating
    point AND cache-shaped — the allocation smoking gun."""
    import jax.numpy as jnp
    bad = []

    def visit(jx):
        for eqn in jx.eqns:
            sub = []
            if eqn.primitive.name == "scan":
                inner = eqn.params["jaxpr"].jaxpr
                n_carry = eqn.params["num_carry"]
                sub = [v.aval for v in inner.invars[:n_carry]]
                visit(inner)
            elif eqn.primitive.name == "while":
                inner = eqn.params["body_jaxpr"].jaxpr
                sub = [v.aval for v in inner.invars]
                visit(inner)
            else:
                for p in eqn.params.values():
                    core = getattr(p, "jaxpr", None)
                    if core is not None and hasattr(core, "eqns"):
                        visit(core)
            for a in sub:
                shape = getattr(a, "shape", ())
                if (len(shape) >= len(cache_shape_tail)
                        and tuple(shape[-len(cache_shape_tail):])
                        == cache_shape_tail
                        and jnp.issubdtype(a.dtype, jnp.floating)):
                    bad.append(a)

    visit(jaxpr)
    return bad


def check_generate() -> None:
    import jax
    import jax.numpy as jnp

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.decode import (
        _build_generate,
        maybe_quantize_params,
    )
    from icikit.models.transformer.model import make_model_mesh

    cfg = TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=2, max_seq=64,
                            compute_dtype="float32",
                            decode_quant="int8")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    import dataclasses
    params = init_params(
        jax.random.key(0),
        dataclasses.replace(cfg, decode_quant="none"), mesh)
    qp = maybe_quantize_params(params, mesh, cfg)
    s_prompt, n_new = 8, 12
    fn = _build_generate(mesh, cfg, s_prompt, n_new)
    prompt = jnp.zeros((2, s_prompt), jnp.int32)
    seeds = jnp.zeros((2,), jnp.int32)
    key_data = jax.random.key_data(jax.random.key(0))
    knobs = jnp.ones((3,), jnp.float32)
    jaxpr = jax.make_jaxpr(fn)(qp, prompt, seeds, key_data, knobs)
    kv = cfg.n_kv_heads or cfg.n_heads
    tail = (s_prompt + n_new, kv, cfg.d_head)
    bad = _float_cache_avals(jaxpr.jaxpr, tail)
    assert not bad, (
        "int8 generate carries a high-precision cache-shaped buffer "
        f"through its decode loop: {bad}")
    print("quant-lint: int8 generate loop carries are int8 OK")


def check_engine() -> None:
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve import Engine, ServeConfig

    cfg = TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=2, max_seq=64,
                            compute_dtype="float32",
                            decode_quant="int8")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(
        jax.random.key(0),
        dataclasses.replace(cfg, decode_quant="none"), mesh)
    eng = Engine(params, mesh, cfg,
                 ServeConfig(max_rows=2, block_size=4, n_blocks=8,
                             max_prompt=8, max_new=8))
    eng.submit(np.arange(5, dtype=np.int32), 6)
    eng.run()
    bufs = eng.pool.buffers()
    assert set(bufs) == {"qkc", "qvc", "ksc", "vsc"}, set(bufs)
    assert all(b.dtype == jnp.int8 for b in bufs["qkc"] + bufs["qvc"])
    print("quant-lint: int8 engine pool round-trips int8 OK")


def check_block_hash_covers_scales() -> None:
    """Prefix-cache era integrity: the sealed-block digest — the one
    fingerprint every sharer of a page re-verifies — must cover the
    int8 arena's SCALE pages, not just the quantized payload. A
    flipped scale corrupts decoded tokens exactly like a flipped int8
    byte, so it must flip the digest too; a digest over payload bytes
    alone would let scale corruption ride shared blocks undetected."""
    import numpy as np

    from icikit.models.transformer import TransformerConfig
    from icikit.models.transformer.model import make_model_mesh
    from icikit.serve.kvpool import KVPool

    cfg = TransformerConfig(vocab=31, d_model=16, n_heads=2, d_head=8,
                            d_ff=32, n_layers=2, max_seq=32,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    pool = KVPool(cfg, mesh, n_blocks=4, block_size=4, quant="int8")
    # the q8 read-back must interleave payload AND scales per layer
    [page] = pool.allocators[0].alloc("lint", 1)
    per_layer = len(pool.page_bytes(0, page, "q8")) // cfg.n_layers
    assert per_layer == 4, (
        "q8 page_bytes must return qk, qv, ksc, vsc per layer, got "
        f"{per_layer} arrays")
    data = np.arange(4 * 2 * 8, dtype=np.int8).reshape(4, 2, 8)
    pool.poke_page(0, page, 0, data)
    pool.seal(0, page)
    assert pool.verify("lint", 0) == []
    vsc = list(pool.vsc)
    vsc[1] = vsc[1].at[0, page, 1, 0].add(0.5)   # ONLY a scale moves
    pool.vsc = tuple(vsc)
    assert pool.verify("lint", 0) == [0], (
        "a flipped scale page did NOT fail the sealed-block verify — "
        "the block hash does not cover the quantized payload's scales")
    print("quant-lint: sealed-block digest covers int8 scale pages OK")


def main() -> int:
    check_pool()
    check_generate()
    check_engine()
    check_block_hash_covers_scales()
    print("quant-lint OK: no high-precision KV allocated on the "
          "int8 path; block digests cover scale pages")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
