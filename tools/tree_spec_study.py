"""Round-14 token-tree + on-policy-distillation study driver
(DECODE.md "Token-tree speculation", ROADMAP item 3's two levers,
records ``decode_spec_r14.jsonl``).

Protocol — both levers measured on the SAME r7/r8 toy teacher, priced
against the r10 int8 floor (0.429 ms/tok):

1. **Teacher**: the r7 Markov toy, trunk-only, byte-identical to
   ``tools/decode_spec_study.py`` (3000 steps → loss ≈ 1.671).
2. **Leg (b), on-policy self-distillation**: attach the r8 head
   (quarter depth, rank 256) and distill against the FROZEN trunk
   twice — once on corpus tokens (the r8 protocol, re-measured as
   the baseline) and once ON-POLICY (``cfg.draft_on_policy``: the
   distill loss moves to the model's OWN greedy continuations,
   refreshed from current params every few steps — the
   ``--draft-sample`` trainer hook's exact machinery). r8 diagnosed
   the α gap as distribution shift (on-corpus agree 0.63 vs 0.377 on
   continuations); this measures whether closing the shift closes
   the gap.
3. **Leg (a), token trees**: greedy speculative acceptance per
   (k ∈ {2,4}) × (tree_branch ∈ {1,2,4}) × drafter ∈ {trained
   (on-policy head), ngram}, b=1. ``tree_branch=1`` rows ARE the
   chain baseline (same program). Tree rows carry the per-branch
   split (``primary_accepted``/``sideways_accepted``/``row_steps``)
   the expected-accepted-length estimator consumes.
4. **Price**: ``icikit.bench.decode.cost_model_rows`` at
   ``bytes_dtype="int8"`` — the same rows ``python -m
   icikit.bench.decode --cost-model --alpha-from
   decode_spec_r14.jsonl --bytes-dtype int8`` reproduces — plus one
   ``kind="verdict"`` row: the best tree projection vs the 15% bar
   (0.85 × int8 floor) and the on-policy α vs the 0.42 flip
   condition, honestly recorded either way.

Usage::

    JAX_PLATFORMS=cpu python tools/tree_spec_study.py \
        --json decode_spec_r14.jsonl [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

# runnable as `python tools/tree_spec_study.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the r7/r8 toy geometry (tools/decode_spec_study.py)
TOY = dict(vocab=64, d_model=64, n_heads=2, d_head=32, d_ff=256,
           n_layers=4, max_seq=160, compute_dtype="float32")
DRAFT_RANK = 256
DISTILL_LR = 3e-3
EXIT_LAYER = 1          # quarter depth — the priced route
ONP_PROMPT = 8          # on-policy continuation prompts (trainer's 8)
ONP_TOKENS = 48         # continuation length per refresh
ONP_EVERY = 8           # steps between refreshes


def train_teacher(steps: int):
    """The r7 acceptance-study model, trunk only — byte-identical to
    decode_spec_study.train_toy."""
    import jax
    import jax.numpy as jnp
    import optax

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import (make_model_mesh,
                                                 make_train_step)
    from icikit.models.transformer.train import make_markov_sampler

    cfg = TransformerConfig(**TOY)
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    sampler = make_markov_sampler(cfg.vocab, seed=0)
    _, step = make_train_step(mesh, cfg, optax.adam(3e-3))
    opt_state = optax.adam(3e-3).init(params)
    loss = None
    for s in range(steps):
        chunk = sampler(s, 16, 64)
        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(chunk[:, :-1]),
                                       jnp.asarray(chunk[:, 1:]))
    final = float(np.asarray(loss))
    print(f"teacher trained: {steps} steps, loss {final:.4f}",
          flush=True)
    return mesh, params, sampler, final


def distill_head(mesh, trunk, sampler, steps: int,
                 on_policy: bool):
    """Attach a fresh quarter-depth head and distill it against the
    frozen trunk — on corpus tokens (r8 protocol) or ON-POLICY on the
    model's own greedy continuations (the round-14 leg: the distill
    batch is refreshed from current params every ONP_EVERY steps,
    exactly the trainer's --draft-sample machinery). The param-group
    split keeps the trunk bitwise the teacher either way."""
    import jax
    import jax.numpy as jnp
    import optax

    from icikit.models.transformer import TransformerConfig
    from icikit.models.transformer.decode import greedy_generate
    from icikit.models.transformer.draft import init_draft_params
    from icikit.models.transformer.model import make_train_step

    cfg = TransformerConfig(**TOY, draft_head=True,
                            draft_layers=EXIT_LAYER,
                            draft_rank=DRAFT_RANK, draft_kl=0.5,
                            draft_on_policy=on_policy)
    params = dict(trunk)
    params.update(init_draft_params(
        jax.random.fold_in(jax.random.key(0), 7), cfg,
        params["w_out"]))
    tx = optax.multi_transform(
        {"draft": optax.adam(DISTILL_LR),
         "frozen": optax.set_to_zero()},
        lambda p: {k: ("draft" if k.startswith("draft_") else "frozen")
                   for k in p})
    _, step = make_train_step(mesh, cfg, tx,
                              draft_p0=ONP_PROMPT if on_policy else 0)
    opt_state = tx.init(params)
    metrics = None
    draft_batch = None
    for s in range(steps):
        chunk = sampler(100000 + s, 16, 64)
        tok = jnp.asarray(chunk[:, :-1])
        if on_policy and s % ONP_EVERY == 0:
            # the model's own continuations of this batch's prompts,
            # from CURRENT params — the trunk is frozen here, so one
            # refresh would suffice; the periodic refresh keeps the
            # protocol identical to the trainer's co-training hook
            draft_batch = greedy_generate(
                params, tok[:, :ONP_PROMPT], mesh, cfg, ONP_TOKENS)
        params, opt_state, _, metrics = step(
            params, opt_state, tok, jnp.asarray(chunk[:, 1:]),
            draft_tokens=draft_batch)
    m = {k: float(np.asarray(v)) for k, v in metrics.items()}
    for k in trunk:  # the freeze really froze
        np.testing.assert_array_equal(np.asarray(trunk[k]),
                                      np.asarray(params[k]))
    mode = "on-policy" if on_policy else "corpus"
    print(f"head distilled ({mode}, L_d={EXIT_LAYER}, "
          f"rank={DRAFT_RANK}, {steps} steps): draft_loss "
          f"{m['draft_loss']:.4f}, top1_agree "
          f"{m['draft_top1_agree']:.4f}", flush=True)
    return cfg, params, m


def measure_rows(quick: bool) -> list:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from icikit.models.transformer import speculative_generate

    teach_steps = 120 if quick else 3000
    distill_steps = 120 if quick else 3000
    n_new = 48 if quick else 96
    ks = (2,) if quick else (2, 4)
    branches = (1, 2) if quick else (1, 2, 4)
    mesh, trunk, sampler, final_loss = train_teacher(teach_steps)
    rows = []
    heads = {}
    for on_policy in (False, True):
        heads[on_policy] = distill_head(mesh, trunk, sampler,
                                        distill_steps, on_policy)
    sh = NamedSharding(mesh, P("dp", None))
    chunk = sampler(2**31 + 1, 1, 8)
    prompt = jax.device_put(jnp.asarray(chunk[:, :8]), sh)

    def measure(cfg, params, drafter, k, nb):
        _, st = speculative_generate(
            params, prompt, mesh, cfg, n_new, k=k,
            draft_layers=EXIT_LAYER, drafter=drafter,
            return_stats=True, tree_branch=nb)
        return st

    # off-policy trained rows: the r8 baseline re-measured on this
    # session's teacher — context rows (kind="acceptance_offpolicy",
    # NOT priced: the committed r8 rows already price that route);
    # what this study prices is the on-policy head and the trees
    cfg_off, params_off, tm_off = heads[False]
    for k in ks:
        st = measure(cfg_off, params_off, "trained", k, 1)
        rows.append({
            "kind": "acceptance_offpolicy",
            "corpus": "markov-order2",
            "protocol": "r8-posthoc-distill",
            "drafter": "trained",
            "train_steps": teach_steps,
            "distill_steps": distill_steps,
            "teacher_loss": round(final_loss, 4),
            "train_draft_top1_agree":
                round(tm_off["draft_top1_agree"], 4),
            "n_layers": cfg_off.n_layers,
            "batch": 1, "k": k, "draft_layers": EXIT_LAYER,
            "n_new": n_new,
            "acceptance_rate": round(st["acceptance_rate"], 4),
            "tokens_per_step": round(st["tokens_per_step"], 4),
        })
        print(f"off-policy baseline k={k}: "
              f"α={st['acceptance_rate']:.3f}", flush=True)

    cfg_on, params_on, tm_on = heads[True]
    for drafter, cfg, params in (("trained", cfg_on, params_on),
                                 ("ngram", cfg_on, params_on)):
        for k in ks:
            for nb in branches:
                st = measure(cfg, params, drafter, k, nb)
                row = {
                    "kind": "acceptance",
                    "corpus": "markov-order2",
                    "protocol": ("r14-onpolicy-distill"
                                 if drafter == "trained"
                                 else "r14-tree"),
                    "drafter": drafter,
                    "train_steps": teach_steps,
                    "distill_steps": distill_steps,
                    "teacher_loss": round(final_loss, 4),
                    "train_draft_top1_agree":
                        round(tm_on["draft_top1_agree"], 4),
                    "n_layers": cfg.n_layers,
                    "batch": 1, "k": k,
                    "draft_layers": EXIT_LAYER,
                    "n_new": n_new,
                    "tree_branch": nb,
                    "acceptance_rate":
                        round(st["acceptance_rate"], 4),
                    "tokens_per_step":
                        round(st["tokens_per_step"], 4),
                }
                if nb > 1:
                    row.update(
                        row_steps=st["row_steps"],
                        primary_accepted=st["primary_accepted"],
                        sideways_accepted=st["sideways_accepted"],
                        sideways_rate=round(st["sideways_rate"], 4))
                rows.append(row)
                print(f"acceptance {drafter} k={k} b={nb}: "
                      f"α={st['acceptance_rate']:.3f} "
                      f"tok/pass={st['tokens_per_step']:.3f}"
                      + (f" (sideways {st['sideways_accepted']})"
                         if nb > 1 else ""), flush=True)
    return rows


def verdict_row(json_path: str, rows: list, proj: list) -> dict:
    """The numbers the round exists for: (a) the best tree projection
    vs the 15% bar against the int8 floor, (b) the on-policy α at
    (k=2, quarter, chain) vs the 0.42 flip condition — both recorded
    honestly whether they clear or not."""
    onp = [r for r in rows if r["kind"] == "acceptance"
           and r["drafter"] == "trained" and r["k"] == 2
           and r.get("tree_branch", 1) == 1][0]
    off = [r for r in rows if r["kind"] == "acceptance_offpolicy"
           and r["k"] == 2][0]
    best = min(proj, key=lambda r: r["projected_eff_ms_per_token"])
    floor = best["model_floor_ms_dtype"]
    eff = best["projected_eff_ms_per_token"]
    return {
        "kind": "verdict",
        "round": 14,
        "alpha_source": json_path,
        "bytes_dtype": best["bytes_dtype"],
        "int8_floor_ms": floor,
        "alpha_offpolicy_k2_quarter": off["acceptance_rate"],
        "alpha_onpolicy_k2_quarter": onp["acceptance_rate"],
        "onpolicy_clears_042": onp["acceptance_rate"] >= 0.42,
        "best_projection": {
            "drafter": best["drafter"], "k": best["k"],
            "tree_branch": best.get("tree_branch", 1),
            "measured_acceptance": best["measured_acceptance"],
            "tokens_per_step": best.get("measured_tokens_per_step"),
            "projected_eff_ms_per_token": eff,
        },
        "projected_win_pct": round(100.0 * (1.0 - eff / floor), 2),
        "route_breaks_even": eff < floor,
        "route_clears_15pct": eff <= 0.85 * floor,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", dest="json_path",
                    default="decode_spec_r14.jsonl")
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized run (fewer steps/tokens/arms)")
    args = ap.parse_args(argv)

    rows = measure_rows(args.quick)
    with open(args.json_path, "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    # price every measured point through the shared one-command path
    # (bit-identical to `python -m icikit.bench.decode --cost-model
    # --alpha-from <file> --bytes-dtype int8`) — the r14 verdict
    # races the INT8 floor, the best single-token baseline this repo
    # has built
    from icikit.bench.decode import cost_model_rows
    proj = cost_model_rows(args.json_path, preset="base", batch=1,
                           cache_len=320, alpha_batch=1,
                           bytes_dtype="int8")
    verdict = verdict_row(args.json_path, rows, proj)
    with open(args.json_path, "a") as f:
        for r in proj + [verdict]:
            f.write(json.dumps(r) + "\n")
    for r in proj:
        print(f"projection {r['drafter']} k={r['k']} "
              f"b={r.get('tree_branch', 1)}: "
              f"α={r['measured_acceptance']:.3f} -> "
              f"{r['projected_eff_ms_per_token']} ms/tok vs int8 "
              f"floor {r['model_floor_ms_dtype']}", flush=True)
    print("verdict:", json.dumps(verdict), flush=True)
    print(f"wrote {len(rows) + len(proj) + 1} rows to "
          f"{args.json_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
