#!/usr/bin/env python
"""Fleet round-18 study: the kill-the-leader soak.

One campaign, appending to ``serve_fleet_ha_r18.jsonl``: a fleet of
2 engines + 3 coordinators (1 leader, 2 warm standbys) serves a
greedy trace while every HA failure mode fires at once:

- **leader death #1** (chaos): the seed leader is armed with
  ``die:fleet.journal.write`` — it dies MID-APPEND, leaving a torn
  half-frame at the journal tail that the promoting standby must
  detect (``torn`` counted in its elected event) and replay past.
- **leader death #2** (driver): the successor is SIGKILLed mid-decode
  once half the timed trace has completed; the last standby promotes.
- **double-leader drill**: the first standby is armed with
  ``io:fleet.ha.epoch`` — at promotion it mints a stale epoch and
  must recover through the journal's O_EXCL ``EpochCollision``
  backstop (observable as a ``fleet.leader.epoch_collision`` event).
- **rotten lease drill**: the second standby is armed with
  ``corrupt:fleet.ha.lease`` on two CONSECUTIVE reads — streak
  policy promotes it over the unreadable file, it loses the election
  to the live leader, and must fall back to tailing (the
  ``LostElection`` recovery path) instead of crashing.
- **engine churn**: one engine is chaos-killed mid-decode
  (``die:fleet.engine.die``) and the queue-depth watch alert spawns a
  token-authenticated joiner whose bridge-rewarmed first commit
  prices scale-up-to-first-token.

Exit bar: every request completes, every completed request's tokens
are bitwise identical to single-request decode, ZERO duplicate
commits, each driver-measured failover under 2x the lease timeout,
and every drill observed in the record.

Reproduce::

    python tools/fleet_ha_study.py --json serve_fleet_ha_r18.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from icikit.bench.fleet import run_fleet_ha  # noqa: E402


def soak(json_path: str | None = None, n_requests: int = 32,
         seed: int = 0, lease_timeout_s: float = 1.5,
         timeout_s: float = 900.0) -> dict:
    """The kill-the-leader soak; returns the record (and raises on
    any violated bar). Coordinators: coord0 (seed leader, dies
    mid-append), coord1 (promotes through the epoch-collision drill,
    then SIGKILLed), coord2 (rides the rotten-lease drill, finishes
    the trace). Engines: both0 (survivor), both1 (chaos-killed),
    joiner (alert-spawned)."""
    rec = run_fleet_ha(
        # decode lengths sized so the backlog outlives the join
        # alert: the scale-up-to-first-token bar needs the joiner to
        # claim work before the fleet drains
        n_engines=2, n_requests=n_requests, rate_rps=16.0,
        prompt_len=8, new_min=24, new_max=32, rows=2,
        n_standbys=2, kill_leader_at=(0.5,), join_engine=True,
        seed=seed, lease_s=5.0, lease_timeout_s=lease_timeout_s,
        heartbeat_timeout_s=2.0, snapshot_every=64,
        pending_high=4.0, verify=True, timeout_s=timeout_s,
        coord_env={
            # die mid-append once the decode window is under way:
            # write #60 lands after the warm phase (~30 records) and
            # the 32-submit burst, inside the timed claim/commit flow
            "coord0": {"ICIKIT_CHAOS":
                       "seed=11;die:fleet.journal.write=@60"},
            "coord1": {"ICIKIT_CHAOS":
                       "seed=12;io:fleet.ha.epoch=@0"},
            "coord2": {"ICIKIT_CHAOS":
                       "seed=13;corrupt:fleet.ha.lease=@6+7"},
        },
        engine_env={
            "both1": {"ICIKIT_CHAOS":
                      "seed=2;die:fleet.engine.die=@12"},
        })
    # the soak's bars, enforced loudly
    assert rec["completed"] == n_requests and not rec["failed"], rec
    assert rec["identity_ok"], rec
    assert rec["duplicate_commits"] == 0, rec
    # leader died twice: once mid-append (exit 23 is the
    # fleet.journal.write drill's signature), once by SIGKILL
    assert rec["coordinators"]["coord0"]["returncode"] == 23, rec
    assert rec["leader_kills"] >= 1, rec
    assert rec["elected_events"] >= 3, rec
    bar_ms = lease_timeout_s * 2 * 1e3
    assert all(ms < bar_ms for ms in rec["failover_ms"]), rec
    # the torn half-frame was seen and replayed past by a successor
    assert any(e.get("torn", 0) >= 1 for e in rec["elected"]), rec
    assert rec["chaos_events"]["epoch_collision"] >= 1, rec
    assert rec["chaos_events"]["lease_corrupt"] >= 2, rec
    # engine churn: both1 chaos-died (spawn order both0, both1,
    # joiner; a chaos-killed engine exits before its stats line, so
    # index by order), its work was reissued, and the alert-spawned
    # joiner priced scale-up-to-first-token
    assert rec["engines"][1]["returncode"] != 0, rec
    assert rec["reissues"] >= 1, rec
    assert rec["joined_engine"] is not None, rec
    assert rec["scaleup_ttft_ms"] is not None, rec
    if json_path:
        with open(json_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", dest="json_path",
                    default="serve_fleet_ha_r18.jsonl")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--lease-timeout", type=float, default=1.5)
    args = ap.parse_args(argv)
    rec = soak(args.json_path, n_requests=args.requests,
               seed=args.seed, lease_timeout_s=args.lease_timeout)
    print("SOAK_OK", json.dumps({
        "failover_ms": rec["failover_ms"],
        "elected": [e["takeover_ms"] for e in rec["elected"]],
        "scaleup_ttft_ms": rec["scaleup_ttft_ms"],
        "duplicate_commits": rec["duplicate_commits"],
        "chaos_events": rec["chaos_events"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
