"""Round-11 serving study: prefix caching + chunked prefill A/B —
the reproducible command behind serve_r11.jsonl.

Two questions, each answered by paired arms over the SAME seeded
workload (matched offered load, per-request token-identity audited
against single-request ``generate`` in every arm):

1. **Prefix caching** (cache on vs off, chunked admission in both):
   on the shared-prefix Poisson workload (system-prompt-shaped: a
   common 48-token prefix, 16-token unique suffixes, short outputs —
   the regime where prefill dominates TTFT), does block sharing
   deliver >= 1.3x tokens/s or >= 2x lower p50 TTFT? The cache-on arm
   measures steady state: warm-up seeds the shared prefix exactly as
   production traffic would have long since done.

2. **Chunked vs whole prefill** (cache off in both, isolating the
   admission discipline): with long prompts admitted into a decoding
   batch, does streaming the prompt through fixed-width chunks reduce
   the p99 TPOT long-prompt admission inflicts on co-batched
   decoders, vs paying the whole prompt in one program call?

Usage::

    JAX_PLATFORMS=cpu python tools/prefix_cache_study.py \
        [--out serve_r11.jsonl] [--seeds 0 1]

CPU-fp32 protocol throughout (the r9 rule: XLA:CPU re-packs bf16
weight operands per program call, which generate's scanned loop
hoists but a per-call engine step cannot — and the identity audit
additionally requires matched arithmetic between the engine's
per-call programs and generate's scanned loop, which on XLA:CPU only
fp32 provides). Every row is backend-stamped; absolute tokens/s waits
on a v5e session like every other decode-side number in this repo.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

try:
    import icikit  # noqa: F401
except ModuleNotFoundError:  # `python tools/prefix_cache_study.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

from icikit.bench.serve import run_bench

COMMON = dict(preset="tiny", rows=4, compute_dtype="float32",
              mode="continuous", verify=True)

# Q1: shared-prefix traffic, cache on/off (chunk 32 both arms).
# Short outputs keep prefill the dominant per-request cost — the
# traffic shape the cache exists for (system prompts / few-shot
# headers); rate 1000 is effectively all-at-once (saturated queue).
Q1 = dict(n_requests=16, rate_rps=1000.0, prompt_len=64,
          prefix_len=48, new_min=4, new_max=12, block_size=8,
          prefill_chunk=32)

# Q2: long prompts, no sharing (prefix 0), chunked (32) vs whole
# (prefill_chunk >= prompt -> one program call per admission). Longer
# outputs keep rows decoding while later prompts admit — the
# co-batched TPOT interference the chunk cap bounds. Prompt 256 puts
# the whole-prefill call well above this CPU's per-dispatch floor
# (at s <= 96 tiny-model prefill is dispatch-bound and chunking only
# multiplies dispatches — measured while scoping this study; the
# regime where the cap matters is long prompts, which is also the
# regime the feature exists for).
Q2 = dict(n_requests=10, rate_rps=1000.0, prompt_len=256,
          prefix_len=0, new_min=8, new_max=16, block_size=8)


def _arm(seed: int, label: str, **over) -> dict:
    kw = {**COMMON, **over}
    [rec] = run_bench(
        kw["preset"], kw["rows"], kw["n_requests"], kw["rate_rps"],
        kw["prompt_len"], kw["new_min"], kw["new_max"],
        kw["block_size"], seed=seed, mode=kw["mode"],
        compute_dtype=kw["compute_dtype"],
        prefix_len=kw["prefix_len"], prefix_cache=kw["prefix_cache"],
        prefill_chunk=kw["prefill_chunk"], verify=kw["verify"])
    rec["study"] = "r11"
    rec["arm"] = label
    assert rec["identity_ok"], (
        f"arm {label} seed {seed}: served tokens diverged from "
        "single-request generate — the A/B is void")
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="serve_r11.jsonl")
    ap.add_argument("--seeds", type=int, nargs="+", default=[0, 1])
    args = ap.parse_args(argv)

    rows = []
    for seed in args.seeds:
        on = _arm(seed, "prefix-cache-on", **Q1, prefix_cache=True)
        off = _arm(seed, "prefix-cache-off", **Q1, prefix_cache=False)
        rows += [on, off]
        tps = on["tokens_per_s"] / off["tokens_per_s"]
        ttft = off["ttft_ms"]["p50"] / on["ttft_ms"]["p50"]
        print(f"[seed {seed}] prefix cache: "
              f"{on['tokens_per_s']} vs {off['tokens_per_s']} tok/s "
              f"(x{tps:.2f}); p50 TTFT {on['ttft_ms']['p50']} vs "
              f"{off['ttft_ms']['p50']} ms (x{ttft:.2f} lower); "
              f"hit_tokens {on['prefix']['hit_tokens']}, "
              f"identity {on['identity_checked']}+"
              f"{off['identity_checked']} OK")

        chunked = _arm(seed, "chunked-prefill", **Q2,
                       prefix_cache=False, prefill_chunk=32)
        whole = _arm(seed, "whole-prefill", **Q2,
                     prefix_cache=False,
                     prefill_chunk=Q2["prompt_len"])
        rows += [chunked, whole]
        print(f"[seed {seed}] chunked vs whole prefill: p99 stall "
              f"(max inter-token gap) {chunked['gap_ms']['p99']} vs "
              f"{whole['gap_ms']['p99']} ms "
              f"(x{whole['gap_ms']['p99'] / chunked['gap_ms']['p99']:.2f} lower), "
              f"p99 TPOT {chunked['tpot_ms']['p99']} vs "
              f"{whole['tpot_ms']['p99']} ms; tok/s "
              f"{chunked['tokens_per_s']} vs {whole['tokens_per_s']} "
              f"(the cap trades throughput for tail latency)")

    with open(args.out, "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    print(f"appended {len(rows)} records to {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
