#!/usr/bin/env python
"""Fleet round-19 study: pricing the armed fleet telemetry plane.

A/B protocol, appending to ``serve_fleet_obs_r19.jsonl``: the SAME
2-engine fleet workload (Poisson arrivals, ``--verify-identity``
audited) runs disarmed and armed (``fleet_obs``: every worker
forwards bus events + metrics snapshots + trace deltas over the
coordinator RPC into the :class:`~icikit.obs.aggregate.FleetCollector`,
which also runs the watch detectors and merges the per-process
traces). Paired per seed, the armed/disarmed tokens/s ratio prices the
plane; the bar is **<5% overhead** — the forwarder's bounded queue and
drop-don't-stall design is what makes that possible, and the study
enforces it loudly.

Each armed row additionally pins the acceptance shape:

- zero telemetry loss (``dropped``/``corrupt_frames``/``lost_batches``
  all 0 — a healthy channel under a healthy run);
- the merged trace passes the structural checker
  (``python -m icikit.obs.check``) and carries ≥1 async request tree
  spanning two ENGINE processes (prefill → handoff → decode);
- the collector's health verdict is healthy.

CPU protocol note: the engine processes share this host's physical
cores with the coordinator, so the overhead measured here is an UPPER
bound on separate-host overhead (the collector steals cycles from the
same socket the engines decode on). The TPU/multi-host session
re-prices absolutes (ROADMAP item 5 ledger).

Reproduce::

    python tools/fleet_obs_study.py --json serve_fleet_obs_r19.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from icikit.bench.fleet import run_fleet  # noqa: E402
from icikit.obs import chrome  # noqa: E402

ARM_KW = dict(
    prompt_len=12, new_min=4, new_max=8, roles="disagg",
    prefix_len=8, verify=True, timeout_s=900.0)


def study(json_path: str | None, seeds=(0, 1), n_engines: int = 2,
          requests: int = 24, rate: float = 60.0,
          overhead_bar_pct: float = 5.0) -> list:
    recs = []
    for seed in seeds:
        base = run_fleet(n_engines, requests, rate, seed=seed,
                         **ARM_KW)
        assert base["identity_ok"] and not base["failed"], base
        trace_path = os.path.join(
            tempfile.mkdtemp(prefix="icikit_fleet_obs_"),
            "merged_trace.json")
        armed = run_fleet(n_engines, requests, rate, seed=seed,
                          fleet_obs=True, obs_out=trace_path,
                          **ARM_KW)
        assert armed["identity_ok"] and not armed["failed"], armed
        tel = armed["telemetry"]
        assert tel["dropped"] == 0, tel
        assert tel["corrupt_frames"] == 0, tel
        assert tel["lost_batches"] == 0, tel
        assert tel["batches"] >= 1, tel
        assert armed["obs_verdict"]["healthy"], armed["obs_verdict"]
        assert armed["cross_process_trees"] >= 1, armed
        problems = chrome.validate(trace_path)
        assert problems == [], problems
        overhead_pct = 100.0 * (1.0 - armed["tokens_per_s"]
                                / base["tokens_per_s"])
        rec = {
            "kind": "serve_fleet_obs",
            "n_engines": n_engines,
            "n_requests": requests,
            "seed": seed,
            "tokens_per_s_base": base["tokens_per_s"],
            "tokens_per_s_armed": armed["tokens_per_s"],
            "overhead_pct": round(overhead_pct, 2),
            "overhead_bar_pct": overhead_bar_pct,
            "telemetry": tel,
            "obs_verdict": armed["obs_verdict"],
            "cross_process_trees": armed["cross_process_trees"],
            "identity_ok": armed["identity_ok"]
            and base["identity_ok"],
            "note": "paired armed/disarmed 2-engine disagg arm; CPU "
                    "co-located collector, so overhead is an upper "
                    "bound on separate-host overhead",
        }
        recs.append(rec)
        if json_path:
            with open(json_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        print(json.dumps({  # icikit-lint: off[obs-print]
            "seed": seed,
            "base": base["tokens_per_s"],
            "armed": armed["tokens_per_s"],
            "overhead_pct": rec["overhead_pct"],
            "cross_process_trees": rec["cross_process_trees"]}))
    mean_overhead = sum(r["overhead_pct"] for r in recs) / len(recs)
    print(json.dumps({  # icikit-lint: off[obs-print]
        "mean_overhead_pct": round(mean_overhead, 2),
        "bar_pct": overhead_bar_pct,
        "within_bar": mean_overhead < overhead_bar_pct}))
    assert mean_overhead < overhead_bar_pct, \
        f"armed fleet obs costs {mean_overhead:.2f}% tokens/s " \
        f"(bar {overhead_bar_pct}%)"
    return recs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", dest="json_path",
                    default="serve_fleet_obs_r19.jsonl")
    ap.add_argument("--seeds", type=int, nargs="+", default=[0, 1])
    ap.add_argument("--engines", type=int, default=2)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--rate", type=float, default=60.0)
    args = ap.parse_args(argv)
    study(args.json_path, seeds=tuple(args.seeds),
          n_engines=args.engines, requests=args.requests,
          rate=args.rate)
    return 0


if __name__ == "__main__":
    sys.exit(main())
