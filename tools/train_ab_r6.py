"""Round-6 train-ceiling structural A/Bs (VERDICT r5 #1/#2) at the
base preset: the two named routes — fused head backward (dx/dw
contracted in-kernel, no g round-trip) and the Pallas save-stack
writer — measured against the r5 combined winner (saved head + bf16
moments), plus the constant-shift forward, interleaved within one
session so every variant sees the same tunnel mood. The session
canary (utils/timing.session_canary) is stamped into every record via
session_quality. Appends records to train_ab_r6.jsonl.

Usage: python tools/train_ab_r6.py [batch ...]   (default: 8)
"""

import json
import sys

from icikit.bench.train import run_bench


def main():
    batches = [int(b) for b in (sys.argv[1:] or ["8"])]
    variants = [
        # r5 combined winner re-measured = this session's baseline
        dict(head="saved", optimizer="fused-bf16mom",
             head_bwd="matmul", softmax_shift=None),
        # route (1): fused head backward, saved + recompute flavors
        dict(head="saved", optimizer="fused-bf16mom",
             head_bwd="fused", softmax_shift=None),
        dict(head="recompute", optimizer="fused-bf16mom",
             head_bwd="fused", softmax_shift=None),
        # + the constant-shift forward (the defaults-audit winner)
        dict(head="saved", optimizer="fused-bf16mom",
             head_bwd="fused", softmax_shift=16.0),
        # route (2): the Pallas save-stack writer, on the best config
        dict(head="saved", optimizer="fused-bf16mom",
             head_bwd="fused", softmax_shift=16.0,
             save_stack="pallas"),
        # shipped-defaults run (must reproduce the headline row)
        dict(),
    ]
    for batch in batches:
        for v in variants:
            rec = run_bench("base", 1, 1, 1, batch, steps=10, warmup=3,
                            windows=3, **v)
            rec["ab"] = v
            print(json.dumps(rec), flush=True)
            with open("train_ab_r6.jsonl", "a") as f:
                f.write(json.dumps(rec) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
