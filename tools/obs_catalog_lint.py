#!/usr/bin/env python
"""Lint: every serving / speculation telemetry name emitted in code
must appear in docs/OBSERVABILITY.md.

The watch layer and the bench regression gate both key on metric NAMES
(``serve.ttft_ms``, ``decode.spec.draft_accepted``, ...). A counter
that exists in code but not in the catalog is telemetry nobody can
alarm on or will remember exists; a renamed counter silently orphans
its alert rule. This lint walks ``icikit/`` for literal
``obs.count/observe/gauge/emit`` names under the ``serve.*`` and
``decode.spec.*`` prefixes — plus the async request-span names the
trace_ctx layer opens — and fails on any name the catalog does not
mention. (The doc may document MORE than code emits — planned names
are fine; the failure mode is only code the doc lost track of.)
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
DOC = ROOT / "docs" / "OBSERVABILITY.md"

EMIT_RE = re.compile(
    r'obs\.(?:count|observe|gauge|emit)\(\s*"'
    r'((?:serve|decode\.spec)\.[^"]+)"')
# request-scoped async span/instant names (trace_ctx call sites in
# serve/: self-opens inside trace_ctx.py itself count too)
CTX_RE = re.compile(
    r'\.(?:open|close|instant|span)\(\s*"(serve\.req[^"]*)"')


def emitted_names() -> set:
    names = set()
    for path in sorted((ROOT / "icikit").rglob("*.py")):
        text = path.read_text()
        names.update(EMIT_RE.findall(text))
        names.update(CTX_RE.findall(text))
    return names


def main() -> int:
    if not DOC.exists():
        print(f"obs catalog lint: {DOC} missing", file=sys.stderr)
        return 1
    doc = DOC.read_text()
    missing = sorted(n for n in emitted_names() if n not in doc)
    if missing:
        print("telemetry emitted in code but absent from "
              "docs/OBSERVABILITY.md's catalog:", file=sys.stderr)
        for n in missing:
            print(f"  {n}", file=sys.stderr)
        return 1
    print(f"obs catalog lint OK: {len(emitted_names())} "
          "serve.*/decode.spec.* names all catalogued")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
