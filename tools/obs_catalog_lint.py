#!/usr/bin/env python
"""Thin shim: this lint is now the ``obs-catalog`` rule of the
unified analysis framework (``icikit.analysis``, docs/ANALYSIS.md) —
every serving / speculation telemetry name emitted in code must
appear in docs/OBSERVABILITY.md. Backward compatible as an ENTRY
POINT (same exit codes); the re-exported helpers are the framework
forms — ``emitted_names`` now takes a ``Project`` and returns a
``name -> (path, line)`` dict, not the old zero-arg set. ``make
check`` runs the whole suite as ``python -m icikit.analysis --gate``.

Run standalone: ``python tools/obs_catalog_lint.py``.
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from icikit.analysis.rules.obs_catalog import (  # noqa: E402,F401
    CTX_RE,
    EMIT_RE,
    check_obs_catalog,
    emitted_names,
)

RULE = "obs-catalog"


def main() -> int:
    from icikit.analysis import shim_main
    return shim_main(RULE, "obs catalog lint OK (via icikit."
                           "analysis): serve.*/decode.spec.* names "
                           "all catalogued")


if __name__ == "__main__":
    raise SystemExit(main())
