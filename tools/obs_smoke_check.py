#!/usr/bin/env python
"""`make obs-smoke` assertion half: the exported trace must hold at
least one COMPLETE request span tree and the bench row's health
verdict must be clean.

Usage::

    python tools/obs_smoke_check.py TRACE_JSON BENCH_JSONL

Checks (beyond ``icikit.obs.check``'s structural validation, which
the Makefile runs separately):

- the trace contains >= 1 ``serve.req`` async tree, and every tree is
  WHOLE: balanced b/e, a ``serve.req`` root that closed on its own
  (no ``closed_by: export`` synthetics — a clean drained run has no
  dangling request state), at least one prefill span and one step
  participation instant among the trees;
- the bench jsonl's continuous row carries ``health.healthy == true``
  with zero alerts (the clean-run half of the watch contract; the
  chaos soaks assert the opposite on drilled runs).
"""

from __future__ import annotations

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:  # runnable as `python tools/obs_smoke_check.py`
    sys.path.insert(0, ROOT)

from icikit.obs import trace_ctx  # noqa: E402


def check_trace(path: str) -> list:
    with open(path) as f:
        events = json.load(f).get("traceEvents", [])
    problems = []
    trees = trace_ctx.request_trees(events)
    if not trees:
        return [f"{path}: no serve.req request trees in trace"]
    saw_prefill = saw_step = False
    for tid, evs in trees.items():
        opens = sum(1 for e in evs if e["ph"] == "b")
        closes = sum(1 for e in evs if e["ph"] == "e")
        if opens != closes:
            problems.append(f"{tid}: {opens} opens vs {closes} closes")
        if not any(e["ph"] == "b" and e["name"] == "serve.req"
                   for e in evs):
            problems.append(f"{tid}: no serve.req root span")
        synth = [e["name"] for e in evs
                 if e.get("args", {}).get("closed_by") == "export"]
        if synth:
            problems.append(
                f"{tid}: spans only closed by export: {synth} "
                "(request state dangled past drain)")
        names = {e["name"] for e in evs}
        saw_prefill |= bool(names & {"serve.req.prefill.chunk",
                                     "serve.req.prefill.whole"})
        saw_step |= "serve.req.step" in names
    if not saw_prefill:
        problems.append("no request tree holds a prefill span")
    if not saw_step:
        problems.append("no request tree holds a step instant")
    if not problems:
        print(f"obs-smoke trace OK: {len(trees)} complete request "
              f"tree(s)")
    return problems


def check_health(path: str) -> list:
    rows = [json.loads(l) for l in open(path) if l.strip()]
    cont = [r for r in rows if r.get("mode") == "continuous"]
    if not cont:
        return [f"{path}: no continuous bench row"]
    problems = []
    for r in cont:
        h = r.get("health")
        if not isinstance(h, dict):
            problems.append(f"{path}: row has no health verdict "
                            "(watch not armed?)")
        elif not h.get("healthy") or h.get("n_alerts"):
            problems.append(f"{path}: clean run verdicted unhealthy: "
                            f"{h.get('alerts')}")
        elif h.get("polls", 0) < 1:
            problems.append(f"{path}: watch never polled")
    if not problems:
        print(f"obs-smoke health OK: {len(cont)} clean continuous "
              "row(s), zero alerts")
    return problems


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    problems = check_trace(argv[0]) + check_health(argv[1])
    for p in problems:
        print(f"OBS-SMOKE FAIL: {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
