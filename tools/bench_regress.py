#!/usr/bin/env python
"""Noise-aware bench regression gate over the committed jsonl ledgers.

The repo's bench records (serve_r*.jsonl, decode_spec_r*.jsonl,
scaling.jsonl, ...) are measurements, but nothing *guards* them: a PR
that silently costs 20% of serving throughput lands green as long as
the tests pass. This gate closes that hole mechanically:

- **paired arms** — rows are grouped by their CONFIG KEY: every
  string/bool field plus the known numeric workload knobs, minus
  ``seed``. Only groups present in BOTH ledgers are compared, so a
  fresh ledger may add arms freely and a baseline arm that was not
  re-measured simply does not gate.
- **provenance-checked** — ``backend`` / ``compute_dtype`` /
  ``decode_quant`` / ``note`` are part of the key, so a CPU row can
  never gate a TPU row (or vice versa): same-provenance rows compare,
  different-provenance rows are disjoint groups.
- **median-of-seeds** — within a group, the compared statistic is the
  median across seed replicas, not any single noisy run.
- **tolerance bands** — each metric carries a direction and a relative
  tolerance (``--metric tokens_per_s:higher:0.1``); the effective band
  additionally widens to the baseline group's own relative half-spread
  across seeds, so a metric that is intrinsically noisy at this
  workload scale cannot flap the gate.
- **machine-readable verdict** — ``--verdict PATH`` writes the full
  comparison (regressions, improvements, unmatched arms) as JSON; the
  exit code is the gate.

Modes::

    # gate a fresh re-measure of a ledger's arms against the
    # committed baseline (fails loudly when NOTHING paired — a gate
    # that compared zero arms must not pass)
    python tools/bench_regress.py --baseline serve_r15.jsonl \\
        --fresh /tmp/serve_remeasure.jsonl --verdict /tmp/verdict.json

    # self-check (make check): the unmodified ledger must pass against
    # itself AND an injected 20% throughput regression must be flagged
    python tools/bench_regress.py --self-check serve_r12.jsonl
"""

from __future__ import annotations

import argparse
import copy
import json
import statistics
import sys

# numeric fields that are workload CONFIG, not measurement (string and
# bool fields are config by rule; "seed" is the replica axis)
NUMERIC_CONFIG = {
    "rows", "dp", "tp", "sp", "n_requests", "rate_rps", "prompt_len",
    "new_min", "new_max", "block_size", "n_blocks", "speculate",
    "tree_branch", "ngram_n", "prefix_len", "prefill_chunk",
    "temperature", "top_k", "top_p", "distinct", "motif", "k", "b",
    "batch", "n_new", "prompt", "draft_layers", "n_layers",
    "train_steps", "distill_steps", "d_model", "n_heads", "d_head",
    "d_ff", "vocab", "max_seq", "runs", "reps", "tokens_per_s_reps",
    "tenants", "zipf", "host_cache_blocks", "n_prompts",
    # fleet rows (serve_fleet_r17.jsonl): engine count is a workload
    # knob — a 4-engine arm must never gate a 1-engine arm
    "n_engines", "lease_s",
    # HA rows (serve_fleet_ha_r18.jsonl): failover timing is priced
    # BY these knobs, so arms only pair within identical HA config
    "n_standbys", "lease_timeout_s", "snapshot_every",
    # cache-aware dispatch rows (serve_fleet_route_r20.jsonl): the
    # host-RAM bridge capacity is a tier knob — a RAM-tier arm must
    # never gate a disk-only arm
    "bridge_ram",
}

# (path, direction, default relative tolerance) — applied when the
# metric resolves in both groups; unknown-to-a-ledger metrics just
# don't gate it
DEFAULT_METRICS = (
    ("tokens_per_s", "higher", 0.10),
    ("ttft_ms.p50", "lower", 0.50),
    ("tpot_ms.p50", "lower", 0.50),
    ("acceptance_rate", "higher", 0.10),
    ("tokens_per_step", "higher", 0.10),
    # r16 tiered-KV rows: the rewarm A/B gates on time-to-first-
    # completion, the spill arms on hit tokens (both noisy at CPU
    # smoke scale, hence the wide bands — the seed-spread widening
    # still applies on top)
    ("ttfc_ms", "lower", 0.50),
    ("prefix.hit_tokens", "higher", 0.25),
    # r20 cache-aware dispatch rows: the study's pairing lands nested
    # per-arm, so the gate reads the routed arms' locality/traffic
    # wins and the weight-rebuild component of scale-up TTFT directly
    ("homog.routed.prefix_hit_ratio", "higher", 0.10),
    ("disagg.routed.migration_bytes", "lower", 0.20),
    ("build_s_cache_warm", "lower", 0.50),
)


def load_rows(paths: list) -> list:
    rows = []
    for path in paths:
        with open(path) as f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError as e:
                    raise SystemExit(
                        f"{path}:{ln}: not valid JSON ({e})")
                if isinstance(row, dict):
                    rows.append(row)
    return rows


def config_key(row: dict) -> tuple:
    """The pairing identity of a row: sorted (field, value) over every
    config field. Strings and bools are config by rule (that is what
    makes the key provenance-checked: backend/compute_dtype/note are
    strings); numbers only via the known-knob list; ``seed`` never."""
    items = []
    for k, v in row.items():
        if k == "seed":
            continue
        if k == "tracing" and v is False:
            # the r15 observability A/B field: False IS the historical
            # default every pre-r15 row carries implicitly — dropping
            # it lets fresh disarmed rows pair with committed
            # baselines, while tracing-armed rows (measurably slower
            # by design) stay a distinct arm
            continue
        if isinstance(v, bool) or isinstance(v, str):
            items.append((k, v))
        elif isinstance(v, (int, float)) and k in NUMERIC_CONFIG:
            items.append((k, v))
    return tuple(sorted(items))


def resolve(row: dict, path: str):
    """Dotted-path metric lookup (``ttft_ms.p50``); None when absent
    or non-numeric."""
    cur = row
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def group_rows(rows: list) -> dict:
    groups: dict = {}
    for row in rows:
        groups.setdefault(config_key(row), []).append(row)
    return groups


def _median_and_spread(vals: list) -> tuple:
    """(median, relative half-spread) across seed replicas — the
    spread is the noise floor the tolerance band widens to."""
    med = statistics.median(vals)
    if len(vals) < 2 or med == 0:
        return med, 0.0
    half = (max(vals) - min(vals)) / 2.0
    return med, abs(half / med)


def compare(baseline_rows: list, fresh_rows: list,
            metrics=DEFAULT_METRICS) -> dict:
    """The gate: returns the verdict dict (``ok`` == no regression)."""
    base = group_rows(baseline_rows)
    fresh = group_rows(fresh_rows)
    shared = [k for k in fresh if k in base]
    regressions, improvements, compared = [], [], 0
    for key in shared:
        label = {k: v for k, v in key}
        label = {k: label[k] for k in
                 ("kind", "mode", "backend", "preset", "drafter")
                 if k in label}
        for path, direction, tol in metrics:
            bvals = [v for v in (resolve(r, path) for r in base[key])
                     if v is not None]
            fvals = [v for v in (resolve(r, path) for r in fresh[key])
                     if v is not None]
            if not bvals or not fvals:
                continue
            bmed, bnoise = _median_and_spread(bvals)
            fmed, _ = _median_and_spread(fvals)
            compared += 1
            if bmed == 0:
                continue
            band = max(tol, bnoise)
            ratio = fmed / bmed
            worse = (ratio < 1.0 - band if direction == "higher"
                     else ratio > 1.0 + band)
            better = (ratio > 1.0 + band if direction == "higher"
                      else ratio < 1.0 - band)
            entry = {
                "metric": path, "direction": direction,
                "baseline": bmed, "fresh": fmed,
                "ratio": round(ratio, 4), "band": round(band, 4),
                "n_baseline": len(bvals), "n_fresh": len(fvals),
                "arm": label,
            }
            if worse:
                regressions.append(entry)
            elif better:
                improvements.append(entry)
    return {
        "ok": not regressions,
        "compared": compared,
        "paired_arms": len(shared),
        "fresh_only_arms": len(fresh) - len(shared),
        "baseline_only_arms": len(base) - len(shared),
        "regressions": regressions,
        "improvements": improvements,
    }


def parse_metric(spec: str) -> tuple:
    parts = spec.split(":")
    if len(parts) != 3 or parts[1] not in ("higher", "lower"):
        raise SystemExit(
            f"bad --metric {spec!r} (want PATH:higher|lower:TOL)")
    return parts[0], parts[1], float(parts[2])


def self_check(paths: list, metrics, inject: float = 0.8) -> dict:
    """The gate's own drill (``make check``): the unmodified ledger
    must pass against itself, and a synthetic throughput regression
    (every higher-is-better metric scaled by ``inject``) must be
    flagged — a gate that cannot see a planted 20% loss is not a
    gate."""
    rows = load_rows(paths)
    if not rows:
        raise SystemExit(f"no rows in {paths}")
    clean = compare(rows, rows, metrics)
    hurt = copy.deepcopy(rows)
    n_injected = 0
    for row in hurt:
        for path, direction, _ in metrics:
            if direction != "higher":
                continue
            cur = resolve(row, path)
            if cur is None:
                continue
            # dotted paths: walk to the leaf's parent
            parts = path.split(".")
            parent = row
            for p in parts[:-1]:
                parent = parent[p]
            parent[parts[-1]] = cur * inject
            n_injected += 1
    injected = compare(rows, hurt, metrics)
    return {
        "mode": "self-check",
        "ledgers": paths,
        "rows": len(rows),
        "clean_pass": clean["ok"],
        "clean": clean,
        "injected_scale": inject,
        "injected_metrics": n_injected,
        "injection_flagged": bool(injected["regressions"]),
        "injected": injected,
        "ok": clean["ok"] and (n_injected == 0
                              or bool(injected["regressions"])),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--baseline", action="append", default=[],
                    metavar="JSONL", help="committed baseline ledger "
                    "(repeatable; rows pool)")
    ap.add_argument("--fresh", action="append", default=[],
                    metavar="JSONL", help="freshly measured ledger "
                    "(repeatable)")
    ap.add_argument("--metric", action="append", default=[],
                    metavar="PATH:DIR:TOL",
                    help="gate metric, e.g. tokens_per_s:higher:0.1 "
                         "(repeatable; replaces the defaults)")
    ap.add_argument("--self-check", nargs="+", default=None,
                    metavar="JSONL",
                    help="gate drill: ledger(s) must pass against "
                         "themselves and flag an injected regression")
    ap.add_argument("--inject", type=float, default=0.8,
                    help="self-check injection scale on "
                         "higher-is-better metrics (default 0.8 = "
                         "a 20%% loss)")
    ap.add_argument("--verdict", default=None, metavar="PATH",
                    help="write the machine-readable verdict JSON")
    ap.add_argument("--require-paired", type=int, default=1,
                    metavar="N", help="fail unless at least N arms "
                    "paired (default 1: a gate that compared nothing "
                    "must FAIL, not silently pass — 0 opts out)")
    args = ap.parse_args(argv)
    metrics = ([parse_metric(m) for m in args.metric]
               if args.metric else DEFAULT_METRICS)
    if args.self_check is not None:
        if args.baseline or args.fresh:
            raise SystemExit("--self-check excludes --baseline/--fresh")
        verdict = self_check(args.self_check, metrics, args.inject)
        desc = (f"self-check {', '.join(args.self_check)}: "
                f"clean_pass={verdict['clean_pass']} "
                f"injection_flagged={verdict['injection_flagged']} "
                f"({verdict['rows']} rows, "
                f"{verdict['clean']['paired_arms']} arms)")
    else:
        if not args.baseline or not args.fresh:
            ap.error("need --baseline and --fresh (or --self-check)")
        verdict = compare(load_rows(args.baseline),
                          load_rows(args.fresh), metrics)
        verdict["mode"] = "gate"
        if verdict["paired_arms"] < args.require_paired:
            verdict["ok"] = False
            verdict["error"] = (
                f"only {verdict['paired_arms']} arms paired "
                f"(require {args.require_paired}) — config keys "
                "probably drifted")
        desc = (f"gate: {verdict['paired_arms']} arms paired, "
                f"{verdict['compared']} metric comparisons, "
                f"{len(verdict['regressions'])} regressions, "
                f"{len(verdict['improvements'])} improvements")
    if args.verdict:
        with open(args.verdict, "w") as f:
            json.dump(verdict, f, indent=1)
    ok = verdict["ok"]
    print(("PASS " if ok else "FAIL ") + desc)
    if "error" in verdict:
        print(f"  {verdict['error']}", file=sys.stderr)
    # self-check failures are the CLEAN pass's regressions (the
    # injected pass is SUPPOSED to regress — only its absence fails)
    detail = (verdict["clean"]["regressions"]
              if verdict.get("mode") == "self-check"
              else verdict.get("regressions", []))
    for r in detail:
        print(f"  REGRESSION {r['metric']} {r['baseline']:.4g} -> "
              f"{r['fresh']:.4g} (ratio {r['ratio']}, band "
              f"{r['band']}) arm={r['arm']}", file=sys.stderr)
    if (verdict.get("mode") == "self-check"
            and not verdict["injection_flagged"]
            and verdict["injected_metrics"]):
        print("  injected regression NOT flagged — tolerance bands "
              "swallow a planted "
              f"{1 - verdict['injected_scale']:.0%} loss",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
