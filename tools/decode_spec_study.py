"""Round-7 multi-token decode study driver (DECODE.md "Multi-token
decode").

Three measured surfaces, each stamped as JSON rows (append-mode, like
every study record file):

1. **A/B wall-time rows** (``kind="ab"``): baseline single-token vs
   fused single-token vs speculative k ∈ {2, 4, 8}, tiny presets,
   b ∈ {1, 8}, escalating-windows protocol + session canary — run by
   ``icikit.bench.decode.run_bench`` wherever this executes (rows
   carry ``backend``; a CPU session measures the machinery and the
   acceptance, not v5e wall time).
2. **Trained-model acceptance rows** (``kind="acceptance"``): the
   device-independent half of the cost model. A small transformer is
   trained in-process on the order-2 Markov corpus (the repo's
   standard synthetic traffic), then the self-speculative acceptance
   rate is measured per (k, draft_layers) at b ∈ {1, 8}. Random-init
   acceptance (the floor) is recorded alongside.
3. **Projection rows** (``kind="projection"``): the acceptance × cost
   model evaluated at the base-preset b=1 geometry for each measured
   acceptance point, plus the break-even acceptance per (k, L_d) —
   what DECODE.md's verdict table renders.

Usage::

    JAX_PLATFORMS=cpu python tools/decode_spec_study.py \
        --json decode_spec_r7.jsonl [--quick]
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def ab_rows(quick: bool) -> list:
    from icikit.bench.decode import run_bench
    rows = []
    n_new = 16 if quick else 32
    for batch in (1, 8):
        for spec, dl in ((0, 0), (2, 1), (4, 1), (8, 1)):
            rec = run_bench("tiny", dp=1, tp=1, batch=batch,
                            prompt_len=8, n_new=n_new, runs=1,
                            speculate=spec, draft_layers=dl)
            rec["kind"] = "ab"
            rows.append(rec)
            print(f"ab tiny b={batch} spec={spec}: "
                  f"{rec['per_token_ms']} ms/tok", flush=True)
    # fused vs unfused single-token step needs the d_head=128 geometry
    for step in ("unfused", "fused"):
        rec = run_bench("tiny128", dp=1, tp=1, batch=1, prompt_len=8,
                        n_new=n_new, runs=1, decode_step=step)
        rec["kind"] = "ab"
        rows.append(rec)
        print(f"ab tiny128 b=1 {step}: {rec['per_token_ms']} ms/tok",
              flush=True)
    return rows


def train_toy(steps: int):
    """Train the acceptance-study model on the Markov corpus with the
    library train step (order-2 structure is learnable by shallow
    layers — exactly the regime a truncated-depth drafter serves)."""
    import jax
    import jax.numpy as jnp
    import optax

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import (make_model_mesh,
                                                 make_train_step)
    from icikit.models.transformer.train import make_markov_sampler

    cfg = TransformerConfig(vocab=64, d_model=64, n_heads=2, d_head=32,
                            d_ff=256, n_layers=4, max_seq=160,
                            compute_dtype="float32")
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    sampler = make_markov_sampler(cfg.vocab, seed=0)
    _, step = make_train_step(mesh, cfg, optax.adam(3e-3))
    opt_state = optax.adam(3e-3).init(params)
    loss = None
    for s in range(steps):
        chunk = sampler(s, 16, 64)
        tok = jnp.asarray(chunk[:, :-1])
        tgt = jnp.asarray(chunk[:, 1:])
        params, opt_state, loss = step(params, opt_state, tok, tgt)
    final = float(np.asarray(loss))
    print(f"toy model trained: {steps} steps, final loss "
          f"{final:.3f}", flush=True)
    return cfg, mesh, params, sampler, final


def acceptance_rows(quick: bool) -> list:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from icikit.models.transformer import init_params, speculative_generate

    # the order-2 structure groks late on this geometry (loss flat at
    # ~4.0 until ~1250 steps, then 2.1 by 1750 — measured in-session);
    # 3000 steps lands a genuinely predictive model
    steps = 120 if quick else 3000
    n_new = 48 if quick else 96
    cfg, mesh, params, sampler, final_loss = train_toy(steps)
    rand_params = init_params(jax.random.key(7), cfg, mesh)
    sh = NamedSharding(mesh, P("dp", None))
    rows = []
    for batch in (1, 8):
        chunk = sampler(2**31 + batch, batch, 8)
        prompt = jax.device_put(jnp.asarray(chunk[:, :8]), sh)
        for k in (2, 4, 8):
            for dl in (1, 2):
                _, st = speculative_generate(
                    params, prompt, mesh, cfg, n_new, k=k,
                    draft_layers=dl, return_stats=True)
                _, st_r = speculative_generate(
                    rand_params, prompt, mesh, cfg, n_new, k=k,
                    draft_layers=dl, return_stats=True)
                rows.append({
                    "kind": "acceptance",
                    "corpus": "markov-order2",
                    "train_steps": steps,
                    "final_loss": round(final_loss, 4),
                    "n_layers": cfg.n_layers,
                    "batch": batch, "k": k, "draft_layers": dl,
                    "n_new": n_new,
                    "acceptance_rate": round(st["acceptance_rate"], 4),
                    "tokens_per_step": round(st["tokens_per_step"], 4),
                    "acceptance_rate_random_init":
                        round(st_r["acceptance_rate"], 4),
                })
                print(f"acceptance b={batch} k={k} dl={dl}: "
                      f"{st['acceptance_rate']:.3f} trained "
                      f"({st_r['acceptance_rate']:.3f} random)",
                      flush=True)
    return rows


def projection_rows(acc_rows: list) -> list:
    """Base-preset b=1 projections at each measured acceptance point +
    the break-even acceptance curve per (k, draft fraction)."""
    from icikit.bench.decode import (SPEC_FLOOR_MS, spec_cost_model)
    from icikit.bench.train import PRESETS
    from icikit.models.transformer import TransformerConfig

    cfg = TransformerConfig(**PRESETS["base"])
    cache_len = 320  # 64-token prompt + 256 generated, the study shape
    rows = []
    for k in (2, 4, 8):
        for frac in (0.25, 0.5):
            ld = max(1, round(cfg.n_layers * frac))
            m = spec_cost_model(cfg, 1, cache_len, k, ld,
                                tokens_per_step=1.0)
            iter_ms = m["model_iter_ms"]
            be = (iter_ms / SPEC_FLOOR_MS - 1) / (k - 1)
            be15 = (iter_ms / (0.85 * SPEC_FLOOR_MS) - 1) / (k - 1)
            row = {
                "kind": "projection", "preset": "base", "batch": 1,
                "k": k, "draft_layers": ld,
                "draft_fraction": frac,
                "model_iter_ms": iter_ms,
                "floor_ms": SPEC_FLOOR_MS,
                "breakeven_acceptance": round(be, 4),
                "breakeven_acceptance_15pct": round(be15, 4),
            }
            # attach the measured trained-toy acceptance at the same
            # depth fraction (b=1 row) and its projected effective cost
            match = [r for r in acc_rows
                     if r["batch"] == 1 and r["k"] == k
                     and r["draft_layers"] / r["n_layers"] == frac]
            if match:
                a = match[0]["acceptance_rate"]
                tps = 1 + (k - 1) * a
                proj = spec_cost_model(cfg, 1, cache_len, k, ld,
                                       tokens_per_step=tps)
                row.update({
                    "measured_acceptance_toy": a,
                    "projected_eff_ms_per_token":
                        proj["projected_eff_ms_per_token"],
                    "projected_vs_floor": proj["projected_vs_floor"],
                })
            rows.append(row)
            print(f"projection k={k} frac={frac}: iter "
                  f"{iter_ms:.3f} ms, break-even α={be:.3f} "
                  f"(15% win α={be15:.3f})"
                  + (f", toy α={row.get('measured_acceptance_toy')}"
                     f" -> {row.get('projected_eff_ms_per_token')}"
                     " ms/tok" if match else ""), flush=True)
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", dest="json_path",
                    default="decode_spec_r7.jsonl")
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized run (fewer steps/tokens)")
    ap.add_argument("--skip-ab", action="store_true")
    args = ap.parse_args(argv)
    rows = []
    if not args.skip_ab:
        rows += ab_rows(args.quick)
    acc = acceptance_rows(args.quick)
    rows += acc
    rows += projection_rows(acc)
    with open(args.json_path, "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    print(f"wrote {len(rows)} rows to {args.json_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
