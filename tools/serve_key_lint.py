"""Thin shim: this lint is now the ``serve-key`` rule of the unified
analysis framework (``icikit.analysis``, docs/ANALYSIS.md) — no
unkeyed randomness inside ``icikit/serve/``. Backward compatible as
an ENTRY POINT (same exit codes); the semantics and the ``BANNED``
pattern table (same ``(regex, why)`` shape as before) live in
``icikit.analysis.rules.serve_key``; ``make check`` runs the whole
suite as ``python -m icikit.analysis --gate``.

Run standalone: ``python tools/serve_key_lint.py`` — exits nonzero
with the offending lines on a hit, exactly like the pre-framework
script.
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from icikit.analysis.rules.serve_key import (  # noqa: E402,F401
    BANNED,
    check_serve_key,
)

RULE = "serve-key"


def main() -> int:
    from icikit.analysis import shim_main
    return shim_main(RULE, "serve-key-lint OK (via icikit.analysis): "
                           "no unkeyed randomness in icikit/serve/")


if __name__ == "__main__":
    raise SystemExit(main())
