"""Lint: no unkeyed randomness inside ``icikit/serve/``.

The r12 sampled-serving contract is that EVERY random draw in the
serving path is keyed by the schedule-invariant per-request counter
``fold_in(fold_in(key(0), seed), position)`` — derived in ONE place
(``icikit.models.transformer.decode.request_stream_data`` /
``fold_streams``/``fold_positions``) and threaded through as data.
Any other randomness inside ``icikit/serve/`` (a ``np.random`` call, a
time-seeded key, a bare ``PRNGKey(0)``/``jax.random.key(...)`` minted
at a sample site) would silently re-tie sampled tokens to engine
state — batch slot, step count, wall clock — and break both the
engine ≡ ``sample_generate`` identity pin and bitwise reissue after a
lease reap. This lint makes that a CI failure instead of a review
hope (wired into ``make check``).

Run: ``python tools/serve_key_lint.py`` — exits nonzero with the
offending lines on a hit.
"""

from __future__ import annotations

import os
import re
import sys

SERVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "icikit", "serve")

# pattern -> why it is banned in icikit/serve/
BANNED = [
    (re.compile(r"np\.random|numpy\.random"),
     "np.random draws are unkeyed — route randomness through the "
     "request's counter stream (decode.request_stream_data)"),
    (re.compile(r"\bPRNGKey\s*\("),
     "bare PRNGKey at a sample site — streams must come from the "
     "per-request seed (decode.request_stream_data)"),
    (re.compile(r"jax\.random\.key\s*\(|random\.key\s*\("),
     "key construction inside icikit/serve — the ONE stream "
     "derivation lives in decode.request_stream_data"),
    (re.compile(r"\brandom\.seed\s*\(|\bdefault_rng\s*\("),
     "host RNG seeding in the serving path"),
    (re.compile(r"key\s*\(\s*int\s*\(\s*time|seed\s*=\s*time\."),
     "time-seeded keys are schedule-dependent by construction"),
]


def main() -> int:
    bad = []
    for root, _, files in os.walk(SERVE_DIR):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            with open(path) as f:
                for ln, line in enumerate(f, 1):
                    stripped = line.split("#", 1)[0]
                    for pat, why in BANNED:
                        if pat.search(stripped):
                            rel = os.path.relpath(path, SERVE_DIR)
                            bad.append(
                                f"icikit/serve/{rel}:{ln}: "
                                f"{line.strip()}\n    -> {why}")
    if bad:
        print("unkeyed randomness inside icikit/serve/ — every draw "
              "must ride the per-request counter streams:")
        print("\n".join(bad))
        return 1
    print("serve-key-lint OK: no unkeyed randomness in icikit/serve/")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
