#!/usr/bin/env python
"""Fleet round-17 study: multi-engine scaling rows + the p−1 soak.

Two campaigns, both appending to ``serve_fleet_r17.jsonl``:

1. **Scaling** (``--scaling``): tokens/s + TTFT at 1/2/4 engines on the
   Poisson and shared-prefix workloads (2 seeds each, every arm
   ``--verify-identity``-audited), plus disaggregated prefill/decode
   arms at 2/4 engines so the records carry measured handoff +
   migration counts. CPU protocol note: the engine processes share
   this host's physical cores, so the scaling ratio is a LOWER bound
   on separate-host scaling — the identity audit and the
   coordination-overhead shape are the portable claims; the TPU/
   multi-host session re-prices absolutes (ROADMAP item 5 ledger).

2. **Soak** (``--soak``): the cross-process ``make chaos`` analogue.
   Four engines (one dedicated prefill, three full) serve a mixed
   greedy+sampled trace while: two engines are killed mid-decode
   (``die:fleet.engine.die`` fires inside lease renewal), and one is
   made DEFECTIVE (``corrupt:serve.kv.page`` under
   ``integrity="pages"`` — its completions fail the sealed-page
   re-verify, so the coordinator quarantines it and reissues its
   work). Exit bar: with p−1 engines unavailable, EVERY request
   completes and every completed request's tokens are bitwise
   identical to single-request ``generate``/``sample_generate`` —
   counter keys carry no engine state, so this must hold — with at
   least one cross-engine KV migration and the quarantine drill
   observed in the run.

Reproduce::

    python tools/fleet_study.py --scaling --soak \\
        --json serve_fleet_r17.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from icikit.bench.fleet import (  # noqa: E402
    _collect_worker_stats,
    _verify_identity,
    _wait,
    run_fleet,
    spawn_worker,
)

WORKLOADS = {
    # name -> (prefix_len of the 16-token prompt)
    "poisson": 0,
    "shared_prefix": 12,
}


def scaling(json_path: str, seeds=(0, 1), engine_counts=(1, 2, 4),
            requests: int = 64, rate: float = 400.0) -> list:
    """Saturating offered load (the whole trace arrives inside the
    first ~160 ms): makespan is compute-bound, so tokens/s tracks the
    fleet's capacity and TTFT tracks queueing relief — at
    arrival-limited rates every engine count trivially matches the
    offered rate and the row measures nothing (the first cut of this
    study did exactly that; kept as the protocol note)."""
    recs = []
    for name, prefix in WORKLOADS.items():
        for n in engine_counts:
            for seed in seeds:
                rec = run_fleet(
                    n, requests, rate, 16, 8, 16, roles="both",
                    prefix_len=prefix, seed=seed, verify=True,
                    timeout_s=900.0)
                rec["workload"] = name
                recs.append(rec)
                _flush(json_path, rec)
                assert rec["identity_ok"] and not rec["failed"], rec
        # the DistServe split, measured at the same load
        for n in (2, 4):
            if n not in engine_counts:
                continue
            rec = run_fleet(
                n, requests, rate, 16, 8, 16, roles="disagg",
                prefix_len=prefix, seed=seeds[0], verify=True,
                timeout_s=900.0)
            rec["workload"] = name
            recs.append(rec)
            _flush(json_path, rec)
            assert rec["identity_ok"] and not rec["failed"], rec
            assert rec["handoffs"] > 0
            assert rec["bridge"]["migrations"] > 0
    return recs


def soak(json_path: str | None = None, n_requests: int = 14,
         seed: int = 0, lease_s: float = 3.0,
         die_at=(8, 16), timeout_s: float = 900.0) -> dict:
    """The p−1-engines-survive soak; returns the soak record (and
    raises on any violated bar). Fleet: pre0 (prefill, killed),
    both1 (killed), bad2 (defective -> quarantined), both3
    (survivor)."""
    from icikit.fleet.coordinator import Coordinator
    from icikit.fleet.worker import build_model

    prompt_len, new_min, new_max = 12, 5, 9
    horizon = prompt_len + 1 + new_max
    model_spec = {"preset": "tiny",
                  "overrides": {"max_seq": max(64, horizon)},
                  "compute_dtype": "float32", "dp": 1, "tp": 1,
                  "init_seed": 0}
    per_row = -(-horizon // 4)
    serve_kw = dict(max_rows=2, block_size=4,
                    n_blocks=per_row * 2 + per_row,
                    max_prompt=prompt_len + 1, max_new=new_max,
                    prefill_chunk=16, integrity="pages")
    model = build_model(model_spec)
    _, _, cfg = model
    rng = np.random.default_rng(seed)
    workload = []
    for i in range(n_requests):
        p = rng.integers(0, cfg.vocab, (prompt_len,)).astype(np.int32)
        n = int(rng.integers(new_min, new_max + 1))
        workload.append((p, n, i))
    tmpdir = tempfile.mkdtemp(prefix="icikit_fleet_soak_")
    coord = Coordinator(os.path.join(tmpdir, "bridge"),
                        lease_s=lease_s, reap_interval_s=0.1,
                        heartbeat_timeout_s=5.0)
    fleet = [
        ("pre0", "prefill",
         {"ICIKIT_CHAOS": f"seed=1;die:fleet.engine.die=@{die_at[0]}"}),
        ("both1", "both",
         {"ICIKIT_CHAOS": f"seed=2;die:fleet.engine.die=@{die_at[1]}"}),
        ("bad2", "both",
         {"ICIKIT_CHAOS": "seed=3;corrupt:serve.kv.page=@1"}),
        ("both3", "both", None),
    ]
    procs = []
    try:
        for eid, role, env in fleet:
            procs.append(spawn_worker(coord.addr, eid, role,
                                      model_spec, serve_kw, tmpdir,
                                      env_extra=env))
        deadline = time.monotonic() + timeout_s
        while len(coord.engines()) < len(fleet):
            if time.monotonic() > deadline:
                raise TimeoutError("workers never registered")
            time.sleep(0.05)
        t0 = time.monotonic()
        rids = []
        for i, (p, n, rs) in enumerate(workload):
            # mixed traffic: even arrivals greedy, odd sampled — the
            # bar covers generate AND sample_generate
            temp = 0.0 if i % 2 == 0 else 0.7
            rids.append(coord.submit(
                p, n, not_before=t0 + i * 0.05, seed=rs,
                temperature=temp, top_p=0.9 if temp else 1.0))
        _wait(coord, procs, timeout_s, require=1)
        makespan = time.monotonic() - t0
        for p in procs:
            if p.poll() is None:
                p.wait(timeout=60)
    finally:
        coord.shutdown()
        for p in procs:
            if p.poll() is None:
                p.kill()
    workers = _collect_worker_stats(procs)
    greedy = [(rid, w) for i, (rid, w) in enumerate(zip(
        rids, [(0.0, p, n, rs) for p, n, rs in workload]))
        if i % 2 == 0]
    sampled = [(rid, w) for i, (rid, w) in enumerate(zip(
        rids, [(0.0, p, n, rs) for p, n, rs in workload]))
        if i % 2 == 1]
    audit_g = _verify_identity(
        model, coord.queue.request, [r for r, _ in greedy],
        [w for _, w in greedy], 0.0, 0, 1.0)
    audit_s = _verify_identity(
        model, coord.queue.request, [r for r, _ in sampled],
        [w for _, w in sampled], 0.7, 0, 0.9)
    reg = coord.engines()
    rec = {
        "kind": "serve_fleet_soak",
        "n_engines": len(fleet),
        "n_requests": n_requests,
        "lease_s": lease_s,
        "makespan_s": round(makespan, 3),
        "completed": sum(coord.queue.request(r).state == "done"
                         for r in rids),
        "reissues": coord.queue.n_reissues,
        "duplicate_commits": coord.queue.n_duplicate_commits,
        "handoffs": coord.n_handoffs,
        "bridge": coord.bridge.stats(),
        "killed": [w["returncode"] != 0 for w in workers],
        "engine_states": {eid: reg[eid]["state"] for eid in reg},
        "identity_greedy": audit_g,
        "identity_sampled": audit_s,
        "note": "cross-process make-chaos analogue: 2 kills + 1 "
                "defective quarantine, p-1 unavailable, survivor "
                "completes everything bitwise",
    }
    # the soak's bars, enforced loudly
    assert rec["completed"] == n_requests, rec
    assert audit_g["identity_ok"] and audit_s["identity_ok"], rec
    assert audit_g["identity_checked"] + audit_s["identity_checked"] \
        == n_requests
    assert workers[0]["returncode"] != 0, "pre0 was not killed"
    assert workers[1]["returncode"] != 0, "both1 was not killed"
    assert rec["engine_states"]["bad2"] == "quarantined", rec
    assert rec["reissues"] >= 1, rec
    assert rec["bridge"]["migrations"] >= 1, rec
    if json_path:
        _flush(json_path, rec)
    return rec


def _flush(path: str | None, rec: dict) -> None:
    if path:
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    print(json.dumps({k: rec[k] for k in
                      ("kind", "makespan_s", "completed")
                      if k in rec}
                     | {"n_engines": rec.get("n_engines"),
                        "tokens_per_s": rec.get("tokens_per_s"),
                        "workload": rec.get("workload"),
                        "roles": rec.get("roles")}))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scaling", action="store_true")
    ap.add_argument("--soak", action="store_true")
    ap.add_argument("--json", dest="json_path",
                    default="serve_fleet_r17.jsonl")
    ap.add_argument("--seeds", type=int, nargs="+", default=[0, 1])
    ap.add_argument("--engines", type=int, nargs="+",
                    default=[1, 2, 4])
    args = ap.parse_args(argv)
    if not (args.scaling or args.soak):
        ap.error("pick at least one of --scaling / --soak")
    if args.scaling:
        scaling(args.json_path, seeds=tuple(args.seeds),
                engine_counts=tuple(args.engines))
    if args.soak:
        rec = soak(args.json_path)
        print("SOAK_OK", json.dumps(rec["engine_states"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
