"""Ngram-drafter acceptance on a REAL (non-synthetic) text stream —
the ROADMAP 3b precondition row for the ``drafter="auto"`` fallback
flip.

The r9 serving rows measured the zero-cost n-gram drafter only on a
synthetic repetitive stream (+6–23% tokens/s), and the defaults-audit
rule kept it opt-in until a real-text acceptance row exists. This
study supplies that row without needing a download: the repo's own
documentation (README/DECODE/docs/*.md — genuine English prose, tens
of KB) is the corpus, byte-level:

1. train a byte-level toy LM on document windows (the model whose
   greedy continuations the drafter must match);
2. run ``speculative_generate(drafter="ngram")`` from held-out prompt
   windows and read the measured acceptance telemetry;
3. the shared-drafter baseline runs on the same prompts for contrast
   (it pays truncated-depth forward passes per proposal; the n-gram
   drafter pays nothing, so ANY acceptance above the window overhead
   is profit — the engine's r9 +tokens/s rows are the priced form).

Rows: ``kind="acceptance"`` with ``drafter="ngram"``,
``corpus="repo-docs-bytes"`` — the same record shape the cost model's
``--alpha-from`` consumes.

Usage::

    JAX_PLATFORMS=cpu python tools/ngram_stream_study.py \
        --json decode_spec_r10.jsonl [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS_FILES = ("README.md", "DECODE.md", "SCALING.md", "MOE.md",
                "PIPELINE.md", "docs/DESIGN.md", "docs/SERVING.md",
                "docs/API.md")
TOY = dict(vocab=256, d_model=64, n_heads=2, d_head=32, d_ff=256,
           n_layers=4, max_seq=256, compute_dtype="float32")


def load_corpus() -> np.ndarray:
    parts = []
    for rel in CORPUS_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            with open(path, "rb") as f:
                parts.append(np.frombuffer(f.read(), np.uint8))
    if not parts:
        raise FileNotFoundError("no corpus docs found")
    return np.concatenate(parts).astype(np.int32)


def train_byte_lm(corpus: np.ndarray, steps: int):
    import jax
    import jax.numpy as jnp
    import optax

    from icikit.models.transformer import TransformerConfig, init_params
    from icikit.models.transformer.model import (make_model_mesh,
                                                 make_train_step)

    cfg = TransformerConfig(**TOY)
    mesh = make_model_mesh(dp=1, tp=1, sp=1)
    params = init_params(jax.random.key(0), cfg, mesh)
    _, step = make_train_step(mesh, cfg, optax.adam(3e-3))
    st = optax.adam(3e-3).init(params)
    rng = np.random.default_rng(0)
    # hold out the final 10% of the byte stream for prompt windows
    split = int(len(corpus) * 0.9)
    train_bytes, held = corpus[:split], corpus[split:]
    loss = None
    for s in range(steps):
        starts = rng.integers(0, split - 129, size=16)
        chunk = np.stack([train_bytes[i:i + 129] for i in starts])
        params, st, loss = step(params, st,
                                jnp.asarray(chunk[:, :-1]),
                                jnp.asarray(chunk[:, 1:]))
    final = float(np.asarray(loss))
    print(f"byte LM trained: {steps} steps on {split} bytes, "
          f"loss {final:.4f} ({final / np.log(2):.2f} bits/byte)",
          flush=True)
    return cfg, mesh, params, held


def acceptance_rows(quick: bool) -> list:
    import jax.numpy as jnp

    from icikit.models.transformer import speculative_generate

    steps = 150 if quick else 2500
    corpus = load_corpus()
    cfg, mesh, params, held = train_byte_lm(corpus, steps)
    rng = np.random.default_rng(1)
    b, s_prompt, n_new = 8, 64, (32 if quick else 128)
    starts = rng.integers(0, len(held) - s_prompt, size=b)
    prompts = jnp.asarray(np.stack([held[i:i + s_prompt]
                                    for i in starts]), jnp.int32)
    rows = []
    for drafter, ks in (("ngram", (2, 3, 4, 8)), ("shared", (2, 4))):
        for k in ks:
            _, st = speculative_generate(
                params, prompts, mesh, cfg, n_new, k=k,
                draft_layers=1, drafter=drafter, ngram_n=3,
                return_stats=True)
            rows.append({
                "kind": "acceptance", "batch": b, "k": k,
                "draft_layers": 1, "n_layers": cfg.n_layers,
                "drafter": drafter, "corpus": "repo-docs-bytes",
                "corpus_bytes": int(len(corpus)),
                "train_steps": steps,
                "s_prompt": s_prompt, "n_new": n_new,
                "acceptance_rate": round(st["acceptance_rate"], 4),
                "tokens_per_step": round(st["tokens_per_step"], 4),
                "verify_steps": st["verify_steps"],
            })
            print(f"{drafter} k={k}: acceptance "
                  f"{st['acceptance_rate']:.4f}, tokens/step "
                  f"{st['tokens_per_step']:.4f}", flush=True)
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", dest="json_path",
                    default="decode_spec_r10.jsonl")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args(argv)
    rows = acceptance_rows(args.quick)
    with open(args.json_path, "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    print(f"{len(rows)} rows appended to {args.json_path}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
